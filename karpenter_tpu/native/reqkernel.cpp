// Requirements-intersection kernel: the host scheduler's hottest check.
//
// Reference semantics: pkg/scheduling/requirement.go:220-254 HasIntersection
// and requirements.go:252-286 Intersects — mirrored exactly from the Python
// algebra in karpenter_tpu/scheduling/requirements.py (a Requirement is a
// value-id set + complement flag + inclusive integer bounds; two negative
// requirements on a shared key never conflict).
//
// The FFD host path calls Requirements.intersects per (pod, instance type)
// inside filter_instance_types (nodeclaim.go:541-640) — tens of thousands of
// calls per solve. This kernel holds the interned instance-type requirement
// table once per solve and answers "which rows intersect this query" in one
// C call. Built at import time with g++ (see native/__init__.py); the Python
// path remains the fallback and the parity oracle.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t NO_BOUND = INT64_MIN;

struct Value {
    int32_t id;
    int64_t num;      // integer value when has_num
    uint8_t has_num;  // value parses as an integer (for bounds checks)
};

struct Req {
    int32_t key;
    uint8_t complement;
    int64_t gte;  // NO_BOUND = absent
    int64_t lte;
    std::vector<Value> values;  // sorted by id
};

struct Table {
    std::vector<std::vector<Req>> rows;  // each row sorted by key
};

bool contains(const std::vector<Value>& vs, int32_t id) {
    size_t lo = 0, hi = vs.size();
    while (lo < hi) {
        size_t mid = (lo + hi) / 2;
        if (vs[mid].id < id)
            lo = mid + 1;
        else
            hi = mid;
    }
    return lo < vs.size() && vs[lo].id == id;
}

bool within(const Value& v, int64_t gte, int64_t lte) {
    if (gte == NO_BOUND && lte == NO_BOUND) return true;
    if (!v.has_num) return false;
    if (gte != NO_BOUND && v.num < gte) return false;
    if (lte != NO_BOUND && v.num > lte) return false;
    return true;
}

// requirement.go:220-254 / requirements.py has_intersection
bool has_intersection(const Req& a, const Req& b) {
    int64_t gte = a.gte;
    if (b.gte != NO_BOUND && (gte == NO_BOUND || b.gte > gte)) gte = b.gte;
    int64_t lte = a.lte;
    if (b.lte != NO_BOUND && (lte == NO_BOUND || b.lte < lte)) lte = b.lte;
    if (gte != NO_BOUND && lte != NO_BOUND && gte > lte) return false;
    if (a.complement && b.complement) return true;
    if (a.complement && !b.complement) {
        for (const auto& v : b.values)
            if (!contains(a.values, v.id) && within(v, gte, lte)) return true;
        return false;
    }
    if (!a.complement && b.complement) {
        for (const auto& v : a.values)
            if (!contains(b.values, v.id) && within(v, gte, lte)) return true;
        return false;
    }
    for (const auto& v : a.values)
        if (contains(b.values, v.id) && within(v, gte, lte)) return true;
    return false;
}

// operator() in (NotIn, DoesNotExist) — requirements.py:164-167
bool is_negative(const Req& r) {
    return r.complement ? !r.values.empty() : r.values.empty();
}

}  // namespace

extern "C" {

void* rk_new() { return new Table(); }

void rk_free(void* h) { delete static_cast<Table*>(h); }

int32_t rk_add_row(void* h) {
    auto* t = static_cast<Table*>(h);
    t->rows.emplace_back();
    return static_cast<int32_t>(t->rows.size()) - 1;
}

// Append one requirement to a row. Rows must receive keys in ascending order
// (the Python side sorts). value_ids sorted ascending; nums/has_num parallel.
void rk_row_add_req(void* h, int32_t row, int32_t key, uint8_t complement, int64_t gte, int64_t lte,
                    const int32_t* value_ids, const int64_t* nums, const uint8_t* has_num, int32_t n) {
    auto* t = static_cast<Table*>(h);
    Req r;
    r.key = key;
    r.complement = complement;
    r.gte = gte;
    r.lte = lte;
    r.values.reserve(n);
    for (int32_t i = 0; i < n; i++) r.values.push_back(Value{value_ids[i], nums[i], has_num[i]});
    t->rows[row].push_back(std::move(r));
}

// Query: flattened requirement array (sorted by key) with a shared value pool.
// out[row] = 1 iff every shared key has a non-empty intersection (with the
// two-negatives exception) — requirements.go Intersects == nil.
void rk_filter(void* h, const int32_t* q_keys, const uint8_t* q_comp, const int64_t* q_gte, const int64_t* q_lte,
               const int32_t* q_val_off, const int32_t* q_val_len, int32_t nq, const int32_t* pool_ids,
               const int64_t* pool_nums, const uint8_t* pool_has_num, uint8_t* out) {
    auto* t = static_cast<Table*>(h);
    std::vector<Req> query(nq);
    for (int32_t i = 0; i < nq; i++) {
        Req& r = query[i];
        r.key = q_keys[i];
        r.complement = q_comp[i];
        r.gte = q_gte[i];
        r.lte = q_lte[i];
        int32_t off = q_val_off[i], len = q_val_len[i];
        r.values.reserve(len);
        for (int32_t j = 0; j < len; j++)
            r.values.push_back(Value{pool_ids[off + j], pool_nums[off + j], pool_has_num[off + j]});
    }
    for (size_t row = 0; row < t->rows.size(); row++) {
        const auto& reqs = t->rows[row];
        bool ok = true;
        size_t i = 0, j = 0;  // merge-join on sorted keys
        while (i < reqs.size() && j < query.size()) {
            if (reqs[i].key < query[j].key) {
                i++;
            } else if (reqs[i].key > query[j].key) {
                j++;
            } else {
                if (!has_intersection(reqs[i], query[j]) &&
                    !(is_negative(reqs[i]) && is_negative(query[j]))) {
                    ok = false;
                    break;
                }
                i++;
                j++;
            }
        }
        out[row] = ok ? 1 : 0;
    }
}

}  // extern "C"
