"""Native (C++) runtime kernels with build-on-import and Python fallback.

The TPU owns the solve; the host control plane's hottest pure-Python loop is
the per-(pod x instance-type) Requirements.intersects check inside
filter_instance_types. `reqkernel.cpp` evaluates it over the whole
instance-type table in one C call. The shared library is compiled with g++ on
first import (cached by source hash next to the package); any failure —
missing compiler, readonly filesystem — degrades to the Python algebra, which
remains the semantics oracle (tests/test_native.py fuzzes parity).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "reqkernel.cpp")

_lib = None
_load_error: str | None = None

NO_BOUND = -(2**63)


def _build() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.environ.get("KARPENTER_NATIVE_CACHE") or os.path.join(tempfile.gettempdir(), "karpenter_tpu_native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"reqkernel-{digest}.so")
    if not os.path.exists(so_path):
        tmp = f"{so_path}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, so_path)
    return so_path


def _load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    if os.environ.get("KARPENTER_DISABLE_NATIVE"):
        _load_error = "disabled via KARPENTER_DISABLE_NATIVE"
        return None
    try:
        lib = ctypes.CDLL(_build())
    except Exception as e:  # solverlint: ok(swallowed-exception): failure recorded in _load_error and surfaced by load_error() — the python fallback path takes over
        _load_error = f"{type(e).__name__}: {e}"
        return None
    lib.rk_new.restype = ctypes.c_void_p
    lib.rk_free.argtypes = [ctypes.c_void_p]
    lib.rk_add_row.argtypes = [ctypes.c_void_p]
    lib.rk_add_row.restype = ctypes.c_int32
    lib.rk_row_add_req.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint8, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
    ]
    lib.rk_filter.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> str | None:
    _load()
    return _load_error


I64_MIN, I64_MAX = -(2**63) + 1, 2**63 - 1  # NO_BOUND reserves -(2**63)


class UnsupportedRequirements(Exception):
    """A value or bound exceeds int64 — the kernel would silently wrap, so
    the caller must stay on the arbitrary-precision Python algebra."""


def _num(value: str):
    try:
        n = int(value)
    except (TypeError, ValueError):
        return 0, 0
    if not (I64_MIN <= n <= I64_MAX):
        raise UnsupportedRequirements(f"integer value {value} exceeds int64")
    return n, 1


def _bound(b):
    if b is None:
        return NO_BOUND
    if not (I64_MIN <= b <= I64_MAX):
        raise UnsupportedRequirements(f"bound {b} exceeds int64")
    return b


class ReqTable:
    """An interned table of Requirements rows + one-call intersect filter."""

    def __init__(self, rows):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native kernel unavailable: {_load_error}")
        self._lib = lib
        self._keys: dict[str, int] = {}
        self._vals: dict[tuple[str, str], int] = {}
        self._handle = ctypes.c_void_p(lib.rk_new())
        self.n_rows = len(rows)
        for reqs in rows:
            row = lib.rk_add_row(self._handle)
            for key, r in sorted(reqs.items(), key=lambda kv: self._key_id(kv[0])):
                ids, nums, has = self._lower_values(key, r.values)
                lib.rk_row_add_req(
                    self._handle, row, self._key_id(key), 1 if r.complement else 0,
                    _bound(r.gte), _bound(r.lte),
                    ids, nums, has, len(r.values),
                )

    def _key_id(self, key: str) -> int:
        kid = self._keys.get(key)
        if kid is None:
            kid = len(self._keys)
            self._keys[key] = kid
        return kid

    def _lower_values(self, key: str, values):
        entries = []
        for v in values:
            vid = self._vals.get((key, v))
            if vid is None:
                vid = len(self._vals)
                self._vals[(key, v)] = vid
            n, h = _num(v)
            entries.append((vid, n, h))
        entries.sort()
        ids = (ctypes.c_int32 * len(entries))(*[e[0] for e in entries])
        nums = (ctypes.c_int64 * len(entries))(*[e[1] for e in entries])
        has = (ctypes.c_uint8 * len(entries))(*[e[2] for e in entries])
        return ids, nums, has

    def filter(self, query) -> bytes:
        """out[row] == 1 iff rows[row].intersects(query) is None."""
        items = sorted(query.items(), key=lambda kv: self._key_id(kv[0]))
        nq = len(items)
        keys = (ctypes.c_int32 * nq)()
        comp = (ctypes.c_uint8 * nq)()
        gte = (ctypes.c_int64 * nq)()
        lte = (ctypes.c_int64 * nq)()
        off = (ctypes.c_int32 * nq)()
        vlen = (ctypes.c_int32 * nq)()
        pool: list[tuple[int, int, int]] = []
        for i, (key, r) in enumerate(items):
            keys[i] = self._key_id(key)
            comp[i] = 1 if r.complement else 0
            gte[i] = _bound(r.gte)
            lte[i] = _bound(r.lte)
            off[i] = len(pool)
            entries = []
            for v in r.values:
                vid = self._vals.get((key, v))
                if vid is None:
                    vid = len(self._vals)
                    self._vals[(key, v)] = vid
                n, h = _num(v)
                entries.append((vid, n, h))
            entries.sort()
            pool.extend(entries)
            vlen[i] = len(entries)
        np_ = len(pool)
        pool_ids = (ctypes.c_int32 * max(np_, 1))(*[e[0] for e in pool])
        pool_nums = (ctypes.c_int64 * max(np_, 1))(*[e[1] for e in pool])
        pool_has = (ctypes.c_uint8 * max(np_, 1))(*[e[2] for e in pool])
        out = (ctypes.c_uint8 * max(self.n_rows, 1))()
        self._lib.rk_filter(self._handle, keys, comp, gte, lte, off, vlen, nq, pool_ids, pool_nums, pool_has, out)
        return bytes(out[: self.n_rows])

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.rk_free(self._handle)
        except Exception:  # solverlint: ok(swallowed-exception): interpreter-teardown __del__ — the ctypes lib may already be unloaded and raising would print to stderr mid-shutdown
            pass
