"""Typed, lightweight equivalents of the corev1 objects the framework consumes.

These are plain dataclasses — not a port of client-go — carrying exactly the
fields the reference's controllers read (pod scheduling constraints, node
capacity/taints, metadata with finalizers/owner-refs). Everything else is
intentionally absent.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ..scheduling.taints import Taint
from ..utils.quantity import Quantity


def new_uid() -> str:
    return f"{uuid.uuid4()}"


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    api_version: str = "v1"
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[OwnerReference] = field(default_factory=list)
    resource_version: int = 0
    generation: int = 1
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None


@dataclass
class Container:
    name: str = "main"
    # resources: {"requests": {res: Quantity}, "limits": {res: Quantity}}
    resources: dict[str, dict[str, Quantity]] = field(default_factory=dict)
    ports: list[dict] = field(default_factory=list)  # {containerPort, hostPort?, hostIP?, protocol?}
    # For init containers: restart_policy == "Always" marks a sidecar (KEP-753).
    restart_policy: str | None = None

    def is_sidecar(self) -> bool:
        return self.restart_policy == "Always"


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: list[dict] = field(default_factory=list)  # [{key, operator, values}]


@dataclass
class NodeAffinity:
    # required: list of OR'd terms; each term is a list of AND'd {key, operator, values}
    required: list[list[dict]] = field(default_factory=list)
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: dict | None = None  # {"matchLabels": {...}, "matchExpressions": [...]}
    topology_key: str = ""
    namespaces: list[str] = field(default_factory=list)
    namespace_selector: dict | None = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: dict | None = None
    min_domains: int | None = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"  # Honor | Ignore
    # pod label keys whose VALUES merge into the selector (k8s >= 1.27
    # matchLabelKeys; topology.go:467-475) — e.g. pod-template-hash for
    # per-revision spread
    match_label_keys: list[str] = field(default_factory=list)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=lambda: [Container()])
    init_containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Affinity | None = None
    tolerations: list[Any] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    node_name: str = ""
    priority: int | None = None
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"
    scheduler_name: str = "default-scheduler"
    overhead: dict[str, Quantity] = field(default_factory=dict)
    volumes: list[dict] = field(default_factory=list)
    termination_grace_period_seconds: int | None = 30
    restart_policy: str = "Always"
    host_network: bool = False
    resource_claims: list[dict] = field(default_factory=list)  # DRA: [{name, resourceClaimName | resourceClaimTemplateName}]


@dataclass
class PodCondition:
    type: str
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = "Pending"
    conditions: list[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    start_time: Optional[float] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class NodeSpec:
    provider_id: str = ""
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeCondition:
    type: str
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class NodeStatus:
    capacity: dict[str, Quantity] = field(default_factory=dict)
    allocatable: dict[str, Quantity] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    node_info: dict[str, str] = field(default_factory=dict)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"

    def key(self) -> str:
        return self.metadata.name


@dataclass
class DaemonSet:
    """Minimal DaemonSet: the scheduler precomputes per-node daemon overhead
    from its pod template."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template_spec: PodSpec = field(default_factory=PodSpec)
    template_metadata: ObjectMeta = field(default_factory=ObjectMeta)
    kind: str = "DaemonSet"

    def to_pod(self) -> "Pod":
        import copy as _copy

        pod = Pod(spec=_copy.deepcopy(self.template_spec))
        pod.metadata.namespace = self.metadata.namespace
        pod.metadata.name = f"{self.metadata.name}-daemon"
        pod.metadata.labels = dict(self.template_metadata.labels)
        pod.metadata.owner_references = [
            OwnerReference(kind="DaemonSet", name=self.metadata.name, uid=self.metadata.uid, controller=True)
        ]
        return pod


@dataclass
class PodTemplate:
    """The pod-shape object a CapacityBuffer's podTemplateRef points at."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template_spec: PodSpec = field(default_factory=PodSpec)
    template_metadata: ObjectMeta = field(default_factory=ObjectMeta)
    kind: str = "PodTemplate"


@dataclass
class Deployment:
    """Minimal scalable workload: replicas + a pod template. Stands in for
    Deployment/ReplicaSet/StatefulSet as a CapacityBuffer scalableRef target."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    template_spec: PodSpec = field(default_factory=PodSpec)
    template_metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict | None = None
    kind: str = "Deployment"


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # spec
    volume_name: str = ""  # bound PV name ("" = unbound)
    storage_class_name: str | None = None  # None = default class; "" = disabled
    # status
    phase: str = "Pending"  # Pending | Bound | Lost
    kind: str = "PersistentVolumeClaim"

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    csi_driver: str = ""  # spec.csi.driver ("" = non-CSI)
    # legacy in-tree volume source plugin name (e.g. "kubernetes.io/aws-ebs"
    # for spec.awsElasticBlockStore); CSI-migrated for limit tracking
    # (volumeusage.go:169-181 driverFromVolume)
    in_tree_source: str = ""
    # spec.nodeAffinity.required.nodeSelectorTerms: OR'd terms, each a list of
    # AND'd {key, operator, values} dicts
    node_affinity_required: list[list[dict]] = field(default_factory=list)
    local: bool = False  # spec.local set
    host_path: bool = False  # spec.hostPath set
    kind: str = "PersistentVolume"


@dataclass
class VolumeAttachment:
    """storagev1.VolumeAttachment: a volume attached to a node. Termination
    waits for these to detach before deleting the instance
    (node/termination/controller.go awaitVolumeDetachment)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    attacher: str = ""  # spec.attacher (CSI driver)
    node_name: str = ""  # spec.nodeName
    persistent_volume_name: str = ""  # spec.source.persistentVolumeName
    attached: bool = True  # status.attached
    kind: str = "VolumeAttachment"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = "Immediate"  # Immediate | WaitForFirstConsumer
    # AllowedTopologies: OR'd TopologySelectorTerms, each a list of AND'd
    # {key, values} matchLabelExpressions
    allowed_topologies: list[list[dict]] = field(default_factory=list)
    kind: str = "StorageClass"


@dataclass
class CSINodeDriver:
    name: str = ""
    allocatable_count: int | None = None  # max volumes this driver can attach


@dataclass
class CSINode:
    """Named after the node it describes; carries per-driver volume limits."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: list[CSINodeDriver] = field(default_factory=list)
    kind: str = "CSINode"


@dataclass
class Device:
    """One allocatable device in a ResourceSlice (resourcev1.Device)."""

    name: str = ""
    # qualified attribute name ("driver/attr" or plain) -> str | int | bool
    attributes: dict[str, Any] = field(default_factory=dict)
    capacity: dict[str, Quantity] = field(default_factory=dict)
    # DRA driver name; on slice-published devices the ResourceSlice's driver
    # wins, but instance-type template devices (cloudprovider
    # dynamicresources.go:41-44 ResourceSliceTemplate.Driver) declare theirs
    # here so CEL `device.driver` selectors see it pre-launch
    driver: str = ""
    # multi-allocatable (consumable-capacity) devices can serve several claims
    # until their capacity is exhausted
    allow_multiple_allocations: bool = False
    # partitionable devices (resourcev1 Device.ConsumesCounters): allocating
    # this device draws from its pool's shared counter sets — e.g. MIG
    # partitions consuming slices of one physical GPU's memory/SM budget
    # [{"counterSet": str, "counters": {name: Quantity|str}}]
    consumes_counters: list[dict] = field(default_factory=list)
    # node requirements selecting this device pins (template devices only):
    # the topology the launched node must satisfy when the device is chosen —
    # feeds per-instance-type requirement superposition
    # (allocator.go:90-134 ContributedRequirements)
    # [{"key", "operator", "values"}]
    requirements: list[dict] = field(default_factory=list)


@dataclass
class ResourceSlice:
    """A driver's published pool chunk of devices on a node (resourcev1)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    driver: str = ""
    pool_name: str = ""
    pool_generation: int = 1
    node_name: str = ""  # "" + all_nodes=False means selector-scoped
    all_nodes: bool = False
    node_selector: list[list[dict]] = field(default_factory=list)  # OR'd terms
    devices: list[Device] = field(default_factory=list)
    # pool-level shared counter budgets (resourcev1 CounterSet): devices in
    # this pool draw from these via consumes_counters
    # [{"name": str, "counters": {counter name: Quantity|str}}]
    shared_counters: list[dict] = field(default_factory=list)
    kind: str = "ResourceSlice"


@dataclass
class DeviceClass:
    """Selector bundle a claim request references by class name."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selectors: list[dict] = field(default_factory=list)
    kind: str = "DeviceClass"


@dataclass
class ResourceClaimStatus:
    # {"devices": [{request, driver, pool, device, consumedCapacity?}],
    #  "nodeName": str} once allocated
    allocation: Optional[dict] = None
    reserved_for: list[str] = field(default_factory=list)  # pod uids


@dataclass
class ResourceClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # requests: [{name, deviceClassName?, selectors?, count?, allocationMode?,
    #             capacity?}]
    requests: list[dict] = field(default_factory=list)
    # constraints: [{"matchAttribute": "driver/attr", "requests": [names]?}]
    constraints: list[dict] = field(default_factory=list)
    status: ResourceClaimStatus = field(default_factory=ResourceClaimStatus)
    kind: str = "ResourceClaim"

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class ResourceClaimTemplate:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: list[dict] = field(default_factory=list)
    constraints: list[dict] = field(default_factory=list)
    kind: str = "ResourceClaimTemplate"


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict | None = None  # metav1 label selector
    min_available: int | str | None = None
    max_unavailable: int | str | None = None
    kind: str = "PodDisruptionBudget"


@dataclass
class Lease:
    """coordinationv1.Lease — the leader-election lock object."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    kind: str = "Lease"


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    kind: str = "PriorityClass"


def match_label_selector(selector: dict | None, labels: dict[str, str]) -> bool:
    """metav1.LabelSelector matching: matchLabels AND matchExpressions."""
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op, values = expr["key"], expr["operator"], expr.get("values", [])
        val = labels.get(key)
        if op == "In":
            if val not in values:
                return False
        elif op == "NotIn":
            if val in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
    return True
