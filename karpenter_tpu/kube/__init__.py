"""In-memory Kubernetes substrate: typed objects + an apiserver-like store with
watches, optimistic concurrency, finalizers, and deletion semantics.

The reference's only distributed backend is the kube-apiserver (SURVEY.md L0);
tests there run against envtest (a real local apiserver). Here the same role is
played by `kube.Store` — an in-process object store with resourceVersion
semantics and watch fan-out — so every controller is a real reconciler and the
whole control plane is testable hermetically and deterministically.
"""

from .objects import (  # noqa: F401
    Affinity,
    Container,
    CSINode,
    CSINodeDriver,
    Deployment,
    Device,
    DeviceClass,
    Lease,
    NodeAffinity,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PodStatus,
    PodTemplate,
    PreferredSchedulingTerm,
    ResourceClaim,
    ResourceClaimTemplate,
    ResourceSlice,
    StorageClass,
    VolumeAttachment,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .store import Conflict, NotFound, Store  # noqa: F401
