"""An in-process apiserver substitute: typed object store with optimistic
concurrency, finalizer-gated deletion, and watch fan-out.

Plays the role of the reference's L0 (kube-apiserver/etcd — SURVEY.md layer
map): all durable state lives here; controllers coordinate exclusively through
it. Semantics kept: resourceVersion conflict on stale writes, deletionTimestamp
+ finalizers two-phase delete, watch events (ADDED/MODIFIED/DELETED) delivered
to informers.
"""

from __future__ import annotations

import time

from ..obs.racecheck import make_rlock
from .clone import fast_deepcopy
from typing import Callable, Iterable, Optional


class NotFound(Exception):
    pass


class Conflict(Exception):
    pass


class AlreadyExists(Exception):
    pass


# kinds that are cluster-scoped (key = name, not namespace/name)
CLUSTER_SCOPED = {
    "Node",
    "NodeClaim",
    "NodePool",
    "NodeOverlay",
    "KWOKNodeClass",
    "PriorityClass",
    "StorageClass",
    "PersistentVolume",
    "CSINode",
    "ResourceSlice",
    "DeviceClass",
    "DRAConfig",
}

WatchFn = Callable[[str, object], None]  # (event_type, obj)


def obj_key(obj) -> str:
    meta = obj.metadata
    if obj.kind in CLUSTER_SCOPED:
        return meta.name
    return f"{meta.namespace}/{meta.name}"


class Store:
    """The in-memory 'cluster'. Thread-safe; objects are deep-copied on the
    way in and out so callers can never mutate stored state in place."""

    # the racecheck guarded-field registry (analysis: guarded-field-access;
    # runtime: obs.racecheck.touch). Sanctioned order: `_deliver_lock` may
    # acquire `_lock` (the _drain pop), NEVER the reverse — see the
    # serving-stack lock inventory in karpenter_tpu/serving/__init__.py.
    GUARDED_FIELDS = {
        "_objects": "_lock",
        "_watchers": "_lock",
        "_rv": "_lock",
        "_kind_rv": "_lock",
        "_pending": "_lock",
        "_event_tracer": "_lock",
    }

    def __init__(self, clock=None):
        self._lock = make_rlock("store")
        self._objects: dict[str, dict[str, object]] = {}  # kind -> key -> obj
        self._watchers: dict[str, list[WatchFn]] = {}
        self._rv = 0
        self._clock = clock
        # watch delivery: events are enqueued under self._lock (commit order,
        # stamped with a monotonic commit time) and drained FIFO under
        # self._deliver_lock, so watchers always observe ADDED < MODIFIED <
        # DELETED in resourceVersion order even with concurrent writers.
        self._pending: list[tuple[str, object, float]] = []
        self._deliver_lock = make_rlock("store-deliver")
        # podtrace (obs/podtrace.py): the event-lifecycle tracer's arrival
        # seam — every delivered event is stamped with its commit + delivery
        # monotonic times before the watchers run. None = untraced store.
        self._event_tracer = None
        # per-kind revision: the rv of the last write touching the kind.
        # Caches that depend on one kind's content (e.g. the solver's volume
        # fold on StorageClass/PV/PVC) key on this instead of the global rv,
        # so unrelated writes don't invalidate them.
        self._kind_rv: dict[str, int] = {}

    def kind_revision(self, kind: str) -> int:
        with self._lock:
            return self._kind_rv.get(kind, 0)

    def _now(self) -> float:
        return self._clock.now() if self._clock else 0.0

    # -- watches ---------------------------------------------------------------
    def watch(self, kind: str, fn: WatchFn) -> None:
        """Register a watch callback. Execution context contract: callbacks
        run SYNCHRONOUSLY on whatever thread committed the store write,
        under `_deliver_lock` — so they must be cheap and leaf-locked. This
        is the watch->wake seam the serving stack builds on: informer
        mirrors, the provisioner's batcher trigger, and the fleet
        front-end's push wake (`TenantSession._on_watch_event`, which marks
        the tenant runnable and sets the fleet loop's event) all ride it;
        every registered callback is a reviewed entry in the
        `[tool.solverlint] thread-shared` registry (the thread-escape rule
        enforces that at the call site)."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(fn)

    def unwatch(self, kind: str, fn: WatchFn) -> None:
        """Remove a previously-registered watch (no-op if absent) so
        short-lived observers don't accumulate across a suite."""
        with self._lock:
            fns = self._watchers.get(kind)
            if fns is not None and fn in fns:
                fns.remove(fn)

    def set_event_tracer(self, tracer) -> None:
        """Install (or clear) the podtrace event tracer on the delivery seam."""
        with self._lock:
            self._event_tracer = tracer

    def event_tracer(self):
        with self._lock:
            return self._event_tracer

    def _enqueue(self, event: str, obj) -> None:  # solverlint: ok(guarded-field-access): caller-holds contract — every call site sits inside `with self._lock` (create/update/delete)
        # caller must hold self._lock; the stamp is the event's COMMIT time —
        # podtrace measures queueing delay from commit, not from drain
        self._pending.append((event, obj, time.monotonic()))

    def _drain(self) -> None:
        with self._deliver_lock:
            while True:
                with self._lock:
                    if not self._pending:
                        return
                    event, obj, t_commit = self._pending.pop(0)
                    watchers = list(self._watchers.get(obj.kind, ()))
                    tracer = self._event_tracer
                if tracer is not None and obj.kind == "Pod":
                    # arrival stamp BEFORE the watcher fan-out (and even with
                    # no watchers registered): the tracer only reads scalar
                    # fields off the stored object — the borrow contract.
                    # Kind-gated HERE so non-pod deliveries pay nothing.
                    tracer.on_delivery(event, obj, t_commit, time.monotonic())
                if not watchers:
                    continue
                # ONE clone shared by every watcher: watchers may read and
                # retain it (the stored object is replaced on update, never
                # mutated, and so is this snapshot) but MUST NOT mutate —
                # the same contract as borrow_list. Under churn the
                # per-watcher private clones were the dominant per-event
                # cost (5 pod watchers -> 5 deep clones per arrival).
                c = fast_deepcopy(obj)
                for fn in watchers:
                    fn(event, c)

    # -- CRUD ------------------------------------------------------------------
    def create(self, obj, adopt: bool = False):
        """`adopt=True`: the caller relinquishes `obj` (must not mutate it
        after the call) and accepts the borrow contract on the return value —
        skips both defensive clones. For high-rate producers (the churn
        harness's event driver) where the per-create clone pair dominates."""
        with self._lock:
            kind_map = self._objects.setdefault(obj.kind, {})
            key = obj_key(obj)
            if key in kind_map:
                raise AlreadyExists(f"{obj.kind} {key} already exists")
            self._rv += 1
            if not adopt:
                obj = fast_deepcopy(obj)
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self._now()
            kind_map[key] = obj
            self._enqueue("ADDED", obj)
        self._drain()
        return obj if adopt else fast_deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            obj = self._objects.get(kind, {}).get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            return fast_deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: Optional[str] = None, label_selector: Optional[dict] = None) -> list:
        """label_selector accepts either the flat {key: value} form or the
        metav1 {matchLabels, matchExpressions} form."""
        # cloning outside the lock is safe: stored objects are replaced on
        # update, never mutated in place
        return [fast_deepcopy(o) for o in self.borrow_list(kind, namespace, label_selector)]

    # -- borrowed reads --------------------------------------------------------
    # client-go's shared informer cache hands controllers pointers into the
    # cache with a MUST-NOT-MUTATE contract — that is what makes the
    # reference's read paths cheap. These are the same primitive: the returned
    # objects are the stored ones; callers may only read them, never mutate or
    # retain them across writes. Hot read-only scans (topology domain counting,
    # provisionable-pod filtering, monitors) use these; anything that mutates
    # goes through get/list, which clone.
    def borrow_list(self, kind: str, namespace: Optional[str] = None, label_selector: Optional[dict] = None) -> list:
        with self._lock:
            out = []
            for obj in self._objects.get(kind, {}).values():
                if namespace is not None and obj.kind not in CLUSTER_SCOPED and obj.metadata.namespace != namespace:
                    continue
                if label_selector is not None and not _selector_matches(label_selector, obj.metadata.labels):
                    continue
                out.append(obj)
            return out

    def borrow_get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            return self._objects.get(kind, {}).get(key)

    def update(self, obj, _owned: bool = False):
        """Optimistic-concurrency full update; raises Conflict on stale RV.
        `_owned` (internal, patch()): the object is a patch-private clone the
        caller never sees again — skip the defensive clone-in."""
        with self._lock:
            kind_map = self._objects.setdefault(obj.kind, {})
            key = obj_key(obj)
            current = kind_map.get(key)
            if current is None:
                raise NotFound(f"{obj.kind} {key} not found")
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {key}: resourceVersion {obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            self._rv += 1
            if not _owned:
                obj = fast_deepcopy(obj)
            # deletionTimestamp is set only by delete(); preserve server-side value
            obj.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            # apiserver semantics: generation increments on spec change only
            obj.metadata.generation = current.metadata.generation
            if getattr(obj, "spec", None) != getattr(current, "spec", None):
                obj.metadata.generation += 1
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                del kind_map[key]
                self._enqueue("DELETED", obj)
            else:
                kind_map[key] = obj
                self._enqueue("MODIFIED", obj)
        self._drain()
        return fast_deepcopy(obj)

    def patch(self, kind: str, name: str, fn: Callable[[object], None], namespace: str = "default", retries: int = 10):
        """Read-modify-write with retry — the common controller patch idiom."""
        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            fn(obj)
            try:
                return self.update(obj, _owned=True)
            except Conflict:
                continue
        raise Conflict(f"{kind} {name}: too many conflicts")

    def update_status(self, obj):
        """Status-subresource style update: spec/labels on the server win."""
        def apply(cur):
            cur.status = fast_deepcopy(obj.status)
        ns = getattr(obj.metadata, "namespace", "default")
        return self.patch(obj.kind, obj.metadata.name, apply, namespace=ns)

    def delete(self, kind: str, name: str, namespace: str = "default", grace: bool = True):
        """Two-phase delete: with finalizers present, sets deletionTimestamp and
        MODIFIED; otherwise removes and emits DELETED."""
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            kind_map = self._objects.get(kind, {})
            obj = kind_map.get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            self._rv += 1
            # clone-and-replace, like update(): stored objects are NEVER
            # mutated in place — borrowed readers and out-of-lock list()
            # cloning depend on that invariant
            obj = fast_deepcopy(obj)
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            if obj.metadata.finalizers and grace:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self._now()
                kind_map[key] = obj
                self._enqueue("MODIFIED", obj)
            else:
                del kind_map[key]
                self._enqueue("DELETED", obj)
        self._drain()

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    # -- helpers ---------------------------------------------------------------
    def remove_finalizer(self, kind: str, name: str, finalizer: str, namespace: str = "default"):
        def fn(obj):
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
        try:
            self.patch(kind, name, fn, namespace=namespace)
        except NotFound:
            pass

    def count(self, kind: str) -> int:
        with self._lock:
            return len(self._objects.get(kind, {}))


def _selector_matches(selector: dict, labels: dict[str, str]) -> bool:
    from .objects import match_label_selector

    if "matchLabels" in selector or "matchExpressions" in selector:
        return match_label_selector(selector, labels)
    return match_label_selector({"matchLabels": selector}, labels)
