"""An in-process apiserver substitute: typed object store with optimistic
concurrency, finalizer-gated deletion, and watch fan-out.

Plays the role of the reference's L0 (kube-apiserver/etcd — SURVEY.md layer
map): all durable state lives here; controllers coordinate exclusively through
it. Semantics kept: resourceVersion conflict on stale writes, deletionTimestamp
+ finalizers two-phase delete, watch events (ADDED/MODIFIED/DELETED) delivered
to informers.
"""

from __future__ import annotations

import time

from ..obs.racecheck import make_rlock
from .clone import fast_deepcopy
from typing import Callable, Iterable, Optional


class NotFound(Exception):
    pass


class Conflict(Exception):
    pass


class AlreadyExists(Exception):
    pass


# kinds that are cluster-scoped (key = name, not namespace/name)
CLUSTER_SCOPED = {
    "Node",
    "NodeClaim",
    "NodePool",
    "NodeOverlay",
    "KWOKNodeClass",
    "PriorityClass",
    "StorageClass",
    "PersistentVolume",
    "CSINode",
    "ResourceSlice",
    "DeviceClass",
    "DRAConfig",
}

WatchFn = Callable[[str, object], None]  # (event_type, obj)


def obj_key(obj) -> str:
    meta = obj.metadata
    if obj.kind in CLUSTER_SCOPED:
        return meta.name
    return f"{meta.namespace}/{meta.name}"


class Store:
    """The in-memory 'cluster'. Thread-safe; objects are deep-copied on the
    way in and out so callers can never mutate stored state in place."""

    # the racecheck guarded-field registry (analysis: guarded-field-access;
    # runtime: obs.racecheck.touch). Sanctioned order: `_deliver_lock` may
    # acquire `_lock` (the _drain pop), NEVER the reverse — see the
    # serving-stack lock inventory in karpenter_tpu/serving/__init__.py.
    GUARDED_FIELDS = {
        "_objects": "_lock",
        "_watchers": "_lock",
        "_rv": "_lock",
        "_kind_rv": "_lock",
        "_kind_seq": "_lock",
        "_pending": "_lock",
        "_event_tracer": "_lock",
        "_fault_injector": "_lock",
        "_watch_loss": "_lock",
        "_watch_gap": "_deliver_lock",
        "_watch_base": "_deliver_lock",
    }

    def __init__(self, clock=None):
        self._lock = make_rlock("store")
        self._objects: dict[str, dict[str, object]] = {}  # kind -> key -> obj
        self._watchers: dict[str, list[WatchFn]] = {}
        self._rv = 0
        self._clock = clock
        # watch delivery: events are enqueued under self._lock (commit order,
        # stamped with a monotonic commit time) and drained FIFO under
        # self._deliver_lock, so watchers always observe ADDED < MODIFIED <
        # DELETED in resourceVersion order even with concurrent writers.
        self._pending: list[tuple[str, object, float, int]] = []
        self._deliver_lock = make_rlock("store-deliver")
        # podtrace (obs/podtrace.py): the event-lifecycle tracer's arrival
        # seam — every delivered event is stamped with its commit + delivery
        # monotonic times before the watchers run. None = untraced store.
        self._event_tracer = None
        # faultline (serving/faults.py): the watch-stream fault seam — a
        # FaultInjector may drop, duplicate, or reorder Pod deliveries to
        # prove the serving stack treats the stream as at-least-once and
        # unordered (the store CONTENT stays authoritative). None = the
        # production default: zero-cost, delivery untouched.
        self._fault_injector = None
        # per-kind revision: the rv of the last write touching the kind.
        # Caches that depend on one kind's content (e.g. the solver's volume
        # fold on StorageClass/PV/PVC) key on this instead of the global rv,
        # so unrelated writes don't invalidate them.
        self._kind_rv: dict[str, int] = {}
        # watch-loss detection (faultline): every committed event carries a
        # per-kind delivery SEQUENCE number, and with a fault injector
        # installed the drain observes the delivered seqs like a real
        # informer observes resourceVersions — a gap that survives to
        # queue-quiet (dup and reorder resolve themselves; only a drop
        # cannot) bumps the kind's loss epoch, which level-triggered
        # consumers (Provisioner -> Cluster.resync_pods) poll to re-converge
        # on store content. With no injector the in-process seam is lossless
        # by construction and the tracker stays empty (zero hot-path cost).
        self._kind_seq: dict[str, int] = {}
        self._watch_loss: dict[str, int] = {}  # kind -> cumulative lost-event count
        self._watch_gap: dict[str, list] = {}  # kind -> [watermark, out-of-order seq set]
        self._watch_base: dict[str, int] = {}  # kind -> seq watermark at injector install

    def kind_revision(self, kind: str) -> int:
        with self._lock:
            return self._kind_rv.get(kind, 0)

    def _now(self) -> float:
        return self._clock.now() if self._clock else 0.0

    # -- watches ---------------------------------------------------------------
    def watch(self, kind: str, fn: WatchFn) -> None:
        """Register a watch callback. Execution context contract: callbacks
        run SYNCHRONOUSLY on whatever thread committed the store write,
        under `_deliver_lock` — so they must be cheap and leaf-locked. This
        is the watch->wake seam the serving stack builds on: informer
        mirrors, the provisioner's batcher trigger, and the fleet
        front-end's push wake (`TenantSession._on_watch_event`, which marks
        the tenant runnable and sets the fleet loop's event) all ride it;
        every registered callback is a reviewed entry in the
        `[tool.solverlint] thread-shared` registry (the thread-escape rule
        enforces that at the call site)."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(fn)

    def unwatch(self, kind: str, fn: WatchFn) -> None:
        """Remove a previously-registered watch (no-op if absent) so
        short-lived observers don't accumulate across a suite."""
        with self._lock:
            fns = self._watchers.get(kind)
            if fns is not None and fn in fns:
                fns.remove(fn)

    def set_event_tracer(self, tracer) -> None:
        """Install (or clear) the podtrace event tracer on the delivery seam."""
        with self._lock:
            self._event_tracer = tracer

    def event_tracer(self):
        with self._lock:
            return self._event_tracer

    def set_fault_injector(self, injector) -> None:
        """Install (or clear) a faultline FaultInjector on the delivery seam
        (serving/faults.py: watch-drop / watch-dup / watch-reorder). Taking
        `_deliver_lock` first (the sanctioned order) means no drain is
        mid-flight during the swap, and the gap tracker's baseline is the
        exact seq watermark the lossy stream starts after."""
        with self._deliver_lock:
            with self._lock:
                self._fault_injector = injector
                self._watch_base = dict(self._kind_seq)
                self._watch_gap = {}

    def watch_loss_epoch(self, kind: str) -> int:
        """Cumulative count of watch events detected LOST for `kind` (never
        delivered; duplicates and reorders self-heal and don't count). A
        consumer that mirrors watch events into derived state compares this
        across polls and re-converges from store content on change — the
        level-triggered 'store content is authoritative' contract."""
        with self._lock:
            return self._watch_loss.get(kind, 0)

    def _enqueue(self, event: str, obj) -> None:  # solverlint: ok(guarded-field-access): caller-holds contract — every call site sits inside `with self._lock` (create/update/delete)
        # caller must hold self._lock; the stamp is the event's COMMIT time —
        # podtrace measures queueing delay from commit, not from drain — and
        # the per-kind seq is the delivery sequence the gap tracker audits
        seq = self._kind_seq.get(obj.kind, 0) + 1
        self._kind_seq[obj.kind] = seq
        self._pending.append((event, obj, time.monotonic(), seq))

    def _drain(self) -> None:
        with self._deliver_lock:
            while True:
                with self._lock:
                    if self._pending:
                        event, obj, t_commit, seq = self._pending.pop(0)
                        watchers = list(self._watchers.get(obj.kind, ()))
                    else:
                        event, obj, t_commit, seq, watchers = "", None, 0.0, 0, ()
                    tracer = self._event_tracer
                    injector = self._fault_injector
                if obj is None:
                    if injector is None:
                        return
                    # a reorder fault may have deferred the LAST event of a
                    # burst: flush it now so reordering delays delivery but
                    # can never lose it. The flush is DIRECT — it must not
                    # re-enter the fault matrix, where a due drop rule would
                    # lose the event (and a re-roll would consume a watch
                    # index, shifting every later rule vs the recorded plan)
                    deferred = injector.take_deferred()
                    if deferred is None:
                        # queue AND deferral quiet: any seq still outstanding
                        # in the gap tracker was dropped, never reordered —
                        # publish the loss so level-triggered consumers can
                        # re-converge on store content
                        self._note_watch_loss()
                        return
                    event, obj, t_commit, seq = deferred
                    with self._lock:
                        watchers = list(self._watchers.get(obj.kind, ()))
                    deliveries = ((event, obj, t_commit, seq),)
                elif injector is not None and obj.kind == "Pod":
                    # faultline watch-stream seam: drop / duplicate / reorder
                    # (all deliveries share obj's kind, so `watchers` holds).
                    # Materialize the gap-tracker entry at INTAKE: if this
                    # very event is dropped, _note_watch_loss must still see
                    # the kind to compare its watermark against the
                    # committed seq (the tail-drop case)
                    self._gap_entry(obj.kind)
                    deliveries = injector.on_watch_event(event, obj, t_commit, seq)
                else:
                    deliveries = ((event, obj, t_commit, seq),)
                for event, obj, t_commit, seq in deliveries:
                    if injector is not None:
                        self._observe_delivery(obj.kind, seq)
                    if tracer is not None and obj.kind == "Pod":
                        # arrival stamp BEFORE the watcher fan-out (and even
                        # with no watchers registered): the tracer only reads
                        # scalar fields off the stored object — the borrow
                        # contract. Kind-gated HERE so non-pod deliveries pay
                        # nothing.
                        tracer.on_delivery(event, obj, t_commit, time.monotonic())
                    if not watchers:
                        continue
                    # ONE clone shared by every watcher: watchers may read
                    # and retain it (the stored object is replaced on update,
                    # never mutated, and so is this snapshot) but MUST NOT
                    # mutate — the same contract as borrow_list. Under churn
                    # the per-watcher private clones were the dominant
                    # per-event cost (5 pod watchers -> 5 deep clones per
                    # arrival).
                    c = fast_deepcopy(obj)
                    for fn in watchers:
                        fn(event, c)

    def _gap_entry(self, kind: str) -> list:  # solverlint: ok(guarded-field-access): caller-holds contract — only called from _drain/_observe_delivery, inside `with self._deliver_lock`
        ent = self._watch_gap.get(kind)
        if ent is None:
            ent = self._watch_gap[kind] = [self._watch_base.get(kind, 0), set()]
        return ent

    def _observe_delivery(self, kind: str, seq: int) -> None:  # solverlint: ok(guarded-field-access): caller-holds contract — only called from _drain, inside `with self._deliver_lock`
        # the informer-side audit of the (possibly lossy) delivered stream:
        # contiguous seqs advance the watermark, out-of-order seqs park in
        # the pending set until their gap fills, and seqs at-or-below the
        # watermark are at-least-once duplicates (ignored)
        ent = self._gap_entry(kind)
        if seq == ent[0] + 1:
            ent[0] = seq
            pending = ent[1]
            while ent[0] + 1 in pending:
                pending.discard(ent[0] + 1)
                ent[0] += 1
        elif seq > ent[0] + 1:
            ent[1].add(seq)

    def _note_watch_loss(self) -> None:  # solverlint: ok(guarded-field-access): caller-holds contract — only called from _drain, inside `with self._deliver_lock` (takes `_lock` itself for the committed-seq read + epoch bump)
        # at queue-quiet every reorder has flushed, so any committed seq the
        # tracker never saw delivered was DROPPED — both mid-burst gaps
        # (seqs below max(pending)) and TAIL drops (watermark short of the
        # committed _kind_seq with nothing pending behind it). Count them
        # and adopt the new watermark so one drop is published exactly once.
        with self._lock:
            # a writer may have committed a new event between the drain's
            # empty-queue check and here; its delivery is still coming, so
            # only trust the committed seq as "should have arrived" when
            # the queue is still empty NOW
            committed = dict(self._kind_seq) if not self._pending else {}
            for kind, ent in self._watch_gap.items():
                pending = ent[1]
                top = max(pending) if pending else ent[0]
                top = max(top, committed.get(kind, 0))
                if top <= ent[0] and not pending:
                    continue
                lost = top - ent[0] - len(pending)
                ent[0] = top
                pending.clear()
                if lost > 0:
                    self._watch_loss[kind] = self._watch_loss.get(kind, 0) + lost

    # -- CRUD ------------------------------------------------------------------
    def create(self, obj, adopt: bool = False):
        """`adopt=True`: the caller relinquishes `obj` (must not mutate it
        after the call) and accepts the borrow contract on the return value —
        skips both defensive clones. For high-rate producers (the churn
        harness's event driver) where the per-create clone pair dominates."""
        with self._lock:
            kind_map = self._objects.setdefault(obj.kind, {})
            key = obj_key(obj)
            if key in kind_map:
                raise AlreadyExists(f"{obj.kind} {key} already exists")
            self._rv += 1
            if not adopt:
                obj = fast_deepcopy(obj)
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self._now()
            kind_map[key] = obj
            self._enqueue("ADDED", obj)
        self._drain()
        return obj if adopt else fast_deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            obj = self._objects.get(kind, {}).get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            return fast_deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: Optional[str] = None, label_selector: Optional[dict] = None) -> list:
        """label_selector accepts either the flat {key: value} form or the
        metav1 {matchLabels, matchExpressions} form."""
        # cloning outside the lock is safe: stored objects are replaced on
        # update, never mutated in place
        return [fast_deepcopy(o) for o in self.borrow_list(kind, namespace, label_selector)]

    # -- borrowed reads --------------------------------------------------------
    # client-go's shared informer cache hands controllers pointers into the
    # cache with a MUST-NOT-MUTATE contract — that is what makes the
    # reference's read paths cheap. These are the same primitive: the returned
    # objects are the stored ones; callers may only read them, never mutate or
    # retain them across writes. Hot read-only scans (topology domain counting,
    # provisionable-pod filtering, monitors) use these; anything that mutates
    # goes through get/list, which clone.
    def borrow_list(self, kind: str, namespace: Optional[str] = None, label_selector: Optional[dict] = None) -> list:
        with self._lock:
            out = []
            for obj in self._objects.get(kind, {}).values():
                if namespace is not None and obj.kind not in CLUSTER_SCOPED and obj.metadata.namespace != namespace:
                    continue
                if label_selector is not None and not _selector_matches(label_selector, obj.metadata.labels):
                    continue
                out.append(obj)
            return out

    def borrow_get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            return self._objects.get(kind, {}).get(key)

    def update(self, obj, _owned: bool = False):
        """Optimistic-concurrency full update; raises Conflict on stale RV.
        `_owned` (internal, patch()): the object is a patch-private clone the
        caller never sees again — skip the defensive clone-in."""
        with self._lock:
            kind_map = self._objects.setdefault(obj.kind, {})
            key = obj_key(obj)
            current = kind_map.get(key)
            if current is None:
                raise NotFound(f"{obj.kind} {key} not found")
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {key}: resourceVersion {obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            self._rv += 1
            if not _owned:
                obj = fast_deepcopy(obj)
            # deletionTimestamp is set only by delete(); preserve server-side value
            obj.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            # apiserver semantics: generation increments on spec change only
            obj.metadata.generation = current.metadata.generation
            if getattr(obj, "spec", None) != getattr(current, "spec", None):
                obj.metadata.generation += 1
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                del kind_map[key]
                self._enqueue("DELETED", obj)
            else:
                kind_map[key] = obj
                self._enqueue("MODIFIED", obj)
        self._drain()
        return fast_deepcopy(obj)

    def patch(self, kind: str, name: str, fn: Callable[[object], None], namespace: str = "default", retries: int = 10):
        """Read-modify-write with retry — the common controller patch idiom."""
        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            fn(obj)
            try:
                return self.update(obj, _owned=True)
            except Conflict:
                continue
        raise Conflict(f"{kind} {name}: too many conflicts")

    def update_status(self, obj):
        """Status-subresource style update: spec/labels on the server win."""
        def apply(cur):
            cur.status = fast_deepcopy(obj.status)
        ns = getattr(obj.metadata, "namespace", "default")
        return self.patch(obj.kind, obj.metadata.name, apply, namespace=ns)

    def delete(self, kind: str, name: str, namespace: str = "default", grace: bool = True):
        """Two-phase delete: with finalizers present, sets deletionTimestamp and
        MODIFIED; otherwise removes and emits DELETED."""
        with self._lock:
            key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
            kind_map = self._objects.get(kind, {})
            obj = kind_map.get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            self._rv += 1
            # clone-and-replace, like update(): stored objects are NEVER
            # mutated in place — borrowed readers and out-of-lock list()
            # cloning depend on that invariant
            obj = fast_deepcopy(obj)
            obj.metadata.resource_version = self._rv
            self._kind_rv[obj.kind] = self._rv
            if obj.metadata.finalizers and grace:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self._now()
                kind_map[key] = obj
                self._enqueue("MODIFIED", obj)
            else:
                del kind_map[key]
                self._enqueue("DELETED", obj)
        self._drain()

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    # -- helpers ---------------------------------------------------------------
    def remove_finalizer(self, kind: str, name: str, finalizer: str, namespace: str = "default"):
        def fn(obj):
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
        try:
            self.patch(kind, name, fn, namespace=namespace)
        except NotFound:
            pass

    def count(self, kind: str) -> int:
        with self._lock:
            return len(self._objects.get(kind, {}))


def _selector_matches(selector: dict, labels: dict[str, str]) -> bool:
    from .objects import match_label_selector

    if "matchLabels" in selector or "matchExpressions" in selector:
        return match_label_selector(selector, labels)
    return match_label_selector({"matchLabels": selector}, labels)
