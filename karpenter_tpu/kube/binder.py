"""A minimal kube-scheduler stand-in: binds pending pods to Ready nodes.

The reference relies on the real kube-scheduler (via kind/KWOK) to bind pods
once Karpenter has provisioned capacity; in this hermetic substrate the Binder
plays that role for e2e flows. First-fit over nodes: resources, taints,
node-selector/affinity, registered + schedulable.
"""

from __future__ import annotations

from ..apis import labels as wk
from ..scheduling.hostports import HostPortUsage, pod_host_ports
from ..scheduling.requirements import Requirements
from ..scheduling.taints import taints_tolerate_pod
from ..utils import pods as pod_utils
from ..utils import resources as res


class Binder:
    def __init__(self, store, cluster, clock, dra_enabled: bool = False):
        self.store = store
        self.cluster = cluster
        self.clock = clock
        self.dra_enabled = dra_enabled
        self._dra_allocator = None

    def bind_all(self) -> int:
        """One scheduling pass; returns number of pods bound."""
        bound = 0
        nodes = sorted(self.store.list("Node"), key=lambda n: n.metadata.name)
        node_reqs = {n.metadata.name: Requirements.from_labels(n.metadata.labels) for n in nodes}
        all_pods = self.store.list("Pod")
        # kube PodGC stand-in: active pods bound to a node that no longer
        # exists reset to pending (modeling controller recreation, like
        # eviction does) so the provisioner sees them again; node-owned
        # (static/mirror) pods die with their node instead — they must never
        # become pending demand
        node_names = {n.metadata.name for n in nodes}
        for q in all_pods:
            if q.spec.node_name and q.spec.node_name not in node_names and pod_utils.is_active(q):
                if pod_utils.is_owned_by_node(q):
                    self.store.try_delete("Pod", q.metadata.name, namespace=q.metadata.namespace)
                    continue

                def orphan(p):
                    p.spec.node_name = ""
                    p.status.phase = "Pending"
                    p.status.start_time = None

                self.store.patch("Pod", q.metadata.name, orphan, namespace=q.metadata.namespace)
                q.spec.node_name = ""
                q.status.phase = "Pending"
        # per-node host-port usage, built once per pass from ACTIVE bound
        # pods (terminal pods free their ports, as in Kubernetes)
        self._port_usage = {}
        # bound-pod index by node, maintained as the pass binds: required
        # hostname anti-affinity only ever inspects the candidate node's own
        # pods, so the check must not rescan the whole pod list per node
        self._pods_by_node = {}
        for q in all_pods:
            if q.spec.node_name and pod_utils.is_active(q):
                self._port_usage.setdefault(q.spec.node_name, HostPortUsage()).add(q.key(), pod_host_ports(q))
                self._pods_by_node.setdefault(q.spec.node_name, []).append(q)
        self._dra_allocator = None  # fresh per pass
        self._node_domain = None  # lazy per-pass node->labels map for spreads
        for pod in all_pods:
            if not pod_utils.is_provisionable(pod):
                continue
            node = self._find_node(pod, nodes, node_reqs, all_pods)
            if node is not None:
                self._bind(pod, node)
                pod.spec.node_name = node.metadata.name  # keep local view current for spread counting
                self._port_usage.setdefault(node.metadata.name, HostPortUsage()).add(pod.key(), pod_host_ports(pod))
                self._pods_by_node.setdefault(node.metadata.name, []).append(pod)
                bound += 1
        return bound

    def _dra_ok(self, pod, node) -> bool:
        """Claim-bearing pods bind only where their claims are allocated (or
        allocatable) — the kube-scheduler's DRA plugin behavior. With the
        feature gate off the whole control plane ignores claims, so the binder
        must too or scheduled pods could never bind."""
        if not self.dra_enabled or not pod.spec.resource_claims:
            return True
        from ..scheduling.dynamicresources import Allocator, resolve_pod_claims

        claims, err = resolve_pod_claims(self.store, pod)
        if err is not None:
            return False
        if self._dra_allocator is None:
            self._dra_allocator = Allocator(self.store, self.clock)
        result, aerr = self._dra_allocator.allocate_for_node(node.metadata.name, claims)
        if aerr is not None:
            return False
        self._dra_allocator.commit_for_node(node.metadata.name, result)
        return True

    def _find_node(self, pod, nodes, node_reqs_cache, all_pods):
        reqs = Requirements.from_pod(pod, strict=True)
        requests = res.pod_requests(pod)
        for node in nodes:
            if node.spec.unschedulable or node.metadata.deletion_timestamp is not None:
                continue
            if any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints):
                continue
            if taints_tolerate_pod(node.spec.taints, pod) is not None:
                continue
            if node_reqs_cache[node.metadata.name].compatible(reqs) is not None:
                continue
            sn = self.cluster.node_for_name(node.metadata.name)
            available = sn.available() if sn is not None else node.status.allocatable
            if not res.fits(requests, available):
                continue
            if not self._topology_ok(pod, node, nodes, all_pods):
                continue
            if not self._ports_ok(pod, node):
                continue
            if not self._dra_ok(pod, node):
                continue
            return node
        return None

    def _ports_ok(self, pod, node) -> bool:
        """The kube-scheduler NodePorts plugin: a pod with host ports cannot
        land on a node where an ACTIVE bound pod already holds a conflicting
        port (terminal pods free theirs)."""
        ports = pod_host_ports(pod)
        if not ports:
            return True
        usage = self._port_usage.get(node.metadata.name)
        return usage is None or usage.conflicts(pod.key(), ports) is None

    def _topology_ok(self, pod, node, nodes, all_pods) -> bool:
        """Honor DoNotSchedule spread constraints and required hostname
        anti-affinity — the kube-scheduler behaviors the e2e flows rely on."""
        from .objects import match_label_selector
        from ..controllers.provisioning.scheduling.topology import effective_spread_selector

        for tsc in pod.spec.topology_spread_constraints:
            if tsc.when_unsatisfiable != "DoNotSchedule":
                continue
            node_domain = self._node_domain
            if node_domain is None:
                node_domain = self._node_domain = {n.metadata.name: n.metadata.labels for n in nodes}
            eff_sel = effective_spread_selector(pod, tsc)
            counts: dict[str, int] = {}
            for n in nodes:
                d = n.metadata.labels.get(tsc.topology_key)
                if d is not None:
                    counts.setdefault(d, 0)
            for q in all_pods:
                if not q.spec.node_name or q.metadata.namespace != pod.metadata.namespace:
                    continue
                if not match_label_selector(eff_sel, q.metadata.labels):
                    continue
                d = node_domain.get(q.spec.node_name, {}).get(tsc.topology_key)
                if d is not None:
                    counts[d] = counts.get(d, 0) + 1
            my_domain = node.metadata.labels.get(tsc.topology_key)
            if my_domain is None:
                continue
            if counts:
                if counts.get(my_domain, 0) + 1 - min(counts.values()) > tsc.max_skew:
                    return False
        aff = pod.spec.affinity
        if aff is not None:
            for term in aff.pod_anti_affinity_required:
                if term.topology_key != wk.HOSTNAME_LABEL_KEY:
                    continue
                for q in self._pods_by_node.get(node.metadata.name, ()):
                    if q.metadata.namespace == pod.metadata.namespace and match_label_selector(
                        term.label_selector, q.metadata.labels
                    ):
                        return False
        return True

    def _bind(self, pod, node) -> None:
        def apply(p):
            p.spec.node_name = node.metadata.name
            p.status.phase = "Running"
            p.status.start_time = self.clock.now()

        self.store.patch("Pod", pod.metadata.name, apply, namespace=pod.metadata.namespace)
