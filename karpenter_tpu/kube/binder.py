"""A minimal kube-scheduler stand-in: binds pending pods to Ready nodes.

The reference relies on the real kube-scheduler (via kind/KWOK) to bind pods
once Karpenter has provisioned capacity; in this hermetic substrate the Binder
plays that role for e2e flows. First-fit over nodes: resources, taints,
node-selector/affinity, registered + schedulable.
"""

from __future__ import annotations

from ..apis import labels as wk
from ..scheduling.hostports import HostPortUsage, pod_host_ports
from ..scheduling.requirements import Requirements
from ..scheduling.taints import taints_tolerate_pod
from ..utils import pods as pod_utils
from ..utils import resources as res


class Binder:
    def __init__(self, store, cluster, clock, dra_enabled: bool = False):
        self.store = store
        self.cluster = cluster
        self.clock = clock
        self.dra_enabled = dra_enabled
        self._dra_allocator = None
        self._bound_now: dict[str, str] = {}

    def bind_all(self) -> int:
        """One scheduling pass; returns number of pods bound.

        The pod view is BORROWED (store.borrow_list): under churn, deep-
        cloning every pod per pass was the binder's dominant cost. Borrowed
        objects are never mutated; binds made mid-pass are tracked in the
        `self._bound_now` overlay, and every node-name read below goes
        through `self._nn` so later candidates in the same pass see them —
        exactly the visibility the old mutate-the-local-clone scheme gave."""
        bound = 0
        nodes = sorted(self.store.list("Node"), key=lambda n: n.metadata.name)
        node_reqs = {n.metadata.name: Requirements.from_labels(n.metadata.labels) for n in nodes}
        self._bound_now: dict[str, str] = {}
        all_pods = self.store.borrow_list("Pod")
        # kube PodGC stand-in: active pods bound to a node that no longer
        # exists reset to pending (modeling controller recreation, like
        # eviction does) so the provisioner sees them again; node-owned
        # (static/mirror) pods die with their node instead — they must never
        # become pending demand
        node_names = {n.metadata.name for n in nodes}
        orphaned = False
        for q in all_pods:
            if q.spec.node_name and q.spec.node_name not in node_names and pod_utils.is_active(q):
                if pod_utils.is_owned_by_node(q):
                    # dies with the node: drop from this pass's view too, or
                    # the stale entry would count into affinity matching
                    self.store.try_delete("Pod", q.metadata.name, namespace=q.metadata.namespace)
                    orphaned = True
                    continue

                def orphan(p):
                    p.spec.node_name = ""
                    p.status.phase = "Pending"
                    p.status.start_time = None

                self.store.patch("Pod", q.metadata.name, orphan, namespace=q.metadata.namespace)
                orphaned = True
        if orphaned:
            # rare path: re-borrow so the view reflects the deletions/orphans
            all_pods = self.store.borrow_list("Pod")
        # per-node host-port usage, built once per pass from ACTIVE bound
        # pods (terminal pods free their ports, as in Kubernetes)
        self._port_usage = {}
        for q in all_pods:
            if self._nn(q) and pod_utils.is_active(q):
                self._port_usage.setdefault(self._nn(q), HostPortUsage()).add(q.key(), pod_host_ports(q))
        # store-content authority for node usage (faultline watch-loss
        # robustness): the cluster's per-node usage is event-fed, so a lossy
        # watch stream (dropped bind echo, dropped departure DELETED) leaves
        # it stale mid-pass. Track the pods ACTUALLY bound and non-terminal
        # per store content — the same population Cluster.update_pod counts —
        # keyed by node, so _available() can diff-correct sn.available().
        # When the two views agree (the lossless in-process default) the key
        # sets match and the correction is an exact no-op.
        self._node_pods = {}
        for q in all_pods:
            nn = self._nn(q)
            if nn and not pod_utils.is_terminal(q):
                self._node_pods.setdefault(nn, {})[q.key()] = q
        self._dra_allocator = None  # fresh per pass
        self._node_domain = {n.metadata.name: n.metadata.labels for n in nodes}
        # symmetric anti-affinity (the kube-scheduler's InterPodAffinity
        # plugin): ACTIVE BOUND pods carrying required anti terms repel
        # matching candidates from their domains; maintained incrementally so
        # a pod binding mid-pass repels later candidates in the same pass
        self._anti_holders = [
            (q, term, self._term_namespaces(q, term, all_pods))
            for q in all_pods
            if self._nn(q) and pod_utils.is_active(q) and q.spec.affinity is not None
            for term in q.spec.affinity.pod_anti_affinity_required
        ]
        for pod in all_pods:
            if not pod_utils.is_provisionable(pod):
                continue
            node = self._find_node(pod, nodes, node_reqs, all_pods)
            if node is not None:
                self._bind(pod, node)
                # overlay, not mutation: keeps the pass-local view current
                # for spread/affinity counting without touching the borrowed
                # stored object
                self._bound_now[pod.key()] = node.metadata.name
                self._node_pods.setdefault(node.metadata.name, {})[pod.key()] = pod
                self._port_usage.setdefault(node.metadata.name, HostPortUsage()).add(pod.key(), pod_host_ports(pod))
                if pod.spec.affinity is not None:
                    for term in pod.spec.affinity.pod_anti_affinity_required:
                        self._anti_holders.append((pod, term, self._term_namespaces(pod, term, all_pods)))
                bound += 1
        return bound

    def _nn(self, q) -> str:
        """The pod's node name as of NOW in this pass: binds made earlier in
        the pass (recorded in the overlay) win over the borrowed snapshot."""
        nn = self._bound_now.get(q.key())
        return nn if nn is not None else q.spec.node_name

    @staticmethod
    def _term_namespaces(pod, term, all_pods) -> set:
        return pod_utils.term_namespaces(pod, term, lambda: (p.metadata.namespace for p in all_pods))

    def _dra_ok(self, pod, node) -> bool:
        """Claim-bearing pods bind only where their claims are allocated (or
        allocatable) — the kube-scheduler's DRA plugin behavior. With the
        feature gate off the whole control plane ignores claims, so the binder
        must too or scheduled pods could never bind."""
        if not self.dra_enabled or not pod.spec.resource_claims:
            return True
        from ..scheduling.dynamicresources import Allocator, resolve_pod_claims

        claims, err = resolve_pod_claims(self.store, pod)
        if err is not None:
            return False
        if self._dra_allocator is None:
            self._dra_allocator = Allocator(self.store, self.clock)
        result, aerr = self._dra_allocator.allocate_for_node(node.metadata.name, claims)
        if aerr is not None:
            return False
        self._dra_allocator.commit_for_node(node.metadata.name, result)
        return True

    def _affinity_context(self, pod, all_pods):
        """Per-PENDING-POD precompute for the inter-pod affinity checks: the
        matching pods' occupied domains are node-independent, so one O(pods)
        pass here replaces an O(pods) rescan per candidate node. Reflects
        every bind made earlier in this pass (local node_name updates)."""
        from .objects import match_label_selector

        aff = pod.spec.affinity
        anti_blocked: set = set()  # (key, domain) the pod's own anti terms forbid
        aff_terms: list = []  # (key, allowed domains, found_any, self_match)
        if aff is not None:
            for term in aff.pod_anti_affinity_required:
                key = term.topology_key
                nss = self._term_namespaces(pod, term, all_pods)
                for q in all_pods:
                    if not self._nn(q) or not pod_utils.is_active(q):
                        continue
                    if q.metadata.namespace not in nss:
                        continue
                    if not match_label_selector(term.label_selector, q.metadata.labels):
                        continue
                    d = self._node_domain.get(self._nn(q), {}).get(key)
                    if d is not None:
                        anti_blocked.add((key, d))
            for term in aff.pod_affinity_required:
                key = term.topology_key
                nss = self._term_namespaces(pod, term, all_pods)
                allowed: set = set()
                found_any = False
                for q in all_pods:
                    if not self._nn(q) or not pod_utils.is_active(q):
                        continue
                    if q.metadata.namespace not in nss:
                        continue
                    if not match_label_selector(term.label_selector, q.metadata.labels):
                        continue
                    found_any = True
                    d = self._node_domain.get(self._nn(q), {}).get(key)
                    if d is not None:
                        allowed.add(d)
                self_match = pod.metadata.namespace in nss and match_label_selector(
                    term.label_selector, pod.metadata.labels
                )
                aff_terms.append((key, allowed, found_any, self_match))
        # symmetric enforcement: domains whose holders' anti terms match THIS pod
        holder_blocked: set = set()
        for q, term, q_ns in self._anti_holders:
            if pod.metadata.namespace not in q_ns:
                continue
            if not match_label_selector(term.label_selector, pod.metadata.labels):
                continue
            d = self._node_domain.get(self._nn(q), {}).get(term.topology_key)
            if d is not None:
                holder_blocked.add((term.topology_key, d))
        return anti_blocked, aff_terms, holder_blocked

    def _find_node(self, pod, nodes, node_reqs_cache, all_pods):
        reqs = Requirements.from_pod(pod, strict=True)
        requests = res.pod_requests(pod)
        aff_ctx = self._affinity_context(pod, all_pods)
        for node in nodes:
            if node.spec.unschedulable or node.metadata.deletion_timestamp is not None:
                continue
            if any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints):
                continue
            if taints_tolerate_pod(node.spec.taints, pod) is not None:
                continue
            if node_reqs_cache[node.metadata.name].compatible(reqs) is not None:
                continue
            sn = self.cluster.node_for_name(node.metadata.name)
            available = self._available(node, sn)
            if not res.fits(requests, available):
                continue
            if not self._topology_ok(pod, node, nodes, all_pods, aff_ctx):
                continue
            if not self._ports_ok(pod, node):
                continue
            if not self._dra_ok(pod, node):
                continue
            return node
        return None

    def _available(self, node, sn) -> dict:
        """The node's available resources with the store as the authority:
        start from the cluster's event-fed `sn.available()` and correct it
        for any divergence between the pods the cluster TRACKS on the node
        and the pods the store actually has bound there (including binds
        made earlier in this pass). A lossy watch stream is the only way
        the two differ — when they agree this returns sn.available()
        untouched, so no-fault placements are bit-identical by
        construction."""
        if sn is None:
            return node.status.allocatable
        available = sn.available()
        view = self._node_pods.get(node.metadata.name, {})
        tracked = sn.pod_requests
        if view.keys() != tracked.keys():
            # missed bind/create echoes: the store knows the pod is here,
            # the cluster never saw the event — its requests are in use
            for key, q in view.items():
                if key not in tracked:
                    available = res.subtract(available, res.pod_requests(q))
            # missed departure DELETEDs: the cluster still charges a pod
            # the store no longer has — give its recorded requests back
            ghosts = [tracked[key] for key in tracked if key not in view]
            if ghosts:
                available = res.merge(available, *ghosts)
        return available

    def _ports_ok(self, pod, node) -> bool:
        """The kube-scheduler NodePorts plugin: a pod with host ports cannot
        land on a node where an ACTIVE bound pod already holds a conflicting
        port (terminal pods free theirs)."""
        ports = pod_host_ports(pod)
        if not ports:
            return True
        usage = self._port_usage.get(node.metadata.name)
        return usage is None or usage.conflicts(pod.key(), ports) is None

    def _topology_ok(self, pod, node, nodes, all_pods, aff_ctx) -> bool:
        """Honor DoNotSchedule spread constraints and inter-pod
        (anti-)affinity — the kube-scheduler behaviors the e2e flows rely on.
        `aff_ctx` is the pod's precomputed (anti_blocked, aff_terms,
        holder_blocked) from _affinity_context."""
        from .objects import match_label_selector
        from ..controllers.provisioning.scheduling.topology import effective_spread_selector

        for tsc in pod.spec.topology_spread_constraints:
            if tsc.when_unsatisfiable != "DoNotSchedule":
                continue
            node_domain = self._node_domain
            eff_sel = effective_spread_selector(pod, tsc)
            counts: dict[str, int] = {}
            for n in nodes:
                d = n.metadata.labels.get(tsc.topology_key)
                if d is not None:
                    counts.setdefault(d, 0)
            for q in all_pods:
                # terminal pods vacate their domain (kube-scheduler semantics;
                # mirrors the solver's ignored_for_topology)
                if not self._nn(q) or not pod_utils.is_active(q):
                    continue
                if q.metadata.namespace != pod.metadata.namespace:
                    continue
                if not match_label_selector(eff_sel, q.metadata.labels):
                    continue
                d = node_domain.get(self._nn(q), {}).get(tsc.topology_key)
                if d is not None:
                    counts[d] = counts.get(d, 0) + 1
            my_domain = node.metadata.labels.get(tsc.topology_key)
            if my_domain is None:
                continue
            if counts:
                if counts.get(my_domain, 0) + 1 - min(counts.values()) > tsc.max_skew:
                    return False
        # inter-pod (anti-)affinity, kube-scheduler InterPodAffinity
        # semantics over ANY topology key (a node missing the key offers no
        # domain: anti terms cannot be violated there, affinity terms cannot
        # be satisfied there) — set lookups against the precomputed context
        node_labels = node.metadata.labels
        anti_blocked, aff_terms, holder_blocked = aff_ctx
        for key, d in anti_blocked:
            if node_labels.get(key) == d:
                return False
        for key, d in holder_blocked:
            if node_labels.get(key) == d:
                return False
        for key, allowed, found_any, self_match in aff_terms:
            my_d = node_labels.get(key)
            if my_d is None:
                return False
            if my_d in allowed:
                continue
            # bootstrap rule: with NO matching pod anywhere, a pod matching
            # its own term may found the domain
            if found_any or not self_match:
                return False
        return True

    def _bind(self, pod, node) -> None:
        def apply(p):
            p.spec.node_name = node.metadata.name
            p.status.phase = "Running"
            p.status.start_time = self.clock.now()

        self.store.patch("Pod", pod.metadata.name, apply, namespace=pod.metadata.namespace)
