"""A minimal DaemonSet controller stand-in for the hermetic substrate.

The reference's e2e environment (kind + KWOK) runs the real DaemonSet
controller, so daemon pods exist on every matching node and the
kube-scheduler's NodePorts/resource accounting sees them. This substrate has
no kubelet or controller-manager; the runner materializes one daemon pod per
(DaemonSet, compatible registered node) so that:

- state nodes account daemon usage as REAL pods (the scheduler's phantom
  daemon headroom then nets to zero, exactly as designed in
  existingnode.go:45-60 semantics);
- host-port reservations made by daemons exist on the node for the Binder's
  NodePorts check and the solver's encode;
- emptiness/consolidation treat daemon-only nodes as reclaimable (daemon
  pods are excluded from reschedulability, like the reference).
"""

from __future__ import annotations

from ..apis import labels as wk
from ..scheduling.requirements import Requirements
from ..scheduling.taints import taints_tolerate_pod


class DaemonSetRunner:
    def __init__(self, store, clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> int:
        """Converge daemon pods: create missing ones on compatible registered
        nodes, delete orphans (DS gone or node gone). Returns pods created."""
        created = 0
        daemonsets = {(ds.metadata.namespace, ds.metadata.name): ds for ds in self.store.list("DaemonSet")}
        nodes = {n.metadata.name: n for n in self.store.list("Node")}

        # index existing daemon pods by (ds namespace, ds name, node)
        have: dict[tuple[str, str, str], object] = {}
        for p in self.store.list("Pod"):
            owner = next((o for o in p.metadata.owner_references if o.kind == "DaemonSet"), None)
            if owner is None:
                continue
            key = (p.metadata.namespace, owner.name)
            if key not in daemonsets or (p.spec.node_name and p.spec.node_name not in nodes):
                self.store.try_delete("Pod", p.metadata.name, namespace=p.metadata.namespace)
                continue
            if p.spec.node_name:
                have[(p.metadata.namespace, owner.name, p.spec.node_name)] = p

        from .store import AlreadyExists

        for (ns, ds_name), ds in daemonsets.items():
            template = ds.to_pod()
            for name, node in nodes.items():
                if (ns, ds_name, name) in have:
                    continue
                if node.metadata.deletion_timestamp is not None:
                    continue
                if any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints):
                    continue
                if not self._matches(template, node):
                    continue
                pod = ds.to_pod()
                pod.metadata.name = f"{ds_name}-{name}"
                pod.spec.node_name = name
                pod.status.phase = "Running"
                pod.status.start_time = self.clock.now()
                try:
                    self.store.create(pod)
                    created += 1
                except AlreadyExists:
                    # a non-daemon pod owns the name; converges next tick if
                    # it goes away, and the port is held meanwhile either way
                    continue
        return created

    @staticmethod
    def _matches(template, node) -> bool:
        """DaemonSet scheduling predicate: tolerates the node's taints (the
        real controller adds not-ready/unreachable tolerations implicitly;
        the substrate's registered gate stands in for that) and matches the
        template's node selector or ANY required affinity OR-term — the same
        predicate the scheduler's daemon-compatibility uses
        (_daemon_requirement_alternatives), so materialization converges with
        the headroom the solve reserved."""
        from ..controllers.provisioning.scheduling.scheduler import _daemon_requirement_alternatives

        if taints_tolerate_pod(node.spec.taints, template) is not None:
            return False
        node_reqs = Requirements.from_labels(node.metadata.labels)
        return any(node_reqs.compatible(alt) is None for alt in _daemon_requirement_alternatives(template))
