"""Fast deep-clone for stored API objects.

The Store isolates callers from stored state by copying every object on the
way in and out (the reference gets this for free from apiserver
serialization). `copy.deepcopy` was the control plane's dominant cost at
reference scale (1,000-2,000 pods — host_name_spreading_test.go:59-67): its
memo dict and reflective dispatch cost ~30x what these closed-shape objects
need. This module is a structural-sharing clone specialized to the object
model:

- immutable leaves are SHARED, not copied: str/int/float/bool/None, Quantity
  (never mutated after construction), frozen dataclasses with immutable
  fields (Taint, Toleration), Enum members;
- containers and mutable dataclasses are rebuilt recursively with no memo
  (the object model is a tree — no aliasing or cycles to preserve);
- unknown types fall back to copy.deepcopy, so correctness never depends on
  this registry being complete.
"""

from __future__ import annotations

import copy as _copy
from enum import Enum

from ..scheduling.taints import Taint, Toleration
from ..utils.quantity import Quantity

# shared-on-clone leaf types (immutable, or verified never mutated in place)
_ATOMS = frozenset({str, int, float, bool, bytes, type(None), Quantity, Taint, Toleration})

_CLONERS: dict = {}


def fast_deepcopy(x):
    t = x.__class__
    if t in _ATOMS:
        return x
    if t is dict:
        return {k: fast_deepcopy(v) for k, v in x.items()}
    if t is list:
        return [fast_deepcopy(v) for v in x]
    cloner = _CLONERS.get(t)
    if cloner is None:
        cloner = _CLONERS.setdefault(t, _make_cloner(t))
    return cloner(x)


def _clone_tuple(x):
    return tuple(fast_deepcopy(v) for v in x)


def _clone_set(x):
    return {fast_deepcopy(v) for v in x}


def _clone_instance(x):
    # plain-__dict__ object (all the kube/apis dataclasses): allocate without
    # __init__ and rebuild fields, sharing atomic leaves
    t = x.__class__
    new = t.__new__(t)
    d = new.__dict__
    atoms = _ATOMS
    for k, v in x.__dict__.items():
        d[k] = v if v.__class__ in atoms else fast_deepcopy(v)
    return new


def _make_cloner(t):
    import types

    if t is tuple:
        return _clone_tuple
    if t is set or t is frozenset:
        return _clone_set
    if issubclass(t, Enum) or issubclass(t, (types.FunctionType, types.BuiltinFunctionType, type, types.ModuleType)):
        return lambda x: x  # singletons / identity-preserving
    if issubclass(t, (dict, list, tuple, set)):
        return _copy.deepcopy  # container subclass with unknown invariants
    if getattr(t, "__deepcopy__", None) is not None or getattr(t, "__slots__", None) is not None:
        return _copy.deepcopy
    try:
        probe = t.__new__(t)
        probe.__dict__  # noqa: B018 — instances must carry a plain __dict__
    except Exception:  # solverlint: ok(swallowed-exception): capability probe — classes without a plain __dict__ route to the stdlib deepcopy fallback
        return _copy.deepcopy
    return _clone_instance
