"""Host-side utilities: exact quantity/resource arithmetic, clocks."""

from .quantity import Quantity  # noqa: F401
