"""Pod predicates (reference: pkg/utils/pod/scheduling.go).

The provisioner acts on "provisionable" pods: pending, unbound, and not
destined for termination. Reschedulability feeds disruption decisions.
"""

from __future__ import annotations

from .disruption import DO_NOT_DISRUPT_ANNOTATION

TERMINAL_PHASES = ("Succeeded", "Failed")


def is_scheduled(pod) -> bool:
    return bool(pod.spec.node_name)


def is_terminal(pod) -> bool:
    return pod.status.phase in TERMINAL_PHASES


def is_terminating(pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_provisionable(pod) -> bool:
    """Unbound, non-terminal, not terminating — the pods the provisioner batches."""
    return not is_scheduled(pod) and not is_terminal(pod) and not is_terminating(pod)


def is_active(pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)


def is_reschedulable(pod) -> bool:
    """Pods that must fit elsewhere if their node is disrupted: active and not
    owned by the node itself (static/mirror pods) or a DaemonSet."""
    return is_active(pod) and not is_owned_by_daemonset(pod) and not is_owned_by_node(pod)


def is_owned_by_daemonset(pod) -> bool:
    return any(ref.kind == "DaemonSet" for ref in pod.metadata.owner_references)


def is_owned_by_node(pod) -> bool:
    return any(ref.kind == "Node" for ref in pod.metadata.owner_references)


def has_do_not_disrupt(pod) -> bool:
    return pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION) == "true"


def is_disruptable(pod) -> bool:
    return not has_do_not_disrupt(pod)


def is_eviction_blocked(pod) -> bool:
    return has_do_not_disrupt(pod) and is_active(pod)
