"""Pod predicates (reference: pkg/utils/pod/scheduling.go).

The provisioner acts on "provisionable" pods: pending, unbound, and not
destined for termination. Reschedulability feeds disruption decisions.
"""

from __future__ import annotations

from .disruption import DO_NOT_DISRUPT_ANNOTATION

TERMINAL_PHASES = ("Succeeded", "Failed")


def is_scheduled(pod) -> bool:
    return bool(pod.spec.node_name)


def is_terminal(pod) -> bool:
    return pod.status.phase in TERMINAL_PHASES


def is_terminating(pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_provisionable(pod) -> bool:
    """Unbound, non-terminal, not terminating — the pods the provisioner batches."""
    return not is_scheduled(pod) and not is_terminal(pod) and not is_terminating(pod)


def is_active(pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)


def is_reschedulable(pod) -> bool:
    """Pods that must fit elsewhere if their node is disrupted: active — or
    TERMINATING but owned by a StatefulSet, whose replacement is recreated
    with the same identity only after deletion, so reserving capacity for it
    raises availability (pod/scheduling.go:40-51) — and not owned by the
    node itself (static/mirror pods) or a DaemonSet."""
    return (
        (is_active(pod) or (is_owned_by_statefulset(pod) and is_terminating(pod)))
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


def is_owned_by_statefulset(pod) -> bool:
    return any(ref.kind == "StatefulSet" for ref in pod.metadata.owner_references)


def is_owned_by_daemonset(pod) -> bool:
    return any(ref.kind == "DaemonSet" for ref in pod.metadata.owner_references)


def is_owned_by_node(pod) -> bool:
    return any(ref.kind == "Node" for ref in pod.metadata.owner_references)


def has_do_not_disrupt(pod, now: float | None = None) -> bool:
    """Clock-aware do-not-disrupt check (reference pod/scheduling.go
    IsDoNotDisruptActive:205-240): "true" blocks forever; a positive Go
    duration ("5m", "1h") blocks until pod creation + duration; anything else
    — including "Never", which is NOT a valid Go duration and errors in the
    reference's time.ParseDuration — is treated as if the annotation were
    absent. `now=None` treats duration annotations as active (callers without
    a clock stay conservative)."""
    value = pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION)
    if value is None:
        return False
    if value == "true":
        return True
    from .durations import NEVER, parse_duration

    try:
        seconds = parse_duration(value)
    except ValueError:
        return False  # invalid format: treated as absent
    if seconds is None or seconds <= 0 or seconds == NEVER:
        return False  # "Never" parses here (consolidateAfter-ism) but is an
        # invalid annotation duration in the reference: non-blocking
    if now is None:
        return True
    return now < (pod.metadata.creation_timestamp or 0.0) + seconds


def is_disruptable(pod, now: float | None = None) -> bool:
    return not has_do_not_disrupt(pod, now)


def is_eviction_blocked(pod, now: float | None = None) -> bool:
    return has_do_not_disrupt(pod, now) and is_active(pod)


def term_namespaces(pod, term, all_namespaces) -> set:
    """The namespaces a PodAffinityTerm selects: explicit list > selector
    (empty selector = ALL namespaces, approximated by `all_namespaces()`, a
    callable yielding every currently-known namespace; non-empty selectors
    approximate to the pod's own) > the pod's own namespace. Shared by the
    host topology tracker and the Binder so their term scoping can't drift."""
    if term.namespaces:
        return set(term.namespaces)
    if term.namespace_selector is not None:
        if not term.namespace_selector:
            return set(all_namespaces()) | {pod.metadata.namespace}
        return {pod.metadata.namespace}
    return {pod.metadata.namespace}
