"""PodDisruptionBudget limits (reference: pkg/utils/pdb): can a pod be
evicted without violating any covering PDB?"""

from __future__ import annotations

from ..kube.objects import match_label_selector
from ..utils import pods as pod_utils


class PDBLimits:
    """Stateful like the eviction API: each allowed eviction consumes budget,
    so a drain loop cannot evict a whole priority group past the PDB."""

    def __init__(self, store):
        self.store = store
        self.pdbs = store.list("PodDisruptionBudget")
        self._pods = None
        self._consumed: dict[str, int] = {}  # pdb key -> evictions granted

    def _healthy_matching(self, pdb) -> list:
        if self._pods is None:
            self._pods = [p for p in self.store.list("Pod") if pod_utils.is_active(p)]
        return [
            p
            for p in self._pods
            if p.metadata.namespace == pdb.metadata.namespace and match_label_selector(pdb.selector, p.metadata.labels)
        ]

    def _allowed_disruptions(self, pdb) -> int:
        total = len(self._healthy_matching(pdb))
        allowed = total
        if pdb.min_available is not None:
            allowed = min(allowed, total - _scaled(pdb.min_available, total))
        if pdb.max_unavailable is not None:
            allowed = min(allowed, _scaled(pdb.max_unavailable, total))
        return max(0, allowed)

    def can_evict(self, pod) -> tuple[bool, str | None]:
        """(allowed, blocking pdb name). Does NOT consume budget — callers
        actually evicting must call note_eviction()."""
        for pdb in self.pdbs:
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            if not match_label_selector(pdb.selector, pod.metadata.labels):
                continue
            key = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
            if self._allowed_disruptions(pdb) - self._consumed.get(key, 0) < 1:
                return False, pdb.metadata.name
        return True, None

    def note_eviction(self, pod) -> None:
        for pdb in self.pdbs:
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            if not match_label_selector(pdb.selector, pod.metadata.labels):
                continue
            key = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
            self._consumed[key] = self._consumed.get(key, 0) + 1


def _scaled(value, total: int) -> int:
    if isinstance(value, str) and value.endswith("%"):
        import math

        return math.ceil(int(value[:-1]) * total / 100)
    return int(value)
