"""Injectable clocks (reference: k8s.io/utils/clock usage, e.g. provisioner.go:96).

Every controller takes a Clock so tests are fully deterministic — the same
fake-clock discipline the reference uses throughout its suites.
"""

from __future__ import annotations

import time

from ..obs.racecheck import make_lock


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock; step() advances time manually."""

    GUARDED_FIELDS = {"_t": "_lock"}

    def __init__(self, start: float = 1_000_000.0):
        self._t = start
        self._lock = make_lock("clock")

    def now(self) -> float:
        with self._lock:
            return self._t

    def step(self, seconds: float) -> None:
        with self._lock:
            self._t += seconds

    def set(self, t: float) -> None:
        with self._lock:
            self._t = t

    def sleep(self, seconds: float) -> None:
        self.step(seconds)
