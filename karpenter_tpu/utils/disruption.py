"""Eviction/disruption cost model (reference: pkg/utils/disruption/disruption.go:36-88)."""

from __future__ import annotations

from ..apis.labels import DO_NOT_DISRUPT_ANNOTATION_KEY as DO_NOT_DISRUPT_ANNOTATION

PD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def eviction_cost(pod) -> float:
    """Base 1.0, shifted by pod-deletion-cost annotation and priority, clamped
    to [-10, 10]."""
    cost = 1.0
    raw = pod.metadata.annotations.get(PD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / 2.0**27
        except ValueError:
            pass
    if pod.spec.priority is not None:
        cost += pod.spec.priority / 2.0**25
    return max(-10.0, min(10.0, cost))


def rescheduling_cost(pods) -> float:
    return sum(eviction_cost(p) for p in pods)


def lifetime_remaining(now: float, expire_after: float | None, created_at: float) -> float:
    """Fraction of node lifetime remaining in [0,1]; scales disruption cost
    toward zero as a node approaches expiry."""
    if not expire_after or expire_after == float("inf"):
        return 1.0
    age = now - created_at
    return max(0.0, min(1.0, (expire_after - age) / expire_after))
