"""Kubernetes resource.Quantity semantics on exact integer milli-units.

The reference manipulates k8s.io/apimachinery resource.Quantity throughout
(e.g. pkg/utils/resources/resources.go). We keep the same observable behavior
(milli precision for divisible resources, binary/decimal SI suffix parsing)
but store a single canonical integer milli-value, which is what the solver's
tensor encoding consumes directly.
"""

from __future__ import annotations

import math
import re
from functools import total_ordering

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"n": 10**-9, "u": 10**-6, "m": 10**-3, "": 1, "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}

_QTY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$")


@total_ordering
class Quantity:
    """An exact resource quantity stored as integer milli-units.

    `Quantity.parse("100m").milli == 100`; `Quantity.parse("2Gi").value == 2**31`.
    Sub-milli parse results round up (a request of 1n still occupies 1m), matching
    the scheduler-visible behavior of MilliValue() in apimachinery.
    """

    __slots__ = ("milli",)

    def __init__(self, milli: int = 0):
        self.milli = int(milli)

    # -- constructors ---------------------------------------------------------
    @classmethod
    def parse(cls, s: "str | int | float | Quantity") -> "Quantity":
        if isinstance(s, Quantity):
            return cls(s.milli)
        if isinstance(s, int):
            return cls(s * 1000)
        if isinstance(s, float):
            return cls(math.ceil(s * 1000))
        s = s.strip()
        m = _QTY_RE.match(s)
        if not m:
            raise ValueError(f"cannot parse quantity {s!r}")
        num, suffix = m.groups()
        suffix = suffix or ""
        if suffix in _BINARY:
            scale = _BINARY[suffix]
        else:
            scale = _DECIMAL[suffix]
        # exact integer fast path
        try:
            base = int(num)
            if isinstance(scale, int):
                return cls(base * scale * 1000)
        except ValueError:
            pass
        val = float(num) * float(scale)
        return cls(math.ceil(val * 1000 - 1e-9))

    @classmethod
    def from_milli(cls, milli: int) -> "Quantity":
        return cls(milli)

    @classmethod
    def from_value(cls, value: "int | float") -> "Quantity":
        return cls(math.ceil(value * 1000 - 1e-9) if isinstance(value, float) else value * 1000)

    # -- accessors ------------------------------------------------------------
    @property
    def value(self) -> int:
        """Whole-unit value, rounded up (apimachinery Value() semantics)."""
        return -((-self.milli) // 1000)

    def as_float(self) -> float:
        return self.milli / 1000.0

    def is_zero(self) -> bool:
        return self.milli == 0

    # -- arithmetic -----------------------------------------------------------
    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli + other.milli)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli - other.milli)

    def __mul__(self, k: "int | float") -> "Quantity":
        return Quantity(math.ceil(self.milli * k - 1e-9)) if isinstance(k, float) else Quantity(self.milli * k)

    __rmul__ = __mul__

    def __neg__(self) -> "Quantity":
        return Quantity(-self.milli)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Quantity) and self.milli == other.milli

    def __lt__(self, other: "Quantity") -> bool:
        return self.milli < other.milli

    def __hash__(self) -> int:
        return hash(self.milli)

    def __bool__(self) -> bool:
        return self.milli != 0

    # -- formatting -----------------------------------------------------------
    def __str__(self) -> str:
        if self.milli % 1000 == 0:
            v = self.milli // 1000
            for suffix, scale in (("Ei", 1024**6), ("Pi", 1024**5), ("Ti", 1024**4), ("Gi", 1024**3), ("Mi", 1024**2), ("Ki", 1024)):
                if v != 0 and v % scale == 0 and abs(v) >= scale:
                    return f"{v // scale}{suffix}"
            return str(v)
        return f"{self.milli}m"

    def __repr__(self) -> str:
        return f"Quantity({self})"


ZERO = Quantity(0)


def parse(s) -> Quantity:
    return Quantity.parse(s)
