"""Fixed-capacity ring buffer (reference: pkg/utils/ringbuffer/ringbuffer.go)."""

from __future__ import annotations

from typing import Generic, TypeVar

T = TypeVar("T")


class RingBuffer(Generic[T]):
    def __init__(self, capacity: int):
        self._capacity = capacity
        self._items: list[T] = []
        self._head = 0  # insert position once full

    def insert(self, item: T) -> None:
        if len(self._items) < self._capacity:
            self._items.append(item)
            return
        self._items[self._head] = item
        self._head = (self._head + 1) % self._capacity

    def items(self) -> list[T]:
        """Chronological order, oldest first (once full, _head is the oldest)."""
        if len(self._items) < self._capacity:
            return list(self._items)
        return self._items[self._head :] + self._items[: self._head]

    def __len__(self) -> int:
        return len(self._items)

    def reset(self) -> None:
        self._items.clear()
        self._head = 0
