"""ResourceList arithmetic: Merge / Subtract / Fits and pod request extraction.

Mirrors the behavior of the reference's pkg/utils/resources/resources.go
(Merge, Subtract, Fits, RequestsForPods, Cmp) over plain dicts of
resource-name -> Quantity. These dicts are the host-side exact form; the
solver lowers them to dense float tensors (see karpenter_tpu/solver/encode.py).
"""

from __future__ import annotations

from typing import Iterable, Mapping

from .quantity import Quantity

# Canonical k8s resource names the framework treats specially.
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"

ResourceList = dict  # str -> Quantity


def parse_resource_list(d: Mapping[str, object] | None) -> ResourceList:
    return {k: Quantity.parse(v) for k, v in (d or {}).items()}


def merge(*lists: Mapping[str, Quantity] | None) -> ResourceList:
    """Sum resource lists key-wise (reference: resources.go Merge)."""
    out: ResourceList = {}
    for rl in lists:
        if not rl:
            continue
        for k, v in rl.items():
            out[k] = out.get(k, Quantity(0)) + v
    return out


def subtract(a: Mapping[str, Quantity], b: Mapping[str, Quantity] | None) -> ResourceList:
    """a - b key-wise; keys only in b appear negated (reference: resources.go Subtract)."""
    out: ResourceList = {k: Quantity(v.milli) for k, v in a.items()}
    for k, v in (b or {}).items():
        out[k] = out.get(k, Quantity(0)) - v
    return out


def fits(candidate: Mapping[str, Quantity], total: Mapping[str, Quantity]) -> bool:
    """True iff candidate <= total for every resource candidate requests.

    A resource absent from total is treated as zero capacity
    (reference: resources.go Fits -> Cmp <= 0 for each candidate entry).
    """
    for k, v in candidate.items():
        if v.milli > total.get(k, Quantity(0)).milli:
            return False
    return True


def any_exceeds(candidate: Mapping[str, Quantity], total: Mapping[str, Quantity]) -> list[str]:
    """Names of resources where candidate > total (for error reporting)."""
    return [k for k, v in candidate.items() if v.milli > total.get(k, Quantity(0)).milli]


def is_zero(rl: Mapping[str, Quantity]) -> bool:
    return all(v.is_zero() for v in rl.values())


def max_resources(*lists: Mapping[str, Quantity] | None) -> ResourceList:
    """Key-wise max (used for init-container request semantics)."""
    out: ResourceList = {}
    for rl in lists:
        if not rl:
            continue
        for k, v in rl.items():
            if k not in out or v.milli > out[k].milli:
                out[k] = Quantity(v.milli)
    return out


def pod_requests(pod) -> ResourceList:
    """Effective scheduling requests of a pod, sidecar-aware (KEP-753), plus
    overhead and an implicit pods:1.

    Matches k8s resourcehelper.PodRequests as used by the reference
    (resources.go:115-126): init containers run sequentially, but restartable
    ("sidecar") init containers keep running, so

        effective = max( sum(main) + sum(sidecars),
                         max over non-sidecar init i of
                           (request_i + sum(sidecars started before i)) )
    """
    main = merge(*[c.resources.get("requests", {}) for c in pod.spec.containers])
    sidecar_running: ResourceList = {}
    init_peak: ResourceList = {}
    for c in pod.spec.init_containers:
        req = c.resources.get("requests", {})
        if c.is_sidecar():
            sidecar_running = merge(sidecar_running, req)
        else:
            init_peak = max_resources(init_peak, merge(sidecar_running, req))
    out = max_resources(merge(main, sidecar_running), init_peak)
    if pod.spec.overhead:
        out = merge(out, pod.spec.overhead)
    out[PODS] = out.get(PODS, Quantity(0)) + Quantity.parse(1)
    return out


def pod_limits(pod) -> ResourceList:
    main = merge(*[c.resources.get("limits", {}) for c in pod.spec.containers])
    sidecar_running: ResourceList = {}
    init_peak: ResourceList = {}
    for c in pod.spec.init_containers:
        lim = c.resources.get("limits", {})
        if c.is_sidecar():
            sidecar_running = merge(sidecar_running, lim)
        else:
            init_peak = max_resources(init_peak, merge(sidecar_running, lim))
    return max_resources(merge(main, sidecar_running), init_peak)


def requests_for_pods(pods: Iterable) -> ResourceList:
    return merge(*[pod_requests(p) for p in pods])


def cmp_resources(a: Mapping[str, Quantity], b: Mapping[str, Quantity]) -> int:
    """-1 if a strictly fits in b on all keys with some slack, else comparison helper."""
    fits_ab = fits(a, b)
    fits_ba = fits(b, a)
    if fits_ab and not fits_ba:
        return -1
    if fits_ba and not fits_ab:
        return 1
    return 0


def to_float_dict(rl: Mapping[str, Quantity]) -> dict[str, float]:
    return {k: v.as_float() for k, v in rl.items()}


def fmt(rl: Mapping[str, Quantity]) -> str:
    return ", ".join(f"{k}: {v}" for k, v in sorted(rl.items()))
