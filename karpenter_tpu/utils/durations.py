"""Go-style duration strings ("1h30m", "10s", "Never") and a minimal
standard-cron engine for disruption-budget schedules (nodepool.go:406-421).
"""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone

_DUR_RE = re.compile(r"(\d+)(h|m|s)")

NEVER = float("inf")


def parse_duration(s: str | float | int | None) -> float | None:
    """Parse "1h30m10s" to seconds; "Never" -> inf; None passes through."""
    if s is None:
        return None
    if isinstance(s, (int, float)):
        return float(s)
    if s == "Never":
        return NEVER
    total = 0.0
    pos = 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        n, unit = int(m.group(1)), m.group(2)
        total += n * {"h": 3600, "m": 60, "s": 1}[unit]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"invalid duration {s!r}")
    return total


_MACROS = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@annually": "0 0 1 1 *",
    "@yearly": "0 0 1 1 *",
}


class Cron:
    """Standard 5-field cron matcher (UTC), enough for budget schedules."""

    def __init__(self, expr: str):
        expr = _MACROS.get(expr.strip(), expr.strip())
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"invalid cron {expr!r}")
        ranges = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]
        self.sets = [self._parse_field(f, lo_, hi_) for f, (lo_, hi_) in zip(fields, ranges)]
        self.dom_star = fields[2] == "*"
        self.dow_star = fields[4] == "*"

    @staticmethod
    def _parse_field(field: str, lo_: int, hi_: int) -> set[int]:
        out: set[int] = set()
        for part in field.split(","):
            step = 1
            if "/" in part:
                part, step_s = part.split("/", 1)
                step = int(step_s)
            if part in ("*", ""):
                a, b = lo_, hi_
            elif "-" in part:
                a_s, b_s = part.split("-", 1)
                a, b = int(a_s), int(b_s)
            else:
                a = b = int(part)
            for v in range(a, b + 1, step):
                if v == 7 and lo_ == 0 and hi_ == 6:
                    v = 0  # Sunday may be 7
                if lo_ <= v <= hi_:
                    out.add(v)
        if not out:
            raise ValueError(f"empty cron field {field!r}")
        return out

    def matches(self, t: datetime) -> bool:
        minute, hour, dom, month, dow = self.sets
        if t.minute not in minute or t.hour not in hour or t.month not in month:
            return False
        dom_ok = t.day in dom
        dow_ok = t.isoweekday() % 7 in dow
        # standard cron: if both dom and dow are restricted, either may match
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def active_within(self, now: float, duration_s: float) -> bool:
        """True if any schedule hit occurred in [now - duration, now] (UTC).

        Mirrors Budget.IsActive (nodepool.go:412-430): walk back the duration
        and check whether the schedule fired inside the window.
        """
        end = datetime.fromtimestamp(now, tz=timezone.utc).replace(second=0, microsecond=0)
        steps = int(duration_s // 60) + 1
        t = end
        for _ in range(steps):
            if self.matches(t):
                return True
            t -= timedelta(minutes=1)
        return False
