"""Masked selection kernels for the greedy packer."""

from __future__ import annotations

import jax.numpy as jnp

BIG = jnp.float32(3.4e38)


def first_true_index(mask):
    """Lowest index where mask is True, else -1 (first-fit order)."""
    n = mask.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    cand = jnp.where(mask, idx, n)
    best = jnp.min(cand)
    return jnp.where(best < n, best, -1).astype(jnp.int32)


def masked_argmin(values, mask):
    """Index of the minimum value among mask==True (ties -> lowest index),
    else -1."""
    n = values.shape[0]
    v = jnp.where(mask, values, BIG)
    best = jnp.argmin(v)  # argmin returns first occurrence on ties
    return jnp.where(mask[best], best.astype(jnp.int32), jnp.int32(-1))
