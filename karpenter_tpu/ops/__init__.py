"""Low-level JAX kernels: packed bitsets, masked argmin/first-fit selection."""

from .bitset import pack_bool_masks, test_bit  # noqa: F401
from .select import first_true_index, masked_argmin  # noqa: F401
