"""Packed uint32 bitset kernels.

A Requirement is a membership mask over an interned value vocabulary
(SURVEY.md §7 stage 1); we store masks packed 32 values per uint32 lane so a
pod's full requirement set is a [K, W] uint32 block and membership tests are
gather + shift on the VPU.
"""

from __future__ import annotations

import numpy as np

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover - host-only paths  # solverlint: ok(swallowed-exception): import guard — jnp=None routes every caller to the numpy arm
    jnp = None


def words_for(n_values: int) -> int:
    return max(1, (n_values + 31) // 32)


def pack_bool_masks(bools: np.ndarray) -> np.ndarray:
    """[..., V] bool -> [..., ceil(V/32)] uint32 (little-endian bit order)."""
    *lead, v = bools.shape
    w = words_for(v)
    padded = np.zeros((*lead, w * 32), dtype=bool)
    padded[..., :v] = bools
    r = padded.reshape(*lead, w, 32)
    weights = (1 << np.arange(32, dtype=np.uint64)).astype(np.uint64)
    packed = (r.astype(np.uint64) * weights).sum(axis=-1)
    return packed.astype(np.uint32)


def test_bit(masks, idx):
    """masks: [..., W] uint32; idx: [...] int32 value ids -> [...] bool.

    Gathers the word then tests the bit; idx < 0 returns False.
    """
    word_idx = jnp.clip(idx // 32, 0, masks.shape[-1] - 1)
    bit_idx = (idx % 32).astype(jnp.uint32)
    words = jnp.take_along_axis(masks, word_idx[..., None].astype(jnp.int32), axis=-1)[..., 0]
    hit = (words >> bit_idx) & jnp.uint32(1)
    return jnp.where(idx >= 0, hit.astype(bool), False)
