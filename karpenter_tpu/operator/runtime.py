"""Environment: the full wiring of the control plane, plus a deterministic
tick() driver.

Plays the role of the reference's NewOperator + controller manager
(operator.go:126-252 and controllers.go:87-196), but clock-driven: tests and
the simulation harness advance time explicitly and call tick(), which runs one
round of every controller in dependency order. A wall-clock run loop is a
thin loop over tick() + clock sleeps.
"""

from __future__ import annotations

from ..apis.kwoknodeclass import KWOKNodeClass
from ..cloudprovider import catalog
from ..cloudprovider.kwok import KWOKCloudProvider
from ..cloudprovider.metrics import MetricsCloudProvider
from ..cloudprovider.overlay import OverlayCloudProvider
from ..controllers.disruption import DisruptionController
from ..controllers.nodeclaim.consistency import ConsistencyController
from ..controllers.nodeclaim.disruption import NodeClaimDisruptionController
from ..controllers.nodeclaim.expiration import ExpirationController
from ..controllers.nodeclaim.hydration import HydrationController
from ..controllers.nodeclaim.podevents import PodEventsController
from ..controllers.node.health import HealthController
from ..controllers.node.termination import TerminationController
from ..controllers.nodeclaim.garbagecollection import GarbageCollectionController
from ..controllers.nodeclaim.lifecycle import LifecycleController
from ..controllers.nodepool import (
    NodePoolCounterController,
    NodePoolHashController,
    NodePoolReadinessController,
    NodePoolRegistrationHealthController,
    NodePoolValidationController,
)
from ..controllers.nodeoverlay import InstanceTypeStore, NodeOverlayController
from ..controllers.provisioning.provisioner import Provisioner, ProvisionerOptions
from ..controllers.capacitybuffer import CapacityBufferController
from ..controllers.dynamicresources import DeviceAllocationController, DRAKwokDriver
from ..controllers.static import StaticDeprovisioningController, StaticProvisioningController
from ..controllers.metrics import (
    NodeMetricsController,
    NodePoolMetricsController,
    PodMetricsController,
)
from ..events import Recorder
from ..kube import Store
from ..kube.binder import Binder
from ..kube.daemonsets import DaemonSetRunner
from ..metrics import make_registry
from ..solver import FFDSolver
from ..state import Cluster
from ..state.cost import ClusterCost, PricingController, start_cost_informer
from ..state.informer import start_informers
from ..state.nodepoolhealth import NodePoolHealthState
from ..utils.clock import Clock, FakeClock
from .options import Options


class Environment:
    """A fully wired in-process cluster + Karpenter control plane."""

    def __init__(self, options: Options | None = None, clock=None, cloud_provider=None, instance_types=None, store=None, registration_hooks=None, registry=None):
        """`store` lets a second Environment attach to an existing cluster
        (active/standby takeover tests): informers seed the fresh in-memory
        mirror from the shared store's current content, exactly like a new
        leader warming its caches (operator.go:196-201). `registry` lets the
        fleet front-end share ONE metrics registry across its per-tenant
        environments (per-tenant series split on the bounded `tenant`
        label); default is a private registry per environment."""
        self.options = options or Options()
        self.clock = clock or FakeClock()
        self.registry = registry if registry is not None else make_registry()
        # solvetrace flight recorder backing /debug/solves — the process-wide
        # default, so every solver this environment (or a test beside it)
        # runs is visible from the operator's debug surface
        from ..obs.trace import default_recorder

        self.trace_recorder = default_recorder()
        # podtrace event-lifecycle tracer backing /debug/events (obs/
        # podtrace.py, default-on via KARPENTER_PODTRACE): stamped into the
        # store's delivery seam below and into the provisioner after it is
        # built. Per-environment (= per-tenant in fleet mode; the fleet
        # relabels it at session registration).
        from ..obs.podtrace import PodTracer

        self.podtracer = PodTracer(registry=self.registry)
        self.recorder = Recorder(self.clock)
        self.store = store if store is not None else Store(clock=self.clock)
        if self.podtracer.enabled:
            self.store.set_event_tracer(self.podtracer)
        self.cluster = Cluster(self.store, self.clock)
        start_informers(self.store, self.cluster)

        if cloud_provider is not None:
            base_cloud_provider = cloud_provider
        else:
            its = instance_types if instance_types is not None else catalog.construct_instance_types()
            if self.store.try_get("KWOKNodeClass", KWOKNodeClass().metadata.name) is None:
                self.store.create(KWOKNodeClass())
            base_cloud_provider = KWOKCloudProvider(self.store, its, clock=self.clock)
        # decorator stack (kwok/main.go:36-37 + cloudprovider/metrics): the
        # overlay controller reads the undecorated provider; everyone else the
        # overlay+metrics-decorated one
        self.base_cloud_provider = base_cloud_provider
        self.instance_type_store = InstanceTypeStore()
        self.cloud_provider = MetricsCloudProvider(
            OverlayCloudProvider(base_cloud_provider, self.instance_type_store, self.options), self.registry
        )
        self.nodeoverlay = NodeOverlayController(
            self.store, base_cloud_provider, self.instance_type_store, self.cluster, self.clock,
            options=self.options,
        )

        self.cluster_cost = ClusterCost(self.store, self.cloud_provider, metrics=self.registry)
        start_cost_informer(self.store, self.cluster_cost)
        self.pricing = PricingController(self.store, self.cloud_provider, self.cluster_cost, self.clock)

        solver = self._make_solver()
        self.provisioner = Provisioner(
            self.store,
            self.cluster,
            self.cloud_provider,
            self.clock,
            solver=solver,
            recorder=self.recorder,
            metrics=self.registry,
            options=ProvisionerOptions(
                preference_policy=self.options.preference_policy,
                min_values_policy=self.options.min_values_policy,
                batch_idle_seconds=self.options.batch_idle_duration,
                batch_max_seconds=self.options.batch_max_duration,
                capacity_buffer_enabled=self.options.feature_gates.capacity_buffer,
                dynamic_resources_enabled=self.options.feature_gates.dynamic_resources,
                reserved_capacity_enabled=self.options.feature_gates.reserved_capacity,
            ),
        )
        self.provisioner.podtracer = self.podtracer
        self.device_allocation = DeviceAllocationController(self.store, self.cluster, self.clock)
        self.dra_kwok_driver = DRAKwokDriver(self.store)
        self.capacity_buffer = CapacityBufferController(self.store, self.clock, provisioner=self.provisioner)
        self.static_provisioning = StaticProvisioningController(
            self.store, self.cluster, self.cloud_provider, self.provisioner, self.clock, metrics=self.registry
        )
        self.static_deprovisioning = StaticDeprovisioningController(
            self.store, self.cluster, self.cloud_provider, self.clock, recorder=self.recorder, metrics=self.registry
        )
        self.np_state = NodePoolHealthState()
        self.lifecycle = LifecycleController(
            self.store, self.cluster, self.cloud_provider, self.clock,
            recorder=self.recorder, np_state=self.np_state, metrics=self.registry,
            registration_hooks=registration_hooks,
        )
        self.gc = GarbageCollectionController(self.store, self.cluster, self.cloud_provider, self.clock)
        self.binder = Binder(self.store, self.cluster, self.clock, dra_enabled=self.options.feature_gates.dynamic_resources)
        self.daemonset_runner = DaemonSetRunner(self.store, self.clock)
        self.termination = TerminationController(
            self.store, self.cluster, self.cloud_provider, self.clock,
            recorder=self.recorder, metrics=self.registry,
        )
        self.health = HealthController(
            self.store, self.cluster, self.cloud_provider, self.clock,
            recorder=self.recorder, metrics=self.registry,
            enabled=self.options.feature_gates.node_repair,
        )
        self.nodeclaim_disruption = NodeClaimDisruptionController(self.store, self.cluster, self.cloud_provider, self.clock)
        self.disruption = DisruptionController(
            self.store, self.cluster, self.provisioner, self.cloud_provider, self.clock, self.options,
            recorder=self.recorder, metrics=self.registry, cluster_cost=self.cluster_cost,
        )
        self.expiration = ExpirationController(self.store, self.clock, metrics=self.registry)
        self.consistency = ConsistencyController(self.store, self.clock, recorder=self.recorder)
        self.hydration = HydrationController(self.store)
        self.podevents = PodEventsController(self.store, self.clock)
        self.podevents.register()
        self.nodepool_hash = NodePoolHashController(self.store)
        self.nodepool_counter = NodePoolCounterController(self.store, self.cluster)
        self.nodepool_readiness = NodePoolReadinessController(self.store, self.clock)
        self.nodepool_registration_health = NodePoolRegistrationHealthController(self.store, self.np_state, self.clock)
        self.nodepool_validation = NodePoolValidationController(self.store, self.clock)
        self.pod_metrics = PodMetricsController(self.store, self.clock, self.registry)
        self.node_metrics = NodeMetricsController(self.store, self.cluster, self.clock, self.registry)
        self.nodepool_metrics = NodePoolMetricsController(self.store, self.registry, cluster_cost=self.cluster_cost)
        self.extra_controllers: list = []  # later controllers appended as built

        # pod and node watches trigger the provisioner batcher (the reference's
        # provisioning pod/node trigger controllers, state informer §3.5); the
        # node trigger also closes the gap between a headroom node registering
        # and the pass that records its buffer pods
        self.store.watch("Pod", lambda e, p: self.provisioner.trigger(p.metadata.uid) if e != "DELETED" else None)  # solverlint: ok(thread-escape): delegates straight to Batcher.trigger, whose state is lock-guarded; captures nothing mutable of its own
        self.store.watch("Node", lambda e, n: self.provisioner.trigger(n.metadata.uid) if e != "DELETED" else None)  # solverlint: ok(thread-escape): delegates straight to Batcher.trigger, whose state is lock-guarded; captures nothing mutable of its own

        # racecheck (obs/racecheck.py): under KARPENTER_SOLVER_RACECHECK=1
        # the instrumented locks publish their wait-time histogram to this
        # environment's registry (one env per operator process)
        from ..obs import racecheck

        if racecheck.racecheck_enabled():
            racecheck.set_metrics_registry(self.registry)

    def _make_solver(self):
        if self.options.solver_backend == "tpu":
            from ..solver.tpu import TPUSolver

            return TPUSolver(registry=self.registry)
        return FFDSolver()

    # -- deterministic driver --------------------------------------------------
    def tick(self, provision_force: bool = False, provision: bool = True) -> None:
        """One controller round: provision -> launch/register/init -> bind.
        `provision=False` skips the provisioner reconcile — fleet mode runs
        controller rounds on the operator thread while ALL solves stay on
        the fleet serve loop (one solver, one thread: the provisioner's
        encode caches and device-resident carry are single-threaded by
        design, the same contract ServingLoop relies on)."""
        if hasattr(self.cloud_provider, "flush_pending"):
            self.cloud_provider.flush_pending()
        self.nodeoverlay.reconcile()
        self.nodepool_hash.reconcile()
        self.nodepool_validation.reconcile()
        self.nodepool_registration_health.reconcile()
        self.nodepool_readiness.reconcile()
        if self.options.feature_gates.capacity_buffer:
            self.capacity_buffer.reconcile()
        self.static_provisioning.reconcile()
        self.static_deprovisioning.reconcile()
        if provision:
            self.provisioner.reconcile(force=provision_force)
        self.lifecycle.reconcile_all()
        if hasattr(self.cloud_provider, "flush_pending"):
            self.cloud_provider.flush_pending()
        self.lifecycle.reconcile_all()
        self.termination.reconcile()
        self.lifecycle.reconcile_all()  # claims whose node finished draining release
        self.gc.reconcile()
        if self.options.feature_gates.dynamic_resources:
            self.dra_kwok_driver.reconcile()
        # the DaemonSet controller stand-in materializes daemon pods on
        # registered nodes BEFORE the binder pass, so the binder's NodePorts
        # and resource checks see them like the real kube-scheduler would
        self.daemonset_runner.reconcile()
        self.binder.bind_all()
        if self.options.feature_gates.dynamic_resources:
            self.device_allocation.reconcile()
        self.nodepool_counter.reconcile()
        self.hydration.reconcile()
        self.consistency.reconcile()
        self.expiration.reconcile()
        self.health.reconcile()
        self.nodeclaim_disruption.reconcile()
        self.disruption.reconcile()
        self.pricing.reconcile()
        self.pod_metrics.reconcile()
        self.node_metrics.reconcile()
        self.nodepool_metrics.reconcile()
        from .. import metrics as m

        self.registry.gauge(m.CLUSTER_STATE_SYNCED).set(1.0 if self.cluster.synced() else 0.0)
        self.registry.gauge(m.CLUSTER_STATE_NODE_COUNT).set(len(self.cluster.nodes()))
        for c in self.extra_controllers:
            c.reconcile()

    def settle(self, rounds: int = 10, step_seconds: float = 2.0) -> None:
        """Advance time and tick until quiet (or rounds exhausted)."""
        for _ in range(rounds):
            if isinstance(self.clock, FakeClock):
                self.clock.step(step_seconds)
            self.tick(provision_force=True)

    # -- wall-clock operation (operator.Start + manager run loop) --------------
    def run(self, stop_event=None, tick_seconds: float = 1.0, leader_election: bool = True, identity: str = "") -> None:
        """The standby-capable run loop: informers are live from construction
        (controller warmup, operator.go:196-201); controller rounds execute
        only while holding the leader lease, which a background thread renews
        every retry_period so a long reconcile round can't starve the lease
        into a spurious takeover. Blocks until stop_event is set."""
        import uuid as _uuid

        from ..obs.racecheck import make_event, spawn_thread
        from .leaderelection import LeaderElector

        if isinstance(self.clock, FakeClock):
            raise ValueError("Environment.run drives wall-clock time; construct with clock=Clock() (FakeClock never advances here)")
        stop_event = stop_event or make_event()
        elector = None
        renewer = None
        if leader_election:
            elector = LeaderElector(self.store, self.clock, identity or f"karpenter-{_uuid.uuid4().hex[:8]}")
            renewer = spawn_thread(elector.renew_loop, name="karpenter-lease-renewer", args=(stop_event,))
        try:
            while not stop_event.is_set():
                if elector is None or elector.is_leader():
                    self.tick()
                stop_event.wait(tick_seconds)
        finally:
            # stop the renew thread BEFORE releasing: a live renewer would
            # immediately re-acquire the just-released lease (holder "" reads
            # as lapsed), blocking standby takeover while this process lingers
            stop_event.set()
            if elector is not None:
                if renewer is not None:
                    renewer.join(timeout=5)
                elector.release()
