"""Operator runtime: wiring of store, state, controllers, and providers."""

from .runtime import Environment  # noqa: F401
