"""Global options + feature gates (reference: pkg/operator/options/options.go:68-135).

Flag/env parsing collapses to a dataclass; controllers receive it explicitly
instead of via context injection. The full operational surface is mirrored —
service/ports, client QPS/burst, profiling, warmup/leader-election toggles,
observability switch, resource hints, log configuration — alongside the
scheduler knobs and the 7 feature gates. `from_env` honors the reference's
environment-variable fallbacks; `from_args` parses the reference's flag names.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    node_repair: bool = False
    reserved_capacity: bool = True
    spot_to_spot_consolidation: bool = False
    node_overlay: bool = False
    static_capacity: bool = False
    capacity_buffer: bool = False
    dynamic_resources: bool = False


@dataclass
class Options:
    # scheduler knobs (options.go:85-91)
    batch_max_duration: float = 10.0
    batch_idle_duration: float = 1.0
    preference_policy: str = "Respect"  # Respect | Ignore
    min_values_policy: str = "Strict"  # Strict | BestEffort
    solver_backend: str = "ffd"  # ffd | tpu (the Solver plugin point)
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    # operational surface (options.go:69-84)
    service_name: str = ""
    metrics_port: int = 8080
    health_probe_port: int = 8081
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    enable_profiling: bool = False
    disable_controller_warmup: bool = True
    disable_leader_election: bool = False
    disable_cluster_state_observability: bool = False
    leader_election_name: str = "karpenter-leader-election"
    leader_election_namespace: str = ""
    memory_limit: int = -1  # bytes; <0 = unset
    cpu_requests: int = 1000  # millicores; drives solver/provisioner fan-out
    log_level: str = "info"  # debug | info | error
    log_output_paths: str = "stdout"
    log_error_output_paths: str = "stderr"
    # NOTE mirrors the reference's transitional flag (removed once DRA is GA)
    ignore_dra_requests: bool = True

    def validate(self) -> list[str]:
        """Misconfigurations fail closed with messages (options.go Parse)."""
        errs = []
        if self.preference_policy not in ("Respect", "Ignore"):
            errs.append(f"preference-policy must be Respect or Ignore, got {self.preference_policy!r}")
        if self.min_values_policy not in ("Strict", "BestEffort"):
            errs.append(f"min-values-policy must be Strict or BestEffort, got {self.min_values_policy!r}")
        if self.log_level not in ("debug", "info", "error"):
            errs.append(f"log-level must be debug, info or error, got {self.log_level!r}")
        if self.solver_backend not in ("ffd", "tpu"):
            errs.append(f"solver-backend must be ffd or tpu, got {self.solver_backend!r}")
        if self.batch_idle_duration < 0 or self.batch_max_duration < 0:
            errs.append("batch windows must be non-negative")
        for name, port in (("metrics-port", self.metrics_port), ("health-probe-port", self.health_probe_port)):
            if not 0 <= port <= 65535:
                errs.append(f"{name} must be 0-65535, got {port}")
        return errs

    @classmethod
    def from_env(cls) -> "Options":
        o = cls()
        o.batch_max_duration = float(os.environ.get("BATCH_MAX_DURATION", o.batch_max_duration))
        o.batch_idle_duration = float(os.environ.get("BATCH_IDLE_DURATION", o.batch_idle_duration))
        o.preference_policy = os.environ.get("PREFERENCE_POLICY", o.preference_policy)
        o.min_values_policy = os.environ.get("MIN_VALUES_POLICY", o.min_values_policy)
        o.solver_backend = os.environ.get("SOLVER_BACKEND", o.solver_backend)
        o.service_name = os.environ.get("KARPENTER_SERVICE", o.service_name)
        o.metrics_port = _env_int("METRICS_PORT", o.metrics_port)
        o.health_probe_port = _env_int("HEALTH_PROBE_PORT", o.health_probe_port)
        o.kube_client_qps = _env_int("KUBE_CLIENT_QPS", o.kube_client_qps)
        o.kube_client_burst = _env_int("KUBE_CLIENT_BURST", o.kube_client_burst)
        o.enable_profiling = _env_bool("ENABLE_PROFILING", o.enable_profiling)
        o.disable_controller_warmup = _env_bool("DISABLE_CONTROLLER_WARMUP", o.disable_controller_warmup)
        o.disable_leader_election = _env_bool("DISABLE_LEADER_ELECTION", o.disable_leader_election)
        o.disable_cluster_state_observability = _env_bool(
            "DISABLE_CLUSTER_STATE_OBSERVABILITY", o.disable_cluster_state_observability
        )
        o.leader_election_name = os.environ.get("LEADER_ELECTION_NAME", o.leader_election_name)
        o.leader_election_namespace = os.environ.get("LEADER_ELECTION_NAMESPACE", o.leader_election_namespace)
        o.memory_limit = _env_int("MEMORY_LIMIT", o.memory_limit)
        o.cpu_requests = _env_int("CPU_REQUESTS", o.cpu_requests)
        o.log_level = os.environ.get("LOG_LEVEL", o.log_level)
        o.log_output_paths = os.environ.get("LOG_OUTPUT_PATHS", o.log_output_paths)
        o.log_error_output_paths = os.environ.get("LOG_ERROR_OUTPUT_PATHS", o.log_error_output_paths)
        o.ignore_dra_requests = _env_bool("IGNORE_DRA_REQUESTS", o.ignore_dra_requests)
        _apply_gates(o.feature_gates, os.environ.get("FEATURE_GATES", ""))
        return o

    @classmethod
    def from_args(cls, argv: list[str]) -> "Options":
        """Parse the reference's flag names (options.go AddFlags) on top of the
        environment fallbacks; flags win over env, env wins over defaults.
        Bool flags accept Go's bare form (`--enable-profiling`) and explicit
        values; unknown flags FAIL CLOSED with a message, like the
        reference's flag.FlagSet (provider injectables register their flags
        on the same parser in the reference, they don't bypass it)."""
        import argparse

        o = cls.from_env()
        # Go's flag package accepts single-dash flags; normalize to two
        # (only tokens that look like flags — a negative value such as
        # `--memory-limit -100` must pass through untouched, as Go's flag
        # package accepts the space-separated form)
        argv = [
            "-" + a if a.startswith("-") and not a.startswith("--") and len(a) > 2 and a[1].isalpha() else a
            for a in argv
        ]
        parser = argparse.ArgumentParser(add_help=False, allow_abbrev=False)
        for flag, (attr, conv) in _FLAG_TABLE.items():
            if conv is _parse_bool:
                # Go flag semantics: bare --flag means true
                parser.add_argument("--" + flag, nargs="?", const="true", default=None)
            else:
                parser.add_argument("--" + flag, default=None)
        parser.add_argument("--feature-gates", default=None)
        ns, unknown = parser.parse_known_args(argv)
        # fail closed on any stray dash token (including `-100` whose flag was
        # forgotten — Go errors with 'flag provided but not defined')
        bad = [a for a in unknown if a.startswith("-")]
        if bad:
            raise ValueError(f"unknown flags: {', '.join(bad)}")
        for flag, (attr, conv) in _FLAG_TABLE.items():
            value = getattr(ns, flag.replace("-", "_"))
            if value is None:
                continue
            try:
                setattr(o, attr, conv(value))
            except ValueError as e:
                raise ValueError(f"--{flag}: {e}") from None
        if ns.feature_gates is not None:
            _apply_gates(o.feature_gates, ns.feature_gates)
        errs = o.validate()
        if errs:
            raise ValueError("; ".join(errs))
        return o


_TRUE_WORDS = {"1", "t", "true"}
_FALSE_WORDS = {"0", "f", "false"}


def _env_bool(name: str, default: bool) -> bool:
    """Go strconv.ParseBool semantics, failing closed with the variable name
    on anything else."""
    v = os.environ.get(name)
    if v is None:
        return default
    lv = v.strip().lower()
    if lv in _TRUE_WORDS:
        return True
    if lv in _FALSE_WORDS:
        return False
    raise ValueError(f"{name}={v!r} is not a valid boolean")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name}={v!r} is not a valid integer") from None


def _parse_bool(v: str) -> bool:
    """Go strconv.ParseBool forms (1/t/true, 0/f/false)."""
    lv = v.strip().lower()
    if lv in _TRUE_WORDS:
        return True
    if lv in _FALSE_WORDS:
        return False
    raise ValueError(f"{v!r} is not a valid value, must be a boolean")


def _parse_seconds(v: str) -> float:
    """Accept Go-style durations ('10s', '1m') or plain seconds."""
    from ..utils.durations import parse_duration

    try:
        return float(v)
    except ValueError:
        return parse_duration(v)


def _apply_gates(gates: FeatureGates, spec: str) -> None:
    for item in spec.split(","):
        if "=" in item:
            k, v = item.split("=", 1)
            key = k.strip().replace("-", "_")
            snake = "".join("_" + c.lower() if c.isupper() else c for c in key).lstrip("_")
            if hasattr(gates, snake):
                setattr(gates, snake, v.strip().lower() == "true")


_FLAG_TABLE = {
    "karpenter-service": ("service_name", str),
    "metrics-port": ("metrics_port", int),
    "health-probe-port": ("health_probe_port", int),
    "kube-client-qps": ("kube_client_qps", int),
    "kube-client-burst": ("kube_client_burst", int),
    "enable-profiling": ("enable_profiling", _parse_bool),
    "disable-controller-warmup": ("disable_controller_warmup", _parse_bool),
    "disable-leader-election": ("disable_leader_election", _parse_bool),
    "disable-cluster-state-observability": ("disable_cluster_state_observability", _parse_bool),
    "leader-election-name": ("leader_election_name", str),
    "leader-election-namespace": ("leader_election_namespace", str),
    "memory-limit": ("memory_limit", int),
    "cpu-requests": ("cpu_requests", int),
    "log-level": ("log_level", str),
    "log-output-paths": ("log_output_paths", str),
    "log-error-output-paths": ("log_error_output_paths", str),
    "batch-max-duration": ("batch_max_duration", _parse_seconds),
    "batch-idle-duration": ("batch_idle_duration", _parse_seconds),
    "preference-policy": ("preference_policy", str),
    "min-values-policy": ("min_values_policy", str),
    "solver-backend": ("solver_backend", str),
    "ignore-dra-requests": ("ignore_dra_requests", _parse_bool),
}
