"""Global options + feature gates (reference: pkg/operator/options/options.go:68-135).

Flag/env parsing collapses to a dataclass; controllers receive it explicitly
instead of via context injection.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    node_repair: bool = False
    reserved_capacity: bool = True
    spot_to_spot_consolidation: bool = False
    node_overlay: bool = False
    static_capacity: bool = False
    capacity_buffer: bool = False
    dynamic_resources: bool = False


@dataclass
class Options:
    batch_max_duration: float = 10.0
    batch_idle_duration: float = 1.0
    preference_policy: str = "Respect"  # Respect | Ignore
    min_values_policy: str = "Strict"  # Strict | BestEffort
    solver_backend: str = "ffd"  # ffd | tpu
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    @classmethod
    def from_env(cls) -> "Options":
        o = cls()
        o.batch_max_duration = float(os.environ.get("BATCH_MAX_DURATION", o.batch_max_duration))
        o.batch_idle_duration = float(os.environ.get("BATCH_IDLE_DURATION", o.batch_idle_duration))
        o.preference_policy = os.environ.get("PREFERENCE_POLICY", o.preference_policy)
        o.min_values_policy = os.environ.get("MIN_VALUES_POLICY", o.min_values_policy)
        o.solver_backend = os.environ.get("SOLVER_BACKEND", o.solver_backend)
        gates = os.environ.get("FEATURE_GATES", "")
        for item in gates.split(","):
            if "=" in item:
                k, v = item.split("=", 1)
                key = k.strip().replace("-", "_")
                snake = "".join("_" + c.lower() if c.isupper() else c for c in key).lstrip("_")
                if hasattr(o.feature_gates, snake):
                    setattr(o.feature_gates, snake, v.strip().lower() == "true")
        return o
