"""Lease-based leader election: exactly-one-active controller semantics.

Reference: operator.go:171-202 — controller-runtime's leases resource lock
with release-on-cancel, a dedicated low-QPS leader client (here: the store's
optimistic concurrency IS the rate-independent path), and controller warmup:
informers populate caches before leadership is won so failover is fast.
"""

from __future__ import annotations

from ..kube import Lease, NotFound, ObjectMeta
from ..kube.store import AlreadyExists, Conflict
from ..obs.racecheck import make_lock

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


class LeaderElector:
    # racecheck guarded-field registry: the renew loop runs on its own
    # thread while the controller round reads is_leader() — the pair must
    # change together or a leader can act on a renewed flag with a stale
    # renew timestamp (or vice versa)
    GUARDED_FIELDS = {"_leading": "_lock", "_last_renew": "_lock"}

    def __init__(
        self,
        store,
        clock,
        identity: str,
        lease_name: str = "karpenter-leader-election",
        namespace: str = "kube-system",
        lease_duration: float = DEFAULT_LEASE_DURATION,
        renew_deadline: float = DEFAULT_RENEW_DEADLINE,
        retry_period: float = DEFAULT_RETRY_PERIOD,
    ):
        self.store = store
        self.clock = clock
        self.identity = identity
        self.lease_name = lease_name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._lock = make_lock("leader")
        self._last_renew = 0.0
        self._leading = False

    def is_leader(self) -> bool:
        """Leading AND renewed within the renew deadline — a leader whose
        renewals have been failing must stop acting before a standby can
        legitimately take over (client-go renewDeadline semantics)."""
        now = self.clock.now()
        with self._lock:
            if not self._leading:
                return False
            return now - self._last_renew <= self.renew_deadline

    def renew_loop(self, stop_event) -> None:
        """Background renewal every retry_period, decoupled from controller
        rounds so a long reconcile can't starve the lease into a takeover
        (client-go renews on its own goroutine)."""
        while not stop_event.is_set():
            self.try_acquire_or_renew()
            stop_event.wait(self.retry_period)

    def try_acquire_or_renew(self) -> bool:
        """One election round; returns whether this instance now leads
        (client-go leaderelection tryAcquireOrRenew semantics)."""
        now = self.clock.now()
        try:
            lease = self.store.get("Lease", self.lease_name, self.namespace)
        except NotFound:
            lease = Lease(
                metadata=ObjectMeta(name=self.lease_name, namespace=self.namespace),
                holder_identity=self.identity,
                lease_duration_seconds=int(self.lease_duration),
                acquire_time=now,
                renew_time=now,
            )
            try:
                self.store.create(lease)
                self._set_leading(True, now)
                return True
            except AlreadyExists:  # lost the creation race
                return self._retry_observe()

        expired = now - lease.renew_time > self.lease_duration
        if lease.holder_identity == self.identity:
            return self._renew(lease, now)
        if not expired:
            self._set_leading(False)
            return False
        # takeover: the previous holder's lease lapsed
        def apply(obj):
            if obj.holder_identity != lease.holder_identity or obj.renew_time != lease.renew_time:
                raise Conflict("lease changed under takeover")
            obj.holder_identity = self.identity
            obj.acquire_time = now
            obj.renew_time = now
            obj.lease_transitions += 1

        try:
            self.store.patch("Lease", self.lease_name, apply, namespace=self.namespace, retries=1)
            self._set_leading(True, now)
            return True
        except (Conflict, NotFound):
            self._set_leading(False)
            return False

    def _set_leading(self, leading: bool, renewed_at: float | None = None) -> None:
        with self._lock:
            self._leading = leading
            if renewed_at is not None:
                self._last_renew = renewed_at

    def _renew(self, lease, now: float) -> bool:
        def apply(obj):
            if obj.holder_identity != self.identity:
                raise Conflict("lost leadership")
            obj.renew_time = now

        try:
            self.store.patch("Lease", self.lease_name, apply, namespace=self.namespace, retries=1)
            self._set_leading(True, now)
            return True
        except (Conflict, NotFound):
            self._set_leading(False)
            return False

    def _retry_observe(self) -> bool:
        lease = self.store.try_get("Lease", self.lease_name, self.namespace)
        leading = lease is not None and lease.holder_identity == self.identity
        self._set_leading(leading)
        return leading

    def release(self) -> None:
        """ReleaseOnCancel: fast failover on graceful shutdown. Writes only
        when this instance still holds the lease — a stale loser patching the
        lease could Conflict the new leader's renewal."""
        with self._lock:
            if not self._leading:
                return
            self._leading = False
        current = self.store.try_get("Lease", self.lease_name, self.namespace)
        if current is None or current.holder_identity != self.identity:
            return

        def apply(obj):
            if obj.holder_identity != self.identity:
                raise Conflict("no longer the holder")
            obj.holder_identity = ""
            obj.renew_time = 0.0

        try:
            self.store.patch("Lease", self.lease_name, apply, namespace=self.namespace, retries=1)
        except (Conflict, NotFound):
            pass
