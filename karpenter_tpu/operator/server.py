"""Operator HTTP surface: health probes + Prometheus metrics (+ profiles).

Reference: operator.go:203-219 — metrics server on --metrics-port, healthz/
readyz probes on --health-probe-port, pprof handlers behind
--enable-profiling. Here one threaded stdlib server carries all routes:
/healthz, /readyz, /metrics, /debug/solves (the solvetrace flight-recorder
dump: recent SolveTraces + rolling per-(mode, phase) quantiles, see
obs/trace.py; `?n=<k>` limits to the newest k solves and `?tenant=<label>`
selects a fleet tenant's private recorder), /debug/events (the podtrace
event-lifecycle dump: completed EventRecords with the per-stage e2e
decomposition, SLO budget, and wake-cause split, per tenant — obs/
podtrace.py; same `?n=`/`?tenant=` filters), /debug/tenants (faultline:
per-tenant circuit-breaker state, backoff, last error, and backlog across
every live FleetFrontend — the failure-domain-isolation surface), and
/debug/profile (a py-spy-less stand-in that dumps running thread stacks,
the diagnostic the reference's pprof routes serve in e2e debugging —
karpenter_profiler.go:40-56).
"""

from __future__ import annotations

import json
import sys
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..obs.racecheck import make_lock, spawn_thread


class OperatorServer:
    # racecheck guarded-field registry: start/stop may race (signal handler
    # vs. shutdown path), so the server/thread handles are claimed under a lock
    GUARDED_FIELDS = {"_httpd": "_lock", "_thread": "_lock"}

    def __init__(self, env, port: int = 8080, enable_profiling: bool = False, bind: str = "0.0.0.0", router=None):
        """With `router` (a serving.shard.ShardRouter), this server is the
        fleet-of-fleets AGGREGATION front: /metrics merges every shard's
        fleet families (bounded `shard` label injected), /debug/tenants
        merges shard-stamped rows, /debug/solves|events proxy by ?tenant=
        to the owning shard, /readyz reflects shard breaker health, and
        /debug/shards exposes the router's per-shard breaker rows. `env`
        may be None in router mode (the router has no local tenants)."""
        self.env = env
        self.router = router
        self.port = port
        self.bind = bind  # probes/scrapes come from off-host (operator.go:180-183)
        self.enable_profiling = enable_profiling
        self._lock = make_lock("operator-server")
        self._httpd: ThreadingHTTPServer | None = None
        self._thread = None

    def start(self) -> int:
        env = self.env
        router = self.router
        enable_profiling = self.enable_profiling

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str, ctype: str = "text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/readyz":
                    if router is not None:
                        ready = router.ready()
                        self._send(200 if ready else 503, "ok" if ready else "shard fleet not healthy")
                        return
                    ready = env.cluster.synced()
                    self._send(200 if ready else 503, "ok" if ready else "cluster state not synced")
                elif self.path == "/metrics" and router is not None:
                    # router mode: the shard-merged exposition (every shard's
                    # fleet families with the bounded `shard` label injected)
                    self._send(200, router.merged_metrics(), "text/plain; version=0.0.4")
                elif self.path == "/metrics":
                    # podtrace quantile gauges publish per SCRAPE (sorting
                    # the stage windows rides this handler, never the
                    # serving hot path)
                    from ..obs.podtrace import tenant_surfaces

                    own_tracer = getattr(env, "podtracer", None)
                    if own_tracer is not None:
                        own_tracer.publish_quantiles()
                    for _label, (_rec, tenant_tracer) in tenant_surfaces().items():
                        tenant_tracer.publish_quantiles()
                    self._send(200, env.registry.expose(), "text/plain; version=0.0.4")
                elif router is not None and self.path.split("?", 1)[0] in ("/debug/solves", "/debug/events"):
                    # router mode: proxy the per-tenant dump to the shard
                    # that serves that tenant (?tenant= is REQUIRED — the
                    # router has no local recorder to fall back on)
                    route = self.path.split("?", 1)[0]
                    qs = parse_qs(urlparse(self.path).query)
                    tenant = qs["tenant"][0] if "tenant" in qs else None
                    try:
                        limit = int(qs["n"][0]) if "n" in qs else None
                    except ValueError:
                        self._send(400, "bad ?n= value")
                        return
                    if tenant is None:
                        self._send(400, f"router mode: {route} requires ?tenant=")
                        return
                    try:
                        proxy = router.debug_solves if route == "/debug/solves" else router.debug_events
                        self._send(200, proxy(tenant, n=limit), "application/json")
                    except KeyError:
                        self._send(404, f"unknown tenant {tenant!r}")
                elif self.path.split("?", 1)[0] == "/debug/solves":
                    # served unconditionally (unlike /debug/profile, which the
                    # reference gates behind --enable-profiling): the trace
                    # dump's sensitivity class matches the unauthenticated
                    # /metrics exposition on this same port
                    from ..obs.podtrace import tenant_surfaces
                    from ..obs.trace import default_recorder

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        limit = int(qs["n"][0]) if "n" in qs else None
                    except ValueError:
                        self._send(400, "bad ?n= value")
                        return
                    tenant = qs["tenant"][0] if "tenant" in qs else None
                    if tenant is not None:
                        # per-tenant recorders (fleet mode): resolve through
                        # the podtrace tenant-surface registry
                        surf = tenant_surfaces().get(tenant)
                        if surf is None:
                            self._send(404, f"unknown tenant {tenant!r}")
                            return
                        rec = surf[0]
                    else:
                        rec = getattr(env, "trace_recorder", None) or default_recorder()
                    self._send(200, json.dumps(rec.dump(limit=limit), indent=1), "application/json")
                elif self.path.split("?", 1)[0] == "/debug/events":
                    # the podtrace event-lifecycle dump: per-tenant rings of
                    # completed EventRecords + rolling per-stage quantiles,
                    # SLO budget, and wake-cause attribution
                    from ..obs.podtrace import tenant_surfaces

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        limit = int(qs["n"][0]) if "n" in qs else None
                    except ValueError:
                        self._send(400, "bad ?n= value")
                        return
                    tracers = {}
                    own = getattr(env, "podtracer", None)
                    if own is not None:
                        tracers[own.tenant or "default"] = own
                    for label, (_rec, tracer) in tenant_surfaces().items():
                        tracers.setdefault(label, tracer)
                    tenant = qs["tenant"][0] if "tenant" in qs else None
                    if tenant is not None:
                        if tenant not in tracers:
                            self._send(404, f"unknown tenant {tenant!r}")
                            return
                        tracers = {tenant: tracers[tenant]}
                    body = {"tenants": {label: t.dump(limit=limit) for label, t in sorted(tracers.items())}}
                    self._send(200, json.dumps(body, indent=1), "application/json")
                elif self.path.split("?", 1)[0] == "/debug/tenants":
                    # faultline: per-tenant failure-domain state — breaker
                    # state/backoff/last-error, backlog, wakes — merged
                    # across every live FleetFrontend in this process, or in
                    # router mode across every SHARD (rows stamped with the
                    # owning shard id)
                    if router is not None:
                        self._send(200, json.dumps({"tenants": router.debug_tenants()}, indent=1), "application/json")
                        return
                    from ..serving.fleet import fleet_debug_surfaces

                    self._send(200, json.dumps({"tenants": fleet_debug_surfaces()}, indent=1), "application/json")
                elif self.path == "/debug/shards" and router is not None:
                    # shardfleet: per-shard liveness, breaker snapshot, debug
                    # port, ring index, and seated tenants
                    self._send(200, json.dumps({"shards": router.debug_shards()}, indent=1), "application/json")
                elif self.path == "/debug/profile" and enable_profiling:
                    frames = {}
                    for tid, frame in sys._current_frames().items():
                        frames[str(tid)] = traceback.format_stack(frame)
                    self._send(200, json.dumps(frames, indent=1), "application/json")
                else:
                    self._send(404, "not found")

        # construct AND install under one lock hold: a stop() racing the
        # bind window must either run before any socket exists (no-op, and
        # start proceeds as a legitimate later start) or see the installed
        # handles — never find None while a bound listener is about to be
        # published after it returned
        with self._lock:
            if self._httpd is not None:
                return self.port  # already serving: start() is idempotent
            httpd = ThreadingHTTPServer((self.bind, self.port), Handler)
            self._httpd = httpd
            self.port = httpd.server_address[1]  # resolve port 0
            self._thread = spawn_thread(httpd.serve_forever, name="karpenter-operator-http")
        return self.port

    def stop(self) -> None:
        """Idempotent and double-call-safe: the handles are claimed
        atomically, so a second (or concurrent) stop() finds None and
        returns instead of double-shutting the stdlib server."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)
