"""Dedup-cached event recorder (reference: pkg/events/recorder.go:40-90).

Events involving the same object/reason within the dedupe window collapse to
one. Events are retained in-process (the Store has no Event kind); tests and
the monitor read recorder.events.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..obs.racecheck import make_rlock

DEFAULT_DEDUPE_TIMEOUT = 120.0


@dataclass
class Event:
    involved_kind: str
    involved_name: str
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0
    dedupe_values: tuple = ()
    dedupe_timeout: float = DEFAULT_DEDUPE_TIMEOUT

    def dedupe_key(self) -> str:
        return "-".join([self.reason.lower(), *map(str, self.dedupe_values or (self.involved_kind, self.involved_name))])


class Recorder:
    GUARDED_FIELDS = {"events": "_lock", "_seen": "_lock"}

    def __init__(self, clock, max_events: int = 2000):
        self.clock = clock
        self.events: list[Event] = []
        self._max = max_events
        self._lock = make_rlock("events")
        self._seen: dict[str, float] = {}  # dedupe key -> last publish time

    def publish(self, obj, reason: str, message: str, type_: str = "Normal", dedupe_values: tuple = (), dedupe_timeout: float = DEFAULT_DEDUPE_TIMEOUT) -> bool:
        ev = Event(
            involved_kind=getattr(obj, "kind", type(obj).__name__),
            involved_name=obj.metadata.name if hasattr(obj, "metadata") else str(obj),
            type=type_,
            reason=reason,
            message=message,
            timestamp=self.clock.now(),
            dedupe_values=tuple(dedupe_values),
            dedupe_timeout=dedupe_timeout,
        )
        key = ev.dedupe_key()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and self.clock.now() - last < ev.dedupe_timeout:
                return False
            self._seen[key] = self.clock.now()
            self.events.append(ev)
            if len(self.events) > self._max:
                del self.events[: len(self.events) - self._max]
        return True

    def for_object(self, name: str) -> list[Event]:
        with self._lock:
            return [e for e in self.events if e.involved_name == name]

    def reasons(self) -> list[str]:
        with self._lock:
            return [e.reason for e in self.events]
