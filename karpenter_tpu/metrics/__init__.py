"""Metrics registry + the well-known karpenter_ metric definitions.

Reference: pkg/metrics/metrics.go:36-107 and the per-controller metric files
(scheduling/metrics.go, disruption/metrics.go, controllers/metrics/*). The
names below match the reference's fully-qualified prometheus names.
"""

from __future__ import annotations

from .registry import (
    DEFAULT_BUCKETS,
    DURATION_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)

# -- well-known metric names (reference: pkg/metrics/metrics.go) --------------
NODECLAIMS_CREATED_TOTAL = "karpenter_nodeclaims_created_total"
NODECLAIMS_TERMINATED_TOTAL = "karpenter_nodeclaims_terminated_total"
NODECLAIMS_DISRUPTED_TOTAL = "karpenter_nodeclaims_disrupted_total"
PODS_DISRUPTION_INITIATED_TOTAL = "karpenter_pods_disruption_initiated_total"
NODES_CREATED_TOTAL = "karpenter_nodes_created_total"
NODES_TERMINATED_TOTAL = "karpenter_nodes_terminated_total"

SCHEDULER_SCHEDULING_DURATION = "karpenter_scheduler_scheduling_duration_seconds"
SCHEDULER_QUEUE_DEPTH = "karpenter_scheduler_queue_depth"
SCHEDULER_UNFINISHED_WORK = "karpenter_scheduler_unfinished_work_seconds"
SCHEDULER_IGNORED_PODS = "karpenter_scheduler_ignored_pods_count"
SCHEDULER_UNSCHEDULABLE_PODS = "karpenter_scheduler_unschedulable_pods_count"
SCHEDULER_PENDING_PODS_BY_EFFECTIVE_ZONE = "karpenter_scheduler_pending_pods_by_effective_zone_count"

DISRUPTION_DECISIONS_TOTAL = "karpenter_voluntary_disruption_decisions_total"
DISRUPTION_ELIGIBLE_NODES = "karpenter_voluntary_disruption_eligible_nodes"
DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL = "karpenter_voluntary_disruption_consolidation_timeouts_total"
DISRUPTION_FAILED_VALIDATIONS_TOTAL = "karpenter_voluntary_disruption_failed_validations_total"
DISRUPTION_QUEUE_FAILURES_TOTAL = "karpenter_voluntary_disruption_queue_failures_total"
DISRUPTION_DECISION_EVAL_DURATION = "karpenter_voluntary_disruption_decision_evaluation_duration_seconds"
NODEPOOL_ALLOWED_DISRUPTIONS = "karpenter_nodepools_allowed_disruptions"

PODS_STARTUP_DURATION = "karpenter_pods_startup_duration_seconds"
PODS_BOUND_DURATION = "karpenter_pods_bound_duration_seconds"
PODS_UNBOUND_TIME = "karpenter_pods_unbound_time_seconds"
PODS_PROVISIONING_BOUND_DURATION = "karpenter_pods_provisioning_bound_duration_seconds"
PODS_STATE = "karpenter_pods_state"

NODES_ALLOCATABLE = "karpenter_nodes_allocatable"
NODES_TOTAL_POD_REQUESTS = "karpenter_nodes_total_pod_requests"
NODES_TOTAL_DAEMON_REQUESTS = "karpenter_nodes_total_daemon_requests"
NODES_UTILIZATION = "karpenter_nodes_utilization_percent"
NODES_CURRENT_LIFETIME = "karpenter_nodes_current_lifetime_seconds"

NODEPOOL_USAGE = "karpenter_nodepools_usage"
NODEPOOL_LIMIT = "karpenter_nodepools_limit"
NODEPOOL_COST_TOTAL = "karpenter_nodepools_cost_total"
NODEPOOL_COST_TRACKER_ERRORS_TOTAL = "karpenter_nodepools_cost_tracker_errors_total"

CLUSTER_STATE_SYNCED = "karpenter_cluster_state_synced"
CLUSTER_STATE_NODE_COUNT = "karpenter_cluster_state_node_count"

# tensor-solver observability (no reference analogue — the FFD path *is* the
# semantics there; the TPU backend re-derives placements so it self-checks)
SOLVER_SOLVE_TOTAL = "karpenter_solver_solve_total"
SOLVER_FALLBACK_TOTAL = "karpenter_solver_fallback_total"
SOLVER_VALIDATION_FAILURES_TOTAL = "karpenter_solver_validation_failures_total"
SOLVER_HYBRID_RESIDUAL_TOTAL = "karpenter_solver_hybrid_residual_total"
SOLVER_DECODE_REPAIR_TOTAL = "karpenter_solver_decode_repair_total"
# decode materialization mode per solve; mode is the bounded {full,
# delta-reuse} enum — a warm delta chain should sit at delta-reuse
SOLVER_DECODE_TOTAL = "karpenter_solver_decode_total"
# per-slot reuse attribution: claims served from the decode-delta memo
# instead of re-materialized (the decode-tail analogue of delta-hit)
SOLVER_DECODE_REUSED_SLOTS_TOTAL = "karpenter_solver_decode_reused_slots_total"
# why a delta-capable solve routed to the full path anyway; reason is the
# bounded encode.DELTA_REJECT_REASONS enum ({unseen-sig, row-key, vol-rv,
# pvc, cap, reorder, fallback-global, irreversible, slot-exhausted,
# validate, no-carry}) — the churn harness's per-reason full-solve breakdown
SOLVER_DELTA_REJECT_TOTAL = "karpenter_solver_delta_reject_total"
# why a multi-group pod shape stayed a count=1 item instead of merging
# (signature compression switched off for it); reason is the bounded
# scheduler_model_grouped.DEMOTION_REASONS enum ({multi-key,
# aff-pin-conflict, hatch-off}) — the LRA regime's compression attribution
SOLVER_PACK_ITEM_DEMOTIONS_TOTAL = "karpenter_solver_pack_item_demotions_total"
# pods-per-item compression of the newest full pack (n_pods / n_items):
# ~1.0 means the grouped scan degenerated to per-pod steps
SOLVER_PACK_ITEM_COMPRESSION = "karpenter_solver_pack_item_compression"
SOLVER_ENCODE_SECONDS = "karpenter_solver_encode_seconds"
SOLVER_FFD_MEMO_TOTAL = "karpenter_solver_ffd_memo_total"
SOLVER_FFD_PHASE_SECONDS = "karpenter_solver_ffd_phase_seconds"
# solvetrace surfaces (obs/trace.py): the recompile sentinel, the trace-ring
# eviction counter, and the rolling per-(mode, phase) latency quantiles
SOLVER_RECOMPILE_TOTAL = "karpenter_solver_recompile_total"
SOLVER_TRACE_DROPPED_TOTAL = "karpenter_solver_trace_dropped_total"
SOLVER_SOLVE_QUANTILE_SECONDS = "karpenter_solver_solve_quantile_seconds"
# steady-state churn serving loop (serving/loop.py + the provisioner's
# coalescing batcher): event is the bounded {arrival | departure} enum
SOLVER_CHURN_COALESCED_TOTAL = "karpenter_solver_churn_coalesced_triggers_total"
SOLVER_CHURN_QUEUE_DEPTH = "karpenter_solver_churn_queue_depth"
SOLVER_CHURN_EVENTS_PER_SOLVE = "karpenter_solver_churn_events_per_solve"
SOLVER_CHURN_EVENTS_TOTAL = "karpenter_solver_churn_events_total"
# tensor-native consolidation (the relaxed-LP repack + masked simulations):
# proposer is the bounded {lp | anneal | binary-search | globalpack} enum,
# decision the exact-validation verdict {accept | reject}
SOLVER_CONSOLIDATION_PROPOSALS_TOTAL = "karpenter_solver_consolidation_proposals_total"
SOLVER_CONSOLIDATION_LP_ITERATIONS_TOTAL = "karpenter_solver_consolidation_lp_iterations_total"
SOLVER_CONSOLIDATION_VALIDATION_TOTAL = "karpenter_solver_consolidation_validation_total"
SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR = "karpenter_solver_consolidation_savings_per_hour"
# globalpack (models/globalpack): the joint provisioning + consolidation
# convex solve behind KARPENTER_SOLVER_GLOBALPACK. All label-free or riding
# the bounded proposer enum above — one rounds counter per global solve, the
# iterations spent inside it, and the newest solve's discrete objective
# improvement over the empty delete-set (the two-phase-equivalent base).
SOLVER_GLOBALPACK_ROUNDS_TOTAL = "karpenter_solver_globalpack_rounds_total"
SOLVER_GLOBALPACK_ITERATIONS_TOTAL = "karpenter_solver_globalpack_iterations_total"
SOLVER_GLOBALPACK_OBJECTIVE_IMPROVEMENT = "karpenter_solver_globalpack_objective_improvement"
# fleet front-end (serving/fleet.py): one solver process multiplexing many
# tenant clusters. `tenant` is the BOUNDED fleet label (serving.fleet
# tenant_label: the first registrations keep their sanitized ids, the rest
# collapse to "overflow"); it also rides karpenter_solver_solve_total and
# the churn families so per-tenant serving behavior is attributable from
# one shared registry.
SOLVER_FLEET_RUNNABLE_TENANTS = "karpenter_solver_fleet_runnable_tenants"
# wake episodes split by the bounded `cause` enum (obs.podtrace.WAKE_CAUSES:
# watch-event | batcher-window | poll-floor | rearm) so wake attribution is
# queryable — which seam actually makes tenants runnable in production
SOLVER_FLEET_WAKE_TOTAL = "karpenter_solver_fleet_wake_total"
SOLVER_FLEET_SCHED_WAIT_SECONDS = "karpenter_solver_fleet_sched_wait_seconds"
# podtrace (obs/podtrace.py): the event-lifecycle flight recorder. `stage`
# is the static STAGES tuple (coalesce | sched_wait | prestage | solve |
# decode | e2e), `tenant` the bounded fleet label, `quantile` the
# three-point rolling enum — all bounded by construction.
SOLVER_EVENT_STAGE_QUANTILE_SECONDS = "karpenter_solver_event_stage_quantile_seconds"
SOLVER_EVENT_SLO_BREACH_TOTAL = "karpenter_solver_event_slo_breach_total"
SOLVER_EVENT_TRACE_DROPPED_TOTAL = "karpenter_solver_event_trace_dropped_total"
# wake-to-solve wait: sub-ms when the fleet loop is idle, growing under
# multiplexing pressure — the fairness policy's observable surface
SOLVER_FLEET_SCHED_WAIT_BUCKETS = (0.000_1, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
# racecheck (obs/racecheck.py): lock-contention observability — wait time per
# named serving-stack lock, emitted by the instrumented wrapper under
# KARPENTER_SOLVER_RACECHECK=1. `lock` is the static make_lock call-site enum.
SOLVER_LOCK_WAIT_SECONDS = "karpenter_solver_lock_wait_seconds"
# faultline (serving/faults.py + the recovery layer): failure-domain
# isolation and graceful degradation. `state` is the bounded
# faults.TENANT_STATES enum (healthy | quarantined | probing), `stage` the
# solver.tpu.RECOVERY_STAGES ladder enum (full-reencode | host-ffd), `seam`
# the faults.FAULT_SEAMS injection enum — all closed tuples.
SOLVER_TENANT_STATE = "karpenter_solver_tenant_state"
SOLVER_BREAKER_TRANSITIONS_TOTAL = "karpenter_solver_breaker_transitions_total"
SOLVER_RECOVERY_TOTAL = "karpenter_solver_recovery_total"
SOLVER_FLEET_SHED_TOTAL = "karpenter_solver_fleet_shed_total"
SOLVER_FLEET_WATCHDOG_TOTAL = "karpenter_solver_fleet_watchdog_total"
SOLVER_FLEET_OLDEST_EVENT_AGE = "karpenter_solver_fleet_oldest_event_age_seconds"
SOLVER_FAULT_INJECTIONS_TOTAL = "karpenter_solver_fault_injections_total"
SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL = "karpenter_solver_prestage_worker_restarts_total"
SOLVER_WATCH_RESYNC_TOTAL = "karpenter_solver_watch_resync_total"
# shardfleet (serving/shard.py): the multi-process fleet router. `shard` is
# the BOUNDED shard label (serving.shard.shard_label — same cap/overflow
# contract as tenant_label); `state` reuses the faults.TENANT_STATES enum
# for the router's per-shard circuit breakers. The router also re-exposes
# every shard's karpenter_solver_fleet_* samples with an injected `shard`
# label via ShardRouter.merged_metrics.
SOLVER_FLEET_SHARDS = "karpenter_solver_fleet_shards"
SOLVER_SHARD_STATE = "karpenter_solver_shard_state"
SOLVER_SHARD_REHOMED_TOTAL = "karpenter_solver_shard_rehomed_tenants_total"
SOLVER_SHARD_RESTARTS_TOTAL = "karpenter_solver_shard_restarts_total"
# lock waits live well under the solve buckets: sub-ms is the norm, anything
# past 100ms is contention worth a dashboard line. Shared with the wrapper's
# emission site so a registry that skipped make_registry still gets the
# 10µs-resolution series, not DEFAULT_BUCKETS.
SOLVER_LOCK_WAIT_BUCKETS = (0.000_01, 0.000_1, 0.001, 0.01, 0.1, 1.0)


def make_registry() -> Registry:
    """A registry pre-populated with the reference's metric families."""
    r = Registry()
    r.counter(NODECLAIMS_CREATED_TOTAL, "Number of nodeclaims created", ("reason", "nodepool", "min_values_relaxed"))
    r.counter(NODECLAIMS_TERMINATED_TOTAL, "Number of nodeclaims terminated", ("nodepool", "capacity_type", "zone"))
    r.counter(NODECLAIMS_DISRUPTED_TOTAL, "Number of nodeclaims disrupted", ("reason", "nodepool", "capacity_type"))
    r.counter(PODS_DISRUPTION_INITIATED_TOTAL, "Pod disruptions initiated", ("reason", "nodepool", "capacity_type"))
    r.counter(NODES_CREATED_TOTAL, "Nodes created", ("nodepool", "zone"))
    r.counter(NODES_TERMINATED_TOTAL, "Nodes terminated", ("nodepool", "zone"))
    r.histogram(SCHEDULER_SCHEDULING_DURATION, "Duration of one scheduling solve", (), DURATION_BUCKETS)
    r.gauge(SCHEDULER_QUEUE_DEPTH, "Pods waiting in the scheduling queue", ())
    r.gauge(SCHEDULER_UNFINISHED_WORK, "Seconds the in-flight solve has been running", ())
    r.gauge(SCHEDULER_IGNORED_PODS, "Pods ignored by the scheduler", ())
    r.gauge(SCHEDULER_UNSCHEDULABLE_PODS, "Pods the last solve could not place", ())
    r.gauge(
        SCHEDULER_PENDING_PODS_BY_EFFECTIVE_ZONE,
        "Pending pods by effective zone constraint (a zone name, 'flexible', or 'none')",
        ("zone",),
    )
    r.counter(DISRUPTION_DECISIONS_TOTAL, "Disruption decisions", ("decision", "method", "consolidation_type"))
    r.gauge(DISRUPTION_ELIGIBLE_NODES, "Nodes eligible for disruption", ("method", "consolidation_type"))
    r.counter(DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL, "Consolidation probes aborted on timeout", ("consolidation_type",))
    r.counter(DISRUPTION_FAILED_VALIDATIONS_TOTAL, "Commands dropped by the validator", ("method",))
    r.counter(DISRUPTION_QUEUE_FAILURES_TOTAL, "Disruption commands that failed in the queue", ("method",))
    r.histogram(DISRUPTION_DECISION_EVAL_DURATION, "Time to compute a disruption decision", ("method",), DURATION_BUCKETS)
    r.gauge(NODEPOOL_ALLOWED_DISRUPTIONS, "Budget-allowed disruptions", ("nodepool", "reason"))
    r.histogram(PODS_STARTUP_DURATION, "Pod creation to running", (), DURATION_BUCKETS)
    r.histogram(PODS_BOUND_DURATION, "Pod creation to bound", (), DURATION_BUCKETS)
    r.gauge(PODS_UNBOUND_TIME, "Seconds a pod has been unbound", ("name", "namespace"))
    r.histogram(PODS_PROVISIONING_BOUND_DURATION, "Karpenter-provisioned pod creation to bound", (), DURATION_BUCKETS)
    r.gauge(PODS_STATE, "Pod state", ("name", "namespace", "phase"))
    r.gauge(NODES_ALLOCATABLE, "Node allocatable by resource", ("node_name", "nodepool", "resource_type", "zone"))
    r.gauge(NODES_TOTAL_POD_REQUESTS, "Pod requests on node", ("node_name", "nodepool", "resource_type"))
    r.gauge(NODES_TOTAL_DAEMON_REQUESTS, "Daemon requests on node", ("node_name", "nodepool", "resource_type"))
    r.gauge(NODES_UTILIZATION, "Requested/allocatable percent", ("node_name", "nodepool", "resource_type"))
    r.gauge(NODES_CURRENT_LIFETIME, "Node age", ("node_name", "nodepool"))
    r.gauge(NODEPOOL_USAGE, "Per-pool resource usage", ("nodepool", "resource_type"))
    r.gauge(NODEPOOL_LIMIT, "Per-pool resource limits", ("nodepool", "resource_type"))
    r.gauge(NODEPOOL_COST_TOTAL, "Total tracked cost of the nodepool (not authoritative for billing)", ("nodepool",))
    r.counter(NODEPOOL_COST_TRACKER_ERRORS_TOTAL, "Cost tracking errors", ("nodepool",))
    r.gauge(CLUSTER_STATE_SYNCED, "1 if cluster state is synced", ())
    r.gauge(CLUSTER_STATE_NODE_COUNT, "Nodes tracked by cluster state", ())
    r.counter(SOLVER_SOLVE_TOTAL, "Solves by backend actually used", ("backend", "tenant"))
    r.counter(SOLVER_FALLBACK_TOTAL, "Tensor-path solves that fell back to the host FFD", ("reason",))
    r.counter(SOLVER_VALIDATION_FAILURES_TOTAL, "Device placements rejected by the post-solve validator", ())
    r.counter(
        SOLVER_HYBRID_RESIDUAL_TOTAL,
        "Hybrid partitioned solves that routed a pod-local residual to the host FFD, by reason family",
        ("reason",),
    )
    r.counter(
        SOLVER_DECODE_REPAIR_TOTAL,
        "Tensor decodes that routed part of the placement through the bounded host repair, by reason family",
        ("reason",),
    )
    r.counter(
        SOLVER_DELTA_REJECT_TOTAL,
        "Delta-capable solves routed to the full path, by reject reason",
        ("reason",),
    )
    r.counter(
        SOLVER_DECODE_TOTAL,
        "Tensor decodes by materialization mode (full | delta-reuse)",
        ("mode",),
    )
    r.counter(
        SOLVER_DECODE_REUSED_SLOTS_TOTAL,
        "Slots served from the decode-delta memo instead of re-materialized",
        (),
    )
    r.counter(
        SOLVER_PACK_ITEM_DEMOTIONS_TOTAL,
        "Pods whose multi-group shape stayed a count=1 pack item instead of "
        "merging, by bounded demotion reason (multi-key | aff-pin-conflict | "
        "hatch-off)",
        ("reason",),
    )
    r.gauge(
        SOLVER_PACK_ITEM_COMPRESSION,
        "Pods-per-item compression of the newest full grouped pack "
        "(n_pods / n_items; ~1.0 = the scan degenerated to per-pod steps)",
        (),
    )
    # backend label values for SOLVER_SOLVE_TOTAL include "hybrid-delta":
    # a warm hybrid re-solve that re-packed only the pod delta against the
    # retained masked carry
    r.histogram(
        SOLVER_ENCODE_SECONDS,
        "Host-side snapshot-encode duration, by mode (full | masked sub-encode | pod delta)",
        ("mode",),
    )
    r.counter(
        SOLVER_FFD_MEMO_TOTAL,
        "Signature-batched host-FFD fit-memo probes, by outcome (hit | miss | invalidate)",
        ("kind",),
    )
    r.histogram(
        SOLVER_FFD_PHASE_SECONDS,
        "Host-FFD per-solve scan time, by phase (existing | inflight | new_claim)",
        ("phase",),
        DURATION_BUCKETS,
    )
    r.counter(
        SOLVER_RECOMPILE_TOTAL,
        "JIT recompiles observed by the solvetrace sentinel, by jitted entry point "
        "(the churn loop's zero-steady-state-recompiles target reads this)",
        ("fn",),
    )
    r.counter(SOLVER_TRACE_DROPPED_TOTAL, "SolveTraces evicted from the bounded flight-recorder ring", ())
    r.gauge(
        SOLVER_SOLVE_QUANTILE_SECONDS,
        "Rolling solve-latency quantiles (p50 | p90 | p99) over the trace ring, per (mode, phase)",
        ("mode", "phase", "quantile"),
    )
    r.counter(
        SOLVER_CHURN_COALESCED_TOTAL,
        "Provisioner triggers that arrived during an in-flight solve and were "
        "coalesced into one batched follow-up solve instead of one solve each",
        ("tenant",),
    )
    r.gauge(
        SOLVER_CHURN_QUEUE_DEPTH,
        "Triggers accumulated in the batcher's pending generation after the last solve",
        ("tenant",),
    )
    r.histogram(
        SOLVER_CHURN_EVENTS_PER_SOLVE,
        "Trigger events drained by one provisioning solve (the coalescing ratio)",
        ("tenant",),
        (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
    )
    r.counter(
        SOLVER_CHURN_EVENTS_TOTAL,
        "Pod churn events applied by the serving loop, by kind (arrival | departure)",
        ("event", "tenant"),
    )
    r.gauge(
        SOLVER_FLEET_RUNNABLE_TENANTS,
        "Tenants currently marked runnable by the fleet front-end's push wake",
        (),
    )
    r.counter(
        SOLVER_FLEET_WAKE_TOTAL,
        "Fleet wake episodes: what marked the tenant runnable and woke the "
        "fleet loop, by bounded cause (watch-event | batcher-window | "
        "poll-floor | rearm)",
        ("tenant", "cause"),
    )
    r.gauge(
        SOLVER_EVENT_STAGE_QUANTILE_SECONDS,
        "Rolling event-lifecycle latency quantiles (p50 | p90 | p99) over the "
        "podtrace ring, per (tenant, stage) — e2e is event-to-placement",
        ("tenant", "stage", "quantile"),
    )
    r.counter(
        SOLVER_EVENT_SLO_BREACH_TOTAL,
        "Completed events whose e2e latency exceeded the podtrace SLO target "
        "(KARPENTER_PODTRACE_SLO) — the SLO budget burn counter",
        ("tenant",),
    )
    r.counter(
        SOLVER_EVENT_TRACE_DROPPED_TOTAL,
        "EventRecords evicted from the bounded podtrace ring or refused at the in-flight cap",
        (),
    )
    r.histogram(
        SOLVER_FLEET_SCHED_WAIT_SECONDS,
        "Time from a tenant becoming runnable to its solve starting (the "
        "deficit-round-robin scheduling delay under multiplexing)",
        ("tenant",),
        SOLVER_FLEET_SCHED_WAIT_BUCKETS,
    )
    r.counter(
        SOLVER_CONSOLIDATION_PROPOSALS_TOTAL,
        "Candidate delete-sets proposed per consolidation round, by proposer "
        "(lp | anneal | binary-search | globalpack)",
        ("proposer",),
    )
    r.counter(
        SOLVER_GLOBALPACK_ROUNDS_TOTAL,
        "Joint provisioning+consolidation global repack solves run",
        (),
    )
    r.counter(
        SOLVER_GLOBALPACK_ITERATIONS_TOTAL,
        "Projected-gradient iterations spent by the global repack (inits x steps per solve)",
        (),
    )
    r.gauge(
        SOLVER_GLOBALPACK_OBJECTIVE_IMPROVEMENT,
        "Newest global solve's discrete objective improvement over the empty delete-set base",
        (),
    )
    r.counter(
        SOLVER_CONSOLIDATION_LP_ITERATIONS_TOTAL,
        "Projected-gradient iterations spent by the relaxed-LP repack (inits x steps per solve)",
        (),
    )
    r.counter(
        SOLVER_CONSOLIDATION_VALIDATION_TOTAL,
        "Exact host validations of device-proposed consolidation subsets, by decision",
        ("decision",),
    )
    r.gauge(
        SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR,
        "Hourly price saved by the newest accepted consolidation command, by proposer",
        ("proposer",),
    )
    r.histogram(
        SOLVER_LOCK_WAIT_SECONDS,
        "Time spent waiting to acquire a named serving-stack lock (racecheck wrapper)",
        ("lock",),
        SOLVER_LOCK_WAIT_BUCKETS,
    )
    r.gauge(
        SOLVER_TENANT_STATE,
        "Per-tenant circuit-breaker state (1 on the current state's series): "
        "healthy | quarantined | probing",
        ("tenant", "state"),
    )
    r.counter(
        SOLVER_BREAKER_TRANSITIONS_TOTAL,
        "Tenant circuit-breaker transitions INTO a state (quarantined = the "
        "failure domain closed; probing = a half-open re-admission probe; "
        "healthy = re-admitted)",
        ("tenant", "state"),
    )
    r.counter(
        SOLVER_RECOVERY_TOTAL,
        "Solve-failure recovery-ladder steps taken, by stage (full-reencode = "
        "quarantined caches + from-scratch retry; host-ffd = exact host fallback)",
        ("stage",),
    )
    r.counter(
        SOLVER_FLEET_SHED_TOTAL,
        "Watch triggers shed by the fleet's per-tenant overload protection "
        "(the tenant's backlog exceeded its cap; its pending pods are served "
        "later, everyone else on time)",
        ("tenant",),
    )
    r.counter(
        SOLVER_FLEET_WATCHDOG_TOTAL,
        "Oldest-event-age watchdog firings: a shedding tenant's backlog aged "
        "past the watchdog bound and was force-served",
        ("tenant",),
    )
    r.gauge(
        SOLVER_FLEET_OLDEST_EVENT_AGE,
        "Age of each runnable tenant's oldest un-served wake (the DRR "
        "starvation surface the watchdog bounds)",
        ("tenant",),
    )
    r.counter(
        SOLVER_FAULT_INJECTIONS_TOTAL,
        "Deterministic faults injected by the faultline FaultSpec plan, by seam",
        ("seam",),
    )
    r.counter(
        SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL,
        "PendingPrestager worker threads restarted by the serving loop's "
        "supervisor after a (real or injected) death",
        (),
    )
    r.counter(
        SOLVER_WATCH_RESYNC_TOTAL,
        "Level-triggered Cluster resyncs from store content after the watch "
        "stream's gap tracker detected lost Pod events",
        (),
    )
    r.gauge(SOLVER_FLEET_SHARDS, "Shard worker processes currently seated on the router's ring", ())
    r.gauge(
        SOLVER_SHARD_STATE,
        "Per-shard router circuit-breaker state (1 on the current state's "
        "series): healthy | quarantined | probing",
        ("shard", "state"),
    )
    r.counter(
        SOLVER_SHARD_REHOMED_TOTAL,
        "Tenants re-homed onto a shard after their home shard died (recorded-"
        "log replay, bit-identical placement contract)",
        ("shard",),
    )
    r.counter(
        SOLVER_SHARD_RESTARTS_TOTAL,
        "Shard worker processes respawned by the router after a death",
        ("shard",),
    )
    return r


__all__ = [
    "Registry",
    "Counter",
    "Gauge",
    "Histogram",
    "make_registry",
    "DEFAULT_BUCKETS",
    "DURATION_BUCKETS",
]
