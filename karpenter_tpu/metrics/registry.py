"""Dependency-free Prometheus-style metrics registry.

Reference: pkg/metrics/metrics.go (karpenter_ namespace counters/gauges/
histograms registered on the controller-runtime registry) — rebuilt as a
small in-process registry with text exposition, since the TPU framework's
control plane is not a Go binary. Metric names/labels mirror the reference
so dashboards port over.
"""

from __future__ import annotations

import math
from bisect import bisect_left

from ..obs.racecheck import make_rlock

NAMESPACE = "karpenter"

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)
DURATION_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = make_rlock("metric")

    def _check(self, labels: dict[str, str]) -> dict[str, str]:
        extra = set(labels) - set(self.label_names)
        if extra:
            raise ValueError(f"{self.name}: unknown labels {extra}")
        return {k: str(labels.get(k, "")) for k in self.label_names}


class Counter(_Metric):
    TYPE = "counter"
    GUARDED_FIELDS = {"_values": "_lock"}

    def __init__(self, name, help_, label_names):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        labels = self._check(labels)
        with self._lock:
            key = _label_key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(self._check(labels)), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def collect(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class Gauge(_Metric):
    TYPE = "gauge"
    GUARDED_FIELDS = {"_values": "_lock"}

    def __init__(self, name, help_, label_names):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        labels = self._check(labels)
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, amount: float, **labels) -> None:
        labels = self._check(labels)
        with self._lock:
            key = _label_key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def delete(self, **labels) -> None:
        with self._lock:
            self._values.pop(_label_key(self._check(labels)), None)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(self._check(labels)), 0.0)

    def collect(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class Histogram(_Metric):
    TYPE = "histogram"
    GUARDED_FIELDS = {"_counts": "_lock", "_sums": "_lock", "_totals": "_lock"}

    def __init__(self, name, help_, label_names, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}  # per-bucket cumulative-style on collect
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        labels = self._check(labels)
        with self._lock:
            key = _label_key(labels)
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = bisect_left(self.buckets, value)
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels) -> int:
        with self._lock:
            return self._totals.get(_label_key(self._check(labels)), 0)

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sums.get(_label_key(self._check(labels)), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """Approximate quantile from bucket midpoints (for tests/monitoring)."""
        with self._lock:
            key = _label_key(self._check(labels))
            counts = self._counts.get(key)
            total = self._totals.get(key, 0)
        if not counts or total == 0:
            return math.nan
        target = q * total
        run = 0
        for i, c in enumerate(counts):
            run += c
            if run >= target:
                return self.buckets[i]
        return self.buckets[-1]

    def collect(self):
        with self._lock:
            out = []
            for key, counts in self._counts.items():
                cumulative, cum = [], 0
                for c in counts:
                    cum += c
                    cumulative.append(cum)
                out.append((dict(key), cumulative, self._totals[key], self._sums[key]))
            return out


class Registry:
    """get-or-create metric registry with prometheus text exposition."""

    GUARDED_FIELDS = {"_metrics": "_lock"}

    def __init__(self):
        self._lock = make_rlock("metric-registry")
        self._metrics: dict[str, _Metric] = {}

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, tuple(labels))

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, tuple(labels))

    def histogram(self, name: str, help_: str = "", labels: tuple[str, ...] = (), buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, tuple(labels), buckets)
                self._metrics[name] = m
            if not isinstance(m, Histogram):
                raise TypeError(f"{name} is a {m.TYPE}, not a histogram")
            return m

    def _get_or_create(self, cls, name, help_, label_names):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, label_names)
                self._metrics[name] = m
            if not isinstance(m, cls):
                raise TypeError(f"{name} is a {m.TYPE}, not a {cls.TYPE}")
            return m

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text format (the /metrics endpoint payload)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda x: x.name):
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            if isinstance(m, Histogram):
                for labels, cumulative, total, sum_ in m.collect():
                    for bound, cum in zip(m.buckets, cumulative):
                        lines.append(_sample(f"{m.name}_bucket", {**labels, "le": _fmt(bound)}, cum))
                    lines.append(_sample(f"{m.name}_bucket", {**labels, "le": "+Inf"}, total))
                    lines.append(_sample(f"{m.name}_sum", labels, sum_))
                    lines.append(_sample(f"{m.name}_count", labels, total))
            else:
                for labels, v in m.collect():
                    lines.append(_sample(m.name, labels, v))
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    return repr(float(v)) if v != int(v) else str(int(v))


def _sample(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"
