"""ChurnHarness: sustained pod churn against a live Provisioner+TPUSolver.

The harness builds a real control plane (operator.Environment: store,
informers, KWOK cloud provider, lifecycle, binder), provisions a base fleet,
then drives a steady arrival/cancel/departure mix through the ServingLoop
for many solve cycles, measuring the serving regime every earlier bench
skipped:

- throughput: pod churn events applied per wall-clock second;
- re-solve latency: P50/P99 over every steady-phase SolveTrace duration
  (the solvetrace ring is the source of truth — the same quantile machinery
  /debug/solves publishes);
- delta-hit rate: the share of solves served from device-resident state
  (mode "delta"/"hybrid-delta") vs full re-encodes — the number that shows
  whether the clone-identity prestager + node_generation row key actually
  let the encoder recognize consecutive serving snapshots;
- recompiles: the solvetrace sentinel's per-fn counts over the steady phase.
  After warmup (which pays every cold compile at the high-water shapes) the
  steady phase must record ZERO — the KARPENTER_SOLVER_BUCKET high-water
  ladder is what pins the jitted shapes under churn.

The event mix is deliberately shaped like a serving steady state: arrivals
land on capacity freed by departures (claims are only created when the mix
overshoots — creating one bumps node_generation and honestly costs a full
re-encode), cancellations delete still-pending pods (the pure pod-axis
removal delta), and bound-pod departures batch onto the periodic bind-flush
iterations that already pay a row-side re-encode.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field

from .. import metrics as m
from ..obs.stats import quantile
from ..obs.trace import TraceRecorder
from .loop import ServingLoop


@dataclass
class ChurnSpec:
    # fleet / catalog scale (defaults = the 1/10-scale CPU gate)
    n_base_pods: int = 5000
    n_types: int = 100
    # steady-phase event mix, per iteration. The defaults BALANCE: per cycle
    # (bind_every iterations) net arrivals == departures, so the bound fleet
    # and node count stay constant — growth is a real workload change and
    # legitimately pays a (one-time, high-water) compile, but steady state
    # must not.
    arrivals: int = 800
    cancels: int = 600
    departures: int = 800  # applied on bind-flush iterations only
    # share of cancellations that hit the NEWEST pending pods (users
    # cancelling just-submitted work). Those typically arrive and cancel
    # within one batching window, so the coalesced solve never sees them —
    # the serving loop absorbs both events for free. The remainder cancels
    # the OLDEST pending pods, i.e. already-placed ones, exercising the
    # removal re-credit delta in steady state.
    cancel_newest_frac: float = 0.8
    bind_every: int = 4  # every k-th iteration flushes lifecycle+binder
    iterations: int = 40
    warmup_cycles: int = 3  # full bind_every-cycles before the sentinel mark
    batch_idle_seconds: float = 0.25
    # wall-clock seconds of the post-steady CONCURRENT segment: a driver
    # thread applies events while the loop solves, so triggers land mid-solve
    # and the batcher's in-flight coalescing (N triggers -> one follow-up
    # solve) is demonstrated, not just unit-tested. 0 skips the segment.
    concurrent_seconds: float = 1.5
    seed: int = 0
    # record/replay (the ROADMAP trace-replay seed): `record_path` dumps the
    # applied event stream as JSONL (one op per line: arrive/cancel/depart/
    # solve/bind_flush/mark — self-contained pod params plus `t`, the op's
    # wall offset from recording start, so a replay's podtrace latency
    # measurements can be compared against the recorded pacing; replayable
    # without the generator); `replay_events` drives the harness from a log
    # instead of generating events, deterministically — the multi-tenant
    # bench replays ONE recorded log into K fleet tenants. Record with
    # concurrent_seconds=0: the concurrent segment's thread interleaving is
    # inherently non-replayable and is logged only in arrival order.
    record_path: str | None = None
    replay_events: list | None = None
    # faultline (serving/faults.py): a seeded FaultSpec plan installed at the
    # named seams (solver hook, store watch delivery, prestager worker,
    # cycle-boundary revocations). None = no injector, zero-cost seams. The
    # spec rides the recorded JSONL header; revocations ride the log as
    # explicit `revoke` ops, so a replay applies them verbatim instead of
    # re-consuming the plan (run_replay never calls take_revocations).
    faults: object | None = None
    double_buffer: bool | None = None  # None = env default (on)
    # worker=False: prestage synchronously. On a CPU-only harness the pack
    # "device" shares the host cores, so a prestage thread can only contend
    # (GIL) — the double buffer's wins here are clone identity + staged-at-
    # event-time prep. On real TPU hardware the pack landing blocks on the
    # tunnel and the worker overlaps for free; set worker=True there.
    worker: bool = False
    trace_capacity: int = 8192

    @classmethod
    def from_event_log(cls, path: str, tenant=None, **overrides) -> "ChurnSpec":
        """A replay spec: drive the harness from a recorded JSONL event log
        instead of generating events. Scale fields are taken from the log's
        header line when present (so gates scale consistently); overrides
        win. The replay is deterministic: same log + same seed = the same
        placements, which is what lets one recorded stream drive K fleet
        tenants and be compared bit-for-bit.

        `tenant` (a tenant id or a collection of them) replays a NAMED
        SUBSET of a tenant-stamped log: ops whose `tenant` tag names a
        different tenant are dropped, while untagged ops (single-tenant
        recordings, shared pacing skeleton) always replay. This is the
        shard re-homing contract — "replay only tenant-7's ops" into a
        surviving shard after its home shard dies."""
        tenants = None
        if tenant is not None:
            tenants = {tenant} if isinstance(tenant, str) else set(tenant)
        events = []
        header: dict = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                op = json.loads(line)
                if op.get("op") == "header":
                    header = op
                elif tenants is None or op.get("tenant") is None or op["tenant"] in tenants:
                    events.append(op)
        kw = {k: header[k] for k in ("n_base_pods", "n_types", "arrivals", "cancels", "departures", "bind_every", "seed", "batch_idle_seconds") if k in header}
        if header.get("faults"):
            from .faults import FaultSpec

            # the recorded fault plan re-installs at the same seams; its
            # solve/watch indices replay against the same op stream, and
            # revocations apply from the logged `revoke` ops (never from the
            # plan — run_replay bypasses take_revocations)
            kw["faults"] = FaultSpec.from_dict(header["faults"])
        kw.update(overrides)
        kw["replay_events"] = events
        kw.setdefault("concurrent_seconds", 0.0)
        return cls(**kw)


@dataclass
class ChurnReport:
    events: int = 0
    wall_seconds: float = 0.0
    events_per_sec: float = 0.0
    solves: int = 0
    modes: dict = field(default_factory=dict)
    delta_hit_rate: float = 0.0
    p50_solve_seconds: float = 0.0
    p99_solve_seconds: float = 0.0
    recompiles: dict = field(default_factory=dict)
    steady_recompiles: int = 0
    # steady-phase full-solve share broken down by delta-reject reason (the
    # karpenter_solver_delta_reject_total counter, windowed over the steady
    # mark) — so a delta-hit regression names the reject family that caused
    # it instead of a bare hit-rate drop
    full_solve_reasons: dict = field(default_factory=dict)
    coalesced_triggers: int = 0
    concurrent_events: int = 0
    concurrent_solves: int = 0
    pods_per_solve_p50: float = 0.0
    prestage_reused: int = 0
    prestage_staged: int = 0
    n_nodes: int = 0
    n_pending_end: int = 0
    # podtrace (obs/podtrace.py) end-to-end columns over the steady window:
    # event-to-PLACEMENT latency per completed EventRecord, with the
    # per-stage decomposition and the stage that dominated the e2e mean —
    # the number a USER of the cluster experiences, vs p50/p99_solve_seconds
    # which only time the re-solve itself
    e2e_events: int = 0
    e2e_p50_seconds: float = 0.0
    e2e_p99_seconds: float = 0.0
    dominant_stage: str = ""
    stage_p99_seconds: dict = field(default_factory=dict)
    slo_breaches: int = 0
    # faultline columns: what the FaultSpec injected over the whole run, the
    # recovery-ladder steps the solver took over the steady window, nodes
    # revoked, and prestager worker restarts — so a chaos run's report shows
    # both the disruption applied AND the machinery that absorbed it
    faults_injected: dict = field(default_factory=dict)
    recoveries: dict = field(default_factory=dict)
    revoked_nodes: int = 0
    prestage_worker_restarts: int = 0

    def as_dict(self) -> dict:
        return {
            "events": self.events,
            "wall_seconds": round(self.wall_seconds, 3),
            "events_per_sec": round(self.events_per_sec, 1),
            "solves": self.solves,
            "modes": dict(self.modes),
            "delta_hit_rate": round(self.delta_hit_rate, 4),
            "p50_solve_seconds": round(self.p50_solve_seconds, 4),
            "p99_solve_seconds": round(self.p99_solve_seconds, 4),
            "e2e_events": self.e2e_events,
            "e2e_p50_seconds": round(self.e2e_p50_seconds, 4),
            "e2e_p99_seconds": round(self.e2e_p99_seconds, 4),
            "dominant_stage": self.dominant_stage,
            "stage_p99_seconds": {k: round(v, 4) for k, v in self.stage_p99_seconds.items()},
            "slo_breaches": self.slo_breaches,
            "recompiles": dict(self.recompiles),
            "steady_recompiles": self.steady_recompiles,
            "full_solve_reasons": dict(self.full_solve_reasons),
            "coalesced_triggers": self.coalesced_triggers,
            "concurrent_events": self.concurrent_events,
            "concurrent_solves": self.concurrent_solves,
            "pods_per_solve_p50": round(self.pods_per_solve_p50, 1),
            "prestage_reused": self.prestage_reused,
            "prestage_staged": self.prestage_staged,
            "n_nodes": self.n_nodes,
            "n_pending_end": self.n_pending_end,
            "faults_injected": dict(self.faults_injected),
            "recoveries": dict(self.recoveries),
            "revoked_nodes": self.revoked_nodes,
            "prestage_worker_restarts": self.prestage_worker_restarts,
        }


# a fixed shape alphabet: churn arrivals cycle deployment-replica shapes, so
# first contacts batch-stamp and every later encode reads stamps (the
# signature axis stays inside its high-water bucket)
_SHAPES = [
    ("250m", "512Mi", None, None),
    ("500m", "512Mi", None, None),
    ("500m", "1Gi", None, None),
    ("1", "1Gi", None, None),
    ("1", "2Gi", None, None),
    ("2", "2Gi", None, None),
    ("250m", "1Gi", {"tier": "web"}, None),
    ("500m", "2Gi", {"tier": "batch"}, None),
    ("1", "512Mi", None, "test-zone-a"),
    ("500m", "1Gi", None, "test-zone-b"),
]


def _make_pod(name: str, cpu: str, memory: str, labels=None, zone: str | None = None):
    from ..apis import labels as wk
    from ..kube.objects import Container, ObjectMeta, Pod, PodSpec
    from ..utils.resources import parse_resource_list

    sel = {wk.ZONE_LABEL_KEY: zone} if zone else {}
    return Pod(
        # deterministic uid: pods created in one fake-clock instant tie-break
        # FFD order on uid, and the parity tests compare two independently
        # built environments — random uids would make even two serial runs
        # disagree on placement grouping
        metadata=ObjectMeta(name=name, namespace="default", uid=f"uid-{name}", labels=dict(labels or {})),
        spec=PodSpec(
            containers=[Container(resources={"requests": parse_resource_list({"cpu": cpu, "memory": memory})})],
            node_selector=sel,
        ),
    )


class ChurnHarness:
    def __init__(self, spec: ChurnSpec | None = None):
        self.spec = spec or ChurnSpec()
        self._seq = 0
        self._pending: deque[str] = deque()  # created, not yet observed bound
        self._bound: deque[str] = deque()
        self._prebuilt: deque = deque()  # pre-constructed arrival pods
        self.env = None
        self.loop: ServingLoop | None = None
        # fleet mode (attach): solves route through the FleetFrontend's DRR
        # pump instead of the private ServingLoop, scoped to this tenant
        self.fleet = None
        self._tenant_id = None
        # faultline: the live FaultInjector when spec.faults is set (installed
        # by _install_faults from build()/attach())
        self.injector = None
        self.recorder = TraceRecorder(capacity=self.spec.trace_capacity, enabled=True)
        # record/replay: the applied-event log (None = not recording). Every
        # op carries `t`, its wall-clock offset from recording start — the
        # per-event arrival timing that lets a replayed log's latency
        # measurements be compared against the recorded run's pacing.
        self._event_log: list[dict] | None = [] if self.spec.record_path else None
        self._log_t0 = time.perf_counter()

    def _log(self, **op) -> None:
        if self._event_log is not None:
            op.setdefault("t", round(time.perf_counter() - self._log_t0, 6))
            # fleet-attached recordings stamp every op with the owning tenant
            # so a merged/fleet log can later be replayed for a NAMED subset
            # (from_event_log(tenant=...)) — the shard re-homing contract
            if self._tenant_id is not None:
                op.setdefault("tenant", self._tenant_id)
            self._event_log.append(op)

    # -- stack -----------------------------------------------------------------
    def build(self):
        import random

        from ..apis import labels as wk
        from ..apis.nodepool import NodePool
        from ..cloudprovider.fake import instance_types_assorted
        from ..kube.objects import ObjectMeta
        from ..operator import Environment
        from ..operator.options import Options
        from ..solver.tpu import TPUSolver

        # claim-name suffixes come from the global RNG; node iteration order
        # sorts on them — seed so two runs of the same spec agree
        random.seed(self.spec.seed)

        env = Environment(
            options=Options(
                solver_backend="tpu",
                batch_idle_duration=self.spec.batch_idle_seconds,
                batch_max_duration=10.0,
            ),
            instance_types=instance_types_assorted(self.spec.n_types),
        )
        pool = NodePool(metadata=ObjectMeta(name="churn-pool"))
        pool.spec.template.requirements = [
            {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
            {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
        ]
        env.store.create(pool)
        # a private flight recorder: the harness wants the WHOLE run's traces
        # (the process-default ring is 256) without perturbing other solvers
        env.provisioner.solver = TPUSolver(registry=env.registry, recorder=self.recorder)
        self.env = env
        self.loop = ServingLoop(
            env.provisioner,
            env.store,
            double_buffer=self.spec.double_buffer,
            worker=self.spec.worker,
        )
        self._install_faults()
        return self

    def attach(self, session, fleet=None):
        """Attach to a fleet TenantSession instead of building a private
        stack: the session's env/loop/recorder serve this harness, and with
        `fleet` given, `solve()` routes through the fleet's DRR pump (the
        push-wake path) instead of pumping the tenant loop directly. The
        caller owns batch-window sizing via the session's Options."""
        import random

        from ..apis import labels as wk
        from ..apis.nodepool import NodePool
        from ..kube.objects import ObjectMeta

        random.seed(self.spec.seed)
        self.env = session.env
        self.loop = session.loop
        self.recorder = session.recorder
        self.fleet = fleet
        self._tenant_id = session.tenant_id
        if self.env.store.try_get("NodePool", "churn-pool") is None:
            pool = NodePool(metadata=ObjectMeta(name="churn-pool"))
            pool.spec.template.requirements = [
                {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
                {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
            ]
            self.env.store.create(pool)
        self._install_faults()
        return self

    def close(self) -> None:
        if self.loop is not None:
            self.loop.close()

    def _install_faults(self) -> None:
        """Install the spec's FaultInjector at every named seam this stack
        exposes: the solver's solve/re-encode hook, the store's watch
        delivery, and the prestager worker loop. Revocations are pulled at
        cycle boundaries by run_cycle."""
        if self.spec.faults is None:
            return
        from .faults import FaultInjector

        self.injector = FaultInjector(self.spec.faults, registry=self.env.registry)
        solver = self.env.provisioner.solver
        if hasattr(solver, "fault_hook"):
            solver.fault_hook = self.injector.solver_hook
        self.env.store.set_fault_injector(self.injector)
        if self.loop is not None and self.loop.prestager is not None:
            self.loop.prestager.fault_hook = self.injector.prestage_hook

    # -- event application -----------------------------------------------------
    def _record_events(self, n: int, event: str) -> None:
        if n and self.env is not None:
            tenant = self.env.provisioner.tenant
            if event == "arrival":
                self.env.registry.counter(m.SOLVER_CHURN_EVENTS_TOTAL).inc(n, event="arrival", tenant=tenant)  # solverlint: ok(metric-label-cardinality): tenant is the provisioner's fleet registration label (a tenant_label() output; "" outside a fleet)
            else:
                self.env.registry.counter(m.SOLVER_CHURN_EVENTS_TOTAL).inc(n, event="departure", tenant=tenant)  # solverlint: ok(metric-label-cardinality): tenant is the provisioner's fleet registration label (a tenant_label() output; "" outside a fleet)

    def _build_pod(self):
        shape = self._seq % len(_SHAPES)
        cpu, mem, labels, zone = _SHAPES[shape]
        name = f"churn-{self._seq}"
        self._seq += 1
        return name, _make_pod(name, cpu, mem, labels, zone), shape

    def prebuild(self, n: int) -> None:
        """Construct n arrival pods ahead of time (a real apiserver receives
        pods over the wire — object construction is the event SOURCE's cost,
        not the serving loop's; the measured phase should apply events, not
        manufacture them)."""
        for _ in range(n):
            self._prebuilt.append(self._build_pod())

    def apply_arrivals(self, n: int) -> int:
        store = self.env.store
        log = self._event_log is not None
        for _ in range(n):
            name, pod, shape = self._prebuilt.popleft() if self._prebuilt else self._build_pod()
            if log:
                cpu, mem, labels, zone = _SHAPES[shape]
                self._log(op="arrive", name=name, cpu=cpu, memory=mem, labels=labels, zone=zone)
            # adopt: the harness relinquishes the pod object on creation
            store.create(pod, adopt=True)
            self._pending.append(name)
        self._record_events(n, "arrival")
        return n

    def apply_cancels(self, n: int) -> int:
        done = 0
        n_new = int(n * self.spec.cancel_newest_frac)
        while done < n_new and self._pending:
            name = self._pending.pop()  # newest first
            if self.env.store.try_delete("Pod", name, namespace="default"):
                self._log(op="cancel", name=name)
                done += 1
        while done < n and self._pending:
            name = self._pending.popleft()  # oldest: already-placed pods
            pod = self.env.store.borrow_get("Pod", name, "default")
            if pod is None:
                continue
            if pod.spec.node_name:
                self._bound.append(name)  # bound since we last looked
                continue
            self.env.store.try_delete("Pod", name, namespace="default")
            self._log(op="cancel", name=name)
            done += 1
        self._record_events(done, "departure")
        return done

    def apply_departures(self, n: int) -> int:
        done = 0
        while done < n and self._bound:
            name = self._bound.popleft()
            if self.env.store.try_delete("Pod", name, namespace="default"):
                self._log(op="depart", name=name)
                done += 1
        self._record_events(done, "departure")
        return done

    def apply_revocations(self, n: int) -> int:
        """Spot-style capacity revocation: n nodes are reclaimed out from
        under the fleet. Node choice is seeded (the injector's rng over the
        sorted name list) and each revocation is logged as an explicit
        `revoke` op, so a replayed log reproduces the exact reclaim."""
        if n <= 0 or self.env is None:
            return 0
        names = sorted(nd.metadata.name for nd in self.env.store.borrow_list("Node"))
        if not names:
            return 0
        rng = self.injector.rng if self.injector is not None else None
        picks = rng.sample(names, min(n, len(names))) if rng is not None else names[: min(n, len(names))]
        events = 0
        for name in picks:
            events += self.revoke_node(name)
        return events

    def revoke_node(self, name: str) -> int:
        """Decode one capacity revocation as FORCED DEPARTURES into the
        churn stream: the node's bound pods are deleted (the workload they
        carried is gone with the capacity), then the Node and its NodeClaim
        are removed with no graceful drain — exactly what a spot reclaim
        looks like to the control plane. Returns churn events applied."""
        store = self.env.store
        if store.try_get("Node", name) is None:
            return 0
        self._log(op="revoke", node=name)
        events = 0
        for pname in [p.metadata.name for p in store.borrow_list("Pod") if p.spec.node_name == name]:
            if store.try_delete("Pod", pname, namespace="default"):
                events += 1
                try:
                    self._bound.remove(pname)
                except ValueError:
                    try:
                        self._pending.remove(pname)
                    except ValueError:
                        pass
        self._record_events(events, "departure")
        claim = next(
            (nc.metadata.name for nc in store.borrow_list("NodeClaim") if nc.status.node_name == name),
            None,
        )
        # forced: no finalizer-gated drain (grace=False), mirror out of
        # cluster state like the chaos node-kill idiom
        try:
            store.delete("Node", name, grace=False)
        except Exception:  # solverlint: ok(swallowed-exception): NotFound race with a concurrent teardown — the node is gone either way, which is the goal
            pass
        self.env.cluster.delete_node(name)
        if claim is not None:
            try:
                store.delete("NodeClaim", claim, grace=False)
            except Exception:  # solverlint: ok(swallowed-exception): NotFound race with a concurrent teardown — the claim is gone either way, which is the goal
                pass
        return events

    def repack_savings(self, mode: str = "global", seed: int = 0) -> float:
        """faultline's revocation path as globalpack's second customer: after
        a spot reclaim (`revoke_node` / `apply_revocations`), measure the
        $/hr the chosen proposer's best EXACT-VALIDATED consolidation
        command would recover over the shrunken fleet. mode="global" runs
        the joint provisioning+retirement convex solve (the
        KARPENTER_SOLVER_GLOBALPACK path — orphaned pods still pending enter
        the solve as unconditional mass), mode="two-phase" the default
        greedy LP ladder. Nothing executes — the command is computed and
        scored only, so a bench gate can compare both modes on one fleet.
        Advances the fake clock past consolidate_after to surface candidates."""
        from ..controllers.disruption.methods import MultiNodeConsolidation, _command_savings_per_hour

        env = self.env
        env.clock.step(40)
        env.nodeclaim_disruption.reconcile()
        ctx = env.disruption.ctx
        ctx.round_candidates = env.disruption.get_candidates()
        ctx.node_pool_totals = None
        method = MultiNodeConsolidation(ctx)
        candidates = method.sort_candidates([c for c in ctx.round_candidates if method.should_disrupt(c)])
        if len(candidates) < 2:
            return 0.0
        deadline = ctx.clock.now() + 60.0
        if mode == "global":
            cmd = method._globalpack_option(candidates, deadline)
        else:
            cmd = method._lp_option(candidates, deadline)
        return _command_savings_per_hour(cmd) if cmd.candidates else 0.0

    def bind_flush(self) -> None:
        """Launch claims, register nodes, bind pending pods — the controller
        work between solves. Re-files newly bound pods from pending to bound."""
        self._log(op="bind_flush")
        env = self.env
        if hasattr(env.cloud_provider, "flush_pending"):
            env.cloud_provider.flush_pending()
        env.lifecycle.reconcile_all()
        if hasattr(env.cloud_provider, "flush_pending"):
            env.cloud_provider.flush_pending()
        env.lifecycle.reconcile_all()
        env.binder.bind_all()
        still = deque()
        for name in self._pending:
            pod = env.store.borrow_get("Pod", name, "default")
            if pod is None:
                continue
            if pod.spec.node_name:
                self._bound.append(name)
            else:
                still.append(name)
        self._pending = still

    def solve(self, force: bool = False):
        """Advance the fake clock past the idle window and pump one serving
        iteration (plus any coalesced drain generations). In fleet mode the
        pump goes through the FleetFrontend's DRR round — the push-wake path
        the watch events already armed — instead of the private loop."""
        self._log(op="solve", force=bool(force))
        self.env.clock.step(self.spec.batch_idle_seconds + 0.05)
        if self.fleet is not None:
            # scope to the attached tenant: a per-tenant warmup solve must
            # not fan out as a forced reconcile of every OTHER tenant
            served = self.fleet.pump(force=force, only=self._tenant_id)
            return served or None
        out = self.loop.pump(force=force)
        self.loop.drain()
        return out

    # -- phases ----------------------------------------------------------------
    def provision_base_fleet(self) -> None:
        """Create and bind the base fleet (cold compiles paid here)."""
        step = max(1, self.spec.n_base_pods // 4)
        created = 0
        while created < self.spec.n_base_pods:
            created += self.apply_arrivals(min(step, self.spec.n_base_pods - created))
            self.solve(force=True)
            self.bind_flush()
        # settle stragglers
        for _ in range(5):
            if not self._pending:
                break
            self.solve(force=True)
            self.bind_flush()

    def run_cycle(self, arrivals: int | None = None, cancels: int | None = None, departures: int | None = None) -> int:
        """One steady cycle: bind_every iterations of (arrivals + cancels +
        solve), with departures + bind flush on the cycle boundary. Returns
        events applied."""
        s = self.spec
        arrivals = s.arrivals if arrivals is None else arrivals
        cancels = s.cancels if cancels is None else cancels
        departures = s.departures if departures is None else departures
        events = 0
        for i in range(s.bind_every):
            events += self.apply_arrivals(arrivals)
            events += self.apply_cancels(cancels)
            self.solve()
            if i == s.bind_every - 1:
                events += self.apply_departures(departures)
                if self.injector is not None:
                    # spot-style revocation at the cycle boundary: forced
                    # departures + node teardown, then the bind flush lets
                    # the controllers start replacing the capacity
                    events += self.apply_revocations(self.injector.take_revocations())
                self.bind_flush()
        return events

    def run(self) -> ChurnReport:
        """Warmup cycles (cold compiles + high-water marks), then the
        measured steady phase. With `spec.replay_events` set, the recorded
        log drives everything instead (see run_replay)."""
        s = self.spec
        if s.replay_events is not None:
            return self.run_replay()
        if self.env is None:
            self.build()
        if self._event_log is not None:
            self._log(
                op="header",
                n_base_pods=s.n_base_pods, n_types=s.n_types, arrivals=s.arrivals,
                cancels=s.cancels, departures=s.departures, bind_every=s.bind_every,
                seed=s.seed, batch_idle_seconds=s.batch_idle_seconds,
                faults=(s.faults.to_dict() if s.faults is not None else None),
            )
        self.provision_base_fleet()
        # free steady-state headroom up front: arrivals land on capacity that
        # departures keep releasing; without this the first cycles would
        # create claims every solve (fleet growth, not steady churn)
        headroom = int((s.arrivals - s.cancels) * s.bind_every * 3)
        self.apply_departures(headroom)
        self.bind_flush()
        # bounding cycle: every churn-varying axis (pending backlog, delta
        # item count, removal count, nnz caps) is pushed PAST its steady-state
        # maximum so the high-water marks — and the one-time compiles they
        # imply — are all established before the sentinel mark; steady-state
        # batch variance then stays strictly inside compiled shapes
        self.run_cycle(
            arrivals=int(s.arrivals * 1.4) + 32,
            cancels=int(s.cancels * 1.6) + 32,
            departures=int(s.departures * 1.4) + 32,
        )
        for _ in range(s.warmup_cycles):
            self.run_cycle()
        # -- steady phase ------------------------------------------------------
        self.prebuild(s.arrivals * s.iterations)
        self._log(op="mark")
        mark = self.recorder.seq
        emark, slo0 = self._etracer_mark()
        rejects0 = self._reject_counts()
        recoveries0 = self._recovery_counts()
        coalesced0 = self.env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total()
        reused0 = self.loop.prestager.reused if self.loop.prestager is not None else 0
        staged0 = self.loop.prestager.staged if self.loop.prestager is not None else 0
        events = 0
        t0 = time.perf_counter()
        done = 0
        while done < s.iterations:
            events += self.run_cycle()
            done += s.bind_every
        wall = time.perf_counter() - t0
        rep = self._report(mark, events, wall, coalesced0, reused0, staged0, emark, slo0)
        rejects1 = self._reject_counts()
        rep.full_solve_reasons = {
            k: int(v - rejects0.get(k, 0)) for k, v in rejects1.items() if v > rejects0.get(k, 0)
        }
        self._fault_columns(rep, recoveries0)
        if s.concurrent_seconds > 0:
            cev, csolves = self.run_concurrent(s.concurrent_seconds)
            rep.concurrent_events = cev
            rep.concurrent_solves = csolves
            rep.coalesced_triggers = int(
                self.env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total() - coalesced0
            )
            # the zero-recompile claim covers the ENTIRE sustained run —
            # re-tally over every post-mark trace so a compile landing in
            # the concurrent segment (or its settle tail) fails the gate
            # instead of hiding outside the steady window
            recompiles: dict[str, int] = {}
            for t in self.recorder.traces():
                if t.seq > mark:
                    for fn, cnt in t.recompiles.items():
                        recompiles[fn] = recompiles.get(fn, 0) + cnt
            rep.recompiles = recompiles
            rep.steady_recompiles = sum(recompiles.values())
        if self._event_log is not None and s.record_path:
            self.dump_event_log(s.record_path)
        return rep

    # -- record/replay ---------------------------------------------------------
    def dump_event_log(self, path: str) -> int:
        """Write the recorded event stream as JSONL; returns ops written."""
        ops = self._event_log or []
        with open(path, "w") as f:
            for op in ops:
                f.write(json.dumps(op) + "\n")
        return len(ops)

    def apply_op(self, op: dict) -> int:
        """Apply one non-solve replay op; returns churn events applied.
        Solve ops are the DRIVER's job (run_replay calls self.solve; the
        multi-tenant bench paces them through the fleet pump instead)."""
        kind = op["op"]
        if kind == "arrive":
            pod = _make_pod(op["name"], op["cpu"], op["memory"], op.get("labels"), op.get("zone"))
            self.env.store.create(pod, adopt=True)
            self._pending.append(op["name"])
            self._record_events(1, "arrival")
            return 1
        if kind in ("cancel", "depart"):
            name = op["name"]
            if not self.env.store.try_delete("Pod", name, namespace="default"):
                return 0
            try:
                self._pending.remove(name)
            except ValueError:
                try:
                    self._bound.remove(name)
                except ValueError:
                    pass
            self._record_events(1, "departure")
            return 1
        if kind == "revoke":
            return self.revoke_node(op["node"])
        if kind == "bind_flush":
            self.bind_flush()
            return 0
        if kind in ("header", "mark"):
            return 0
        raise ValueError(f"unknown replay op {kind!r}")

    def run_replay(self) -> ChurnReport:
        """Drive the harness from `spec.replay_events`, deterministically:
        the recorded arrive/cancel/depart/solve/bind_flush sequence replays
        verbatim, the recorded `mark` op opens the measured window, and the
        report comes from the same machinery as a generated run."""
        s = self.spec
        if self.env is None:
            self.build()
        mark = self.recorder.seq
        emark, slo0 = self._etracer_mark()
        rejects0 = self._reject_counts()
        recoveries0 = self._recovery_counts()
        coalesced0 = self.env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total()
        reused0 = self.loop.prestager.reused if self.loop.prestager is not None else 0
        staged0 = self.loop.prestager.staged if self.loop.prestager is not None else 0
        events = 0
        t0 = time.perf_counter()
        for op in s.replay_events:
            kind = op["op"]
            if kind == "solve":
                self.solve(force=op.get("force", False))
            elif kind == "mark":
                # steady window opens HERE, exactly like the generated run
                mark = self.recorder.seq
                emark, slo0 = self._etracer_mark()
                rejects0 = self._reject_counts()
                recoveries0 = self._recovery_counts()
                coalesced0 = self.env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total()
                reused0 = self.loop.prestager.reused if self.loop.prestager is not None else 0
                staged0 = self.loop.prestager.staged if self.loop.prestager is not None else 0
                events = 0
                t0 = time.perf_counter()
            else:
                events += self.apply_op(op)
        wall = time.perf_counter() - t0
        rep = self._report(mark, events, wall, coalesced0, reused0, staged0, emark, slo0)
        rejects1 = self._reject_counts()
        rep.full_solve_reasons = {
            k: int(v - rejects0.get(k, 0)) for k, v in rejects1.items() if v > rejects0.get(k, 0)
        }
        self._fault_columns(rep, recoveries0)
        return rep

    def run_concurrent(self, seconds: float, batch: int | None = None) -> tuple[int, int]:
        """Wall-clock segment with a concurrent event driver: arrivals and
        cancellations land WHILE solves are in flight, so trigger bursts
        coalesce through the batcher's in-flight window into single
        follow-up solves. The driver paces itself against a pending-backlog
        cap (admission control): an unbounded flood would push the snapshot
        past the warmup's high-water shapes and turn the segment into a
        compile storm instead of a serving measurement. Returns (events
        applied, solves run)."""
        from ..obs.racecheck import make_event, spawn_thread

        stop = make_event()
        applied = [0]
        if batch is None:
            batch = max(20, self.spec.arrivals // 8)
        backlog_cap = self.spec.arrivals * max(2, self.spec.bind_every - 1)

        # declared in the thread-shared registry ([tool.solverlint]
        # thread-shared): the driver mutates only the store (lock-guarded),
        # the harness's deques (atomic append/pop, single consumer per end),
        # and the applied[0] cell it exclusively owns while running
        def _churn_driver():
            while not stop.is_set():
                if len(self._pending) < backlog_cap:
                    applied[0] += self.apply_arrivals(batch)
                    applied[0] += self.apply_cancels(int(batch * 0.75))
                time.sleep(0.001)

        solves0 = self.loop.solves
        t = spawn_thread(_churn_driver, name="churn-driver")
        deadline = time.perf_counter() + seconds
        try:
            while time.perf_counter() < deadline:
                self.solve()
        finally:
            stop.set()
            t.join(timeout=5)
        # settle the backlog the driver left behind
        for _ in range(5):
            if not self._pending:
                break
            self.solve(force=True)
            self.bind_flush()
        return applied[0], self.loop.solves - solves0

    def _reject_counts(self) -> dict:
        """Current delta-reject counter values by reason (cumulative)."""
        out: dict = {}
        for labels, v in self.env.registry.counter(m.SOLVER_DELTA_REJECT_TOTAL).collect():
            out[labels.get("reason", "?")] = v
        return out

    def _recovery_counts(self) -> dict:
        """Current recovery-ladder counter values by stage (cumulative)."""
        out: dict = {}
        for labels, v in self.env.registry.counter(m.SOLVER_RECOVERY_TOTAL).collect():
            out[labels.get("stage", "?")] = v
        return out

    def _fault_columns(self, rep: "ChurnReport", recoveries0: dict) -> None:
        """Fill the report's faultline columns (no-ops without an injector,
        except recoveries — the ladder also absorbs REAL failures)."""
        recov1 = self._recovery_counts()
        rep.recoveries = {
            k: int(v - recoveries0.get(k, 0)) for k, v in recov1.items() if v > recoveries0.get(k, 0)
        }
        prestager = self.loop.prestager if self.loop is not None else None
        rep.prestage_worker_restarts = prestager.restarts if prestager is not None else 0
        if self.injector is not None:
            rep.faults_injected = self.injector.summary()
            rep.revoked_nodes = int(rep.faults_injected.get("revocation", 0))

    def _etracer(self):
        """The environment's podtrace event tracer (None when off/absent)."""
        tr = getattr(self.env, "podtracer", None) if self.env is not None else None
        return tr if tr is not None and tr.enabled else None

    def _etracer_mark(self) -> tuple[int, int]:
        """(completed-event seq, SLO breach count) at the steady mark — the
        window the e2e report columns are computed over."""
        tr = self._etracer()
        return (tr.seq, tr.slo.breaches) if tr is not None else (0, 0)

    def _report(self, mark: int, events: int, wall: float, coalesced0: float = 0.0, reused0: int = 0, staged0: int = 0, emark: int = 0, slo0: int = 0) -> ChurnReport:
        traces = [t for t in self.recorder.traces() if t.seq > mark and t.mode not in ("", "consolidate")]
        durs = sorted(t.duration for t in traces)
        modes: dict[str, int] = {}
        recompiles: dict[str, int] = {}
        for t in traces:
            modes[t.mode] = modes.get(t.mode, 0) + 1
            for fn, n in t.recompiles.items():
                recompiles[fn] = recompiles.get(fn, 0) + n
        delta = modes.get("delta", 0) + modes.get("hybrid-delta", 0)
        eps = [t.n_pods for t in traces]
        rep = ChurnReport(
            events=events,
            wall_seconds=wall,
            events_per_sec=(events / wall) if wall > 0 else 0.0,
            solves=len(traces),
            modes=modes,
            delta_hit_rate=(delta / len(traces)) if traces else 0.0,
            p50_solve_seconds=quantile(durs, 0.50, assume_sorted=True) if durs else 0.0,
            p99_solve_seconds=quantile(durs, 0.99, assume_sorted=True) if durs else 0.0,
            recompiles=recompiles,
            steady_recompiles=sum(recompiles.values()),
            coalesced_triggers=int(self.env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total() - coalesced0),
            # pending-backlog size per solve, NOT the trigger-drain ratio
            # (that one is the karpenter_solver_churn_events_per_solve
            # histogram, fed from the batcher generation)
            pods_per_solve_p50=quantile(sorted(eps), 0.5, assume_sorted=True) if eps else 0.0,
            prestage_reused=(self.loop.prestager.reused - reused0) if self.loop.prestager is not None else 0,
            prestage_staged=(self.loop.prestager.staged - staged0) if self.loop.prestager is not None else 0,
            n_nodes=len(self.env.cluster.nodes()),
            n_pending_end=len(self._pending),
        )
        tr = self._etracer()
        if tr is not None:
            recs = tr.events_since(emark)
            if recs:
                stage_rows = [r.stage_view() for r in recs]
                e2e = sorted(s["e2e"] for s in stage_rows)
                rep.e2e_events = len(e2e)
                rep.e2e_p50_seconds = quantile(e2e, 0.50, assume_sorted=True)
                rep.e2e_p99_seconds = quantile(e2e, 0.99, assume_sorted=True)
                from ..obs.podtrace import STAGES

                rep.stage_p99_seconds = {
                    st: quantile(sorted(s[st] for s in stage_rows), 0.99, assume_sorted=True) for st in STAGES if st != "e2e"
                }
                # dominance over the ADDITIVE decomposition (coalesce +
                # sched_wait + solve == e2e); prestage overlaps and decode
                # trails placement, so neither can "dominate" the e2e
                means = {
                    st: sum(s[st] for s in stage_rows) / len(stage_rows)
                    for st in ("coalesce", "sched_wait", "solve")
                }
                rep.dominant_stage = max(means, key=means.get)
            rep.slo_breaches = tr.slo.breaches - slo0
        return rep
