"""shardfleet: horizontal multi-process fleet sharding with warm-cache scale-out.

The fleet front-end (serving/fleet.py) multiplexes K tenants in ONE process
and names horizontal sharding as its growth axis: one serve loop is the hard
ceiling on aggregate events/sec, and the bounded tenant-label cap collapses
to "overflow" past TENANT_LABEL_CAP tenants. This module is the tenant →
PROCESS scale-out: a `ShardRouter` spawns N shard worker processes (each
running its own `FleetFrontend` serve loop over a private slice of tenants)
and fronts them as one fleet.

Mechanisms, in dependency order:

- CONSISTENT-HASH PLACEMENT (`ShardRing`): tenant→shard assignment hashes
  both shard vnodes and tenant ids onto one 64-bit ring with
  `hashlib.blake2b` — NEVER the builtin `hash()`, whose per-process
  PYTHONHASHSEED randomization would scatter assignments across router
  restarts. Adding/removing a shard only re-homes the tenants whose ring
  successor changed (the moved fraction is bounded near T/N), and the
  assignment is a pure function of the shard-id set: bit-stable across
  restarts and identical in every process.
- WARM-CACHE SCALE-OUT: every shard worker inherits one shared persistent
  `KARPENTER_SOLVER_COMPILE_CACHE` directory (configure_compile_cache is
  first-writer-wins race-safe), so shard N+1's cold start finds shard 1's
  compiled executables on disk and records zero XLA compiles.
- DEVICE PARTITIONING: each worker gets `KARPENTER_SOLVER_SHARD_DEVICES=
  "<index>/<n>"` so `parallel.sharded.default_mesh` builds its mesh over
  that shard's contiguous device slice instead of all shards contending for
  every chip (SNIPPETS.md [1] generalized beyond one process).
- CROSS-SHARD AGGREGATION: each worker runs a loopback OperatorServer; the
  router scrapes and merges /debug/tenants (rows stamped with their shard),
  proxies /debug/solves + /debug/events by ?tenant= to the owning shard,
  and merges the `karpenter_solver_fleet_*` metric families with an
  injected bounded `shard` label (`shard_label`, the `shard` entry in
  solverlint's bounded_label_producers).
- SHARD FAILURE DOMAINS: a per-shard `CircuitBreaker` (the faultline
  pattern, reused verbatim from serving/faults.py) quarantines a shard
  whose pings/commands fail and exponential-backoff re-probes it. A dead
  shard's tenants RE-HOME: the router replays each tenant's recorded
  ChurnSpec JSONL — filtered to that tenant via
  `ChurnSpec.from_event_log(tenant=...)` — into a surviving (or respawned)
  shard, and the rebuilt placement digests bit-identically to the dead
  shard's last run (`placement_digest`).

Wire protocol: one JSON object per line over the worker's stdin/stdout,
each response line prefixed with "KSHARD " so stray library output can
never corrupt framing. The worker emits a ready line before importing
anything heavy; jax/fleet imports are paid lazily on the first add_tenant.

Threading (racecheck): `ShardRouter._drive_shard` threads fan run_all out
across shards (one writer per results key), `ShardRouter._monitor_loop` is
the optional health prober, and the worker-side `_tick_loop` steps live
tenant environments — all registered in [tool.solverlint] thread-shared.
Locks: `shard-router` and `shard-handle` are LEAF locks (never held across
a solve or another lock); handle I/O serializes per shard under
`shard-handle` so concurrent router calls cannot interleave frames.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import subprocess
import sys
import time

from ..obs.racecheck import make_event, make_lock, spawn_thread
from .faults import TENANT_STATES, CircuitBreaker

_WIRE = "KSHARD "

# distinct shard label values the bounded `shard` metric label may carry
# before collapsing to "overflow" — same contract as fleet.TENANT_LABEL_CAP
# (and the same solverlint max-label-values ceiling backstops both)
SHARD_LABEL_CAP = 12
_SHARD_LABELS: dict[str, str] = {}
_SHARD_LABELS_LOCK = make_lock("shard-labels")


def shard_label(shard_id: str) -> str:
    """The BOUNDED metric label for a shard id: first SHARD_LABEL_CAP
    distinct ids keep their sanitized form, later ones collapse to
    "overflow"; colliding sanitized forms get a numeric disambiguator.
    This is the `shard` entry in solverlint's bounded_label_producers —
    every `shard=` label value on a counter/histogram must come from
    here (or carry a justified pragma)."""
    shard_id = str(shard_id)
    with _SHARD_LABELS_LOCK:
        label = _SHARD_LABELS.get(shard_id)
        if label is not None:
            return label
        if len(_SHARD_LABELS) >= SHARD_LABEL_CAP:
            label = "overflow"
        else:
            base = "".join(c if (c.isalnum() or c in "-_.") else "-" for c in shard_id)[:60] or "default"
            used = set(_SHARD_LABELS.values()) | {"overflow"}
            label, n = base, 2
            while label in used:
                label, n = f"{base}-{n}", n + 1
        _SHARD_LABELS[shard_id] = label
        return label


def reset_shard_labels() -> None:
    """Drop the process-global shard-label assignments (test isolation)."""
    with _SHARD_LABELS_LOCK:
        _SHARD_LABELS.clear()


def placement_digest(env) -> str:
    """Content digest of a tenant's node-name-free placement structure:
    one (instance-type, zone, sorted pod names) triple per node, sorted.
    Random claim-name suffixes never enter, so two independent replays of
    the same log digest identically iff their placements match — the
    bit-identical re-homing check, comparable ACROSS processes."""
    from ..apis import labels as wk

    nodes = {n.metadata.name: n for n in env.store.list("Node")}
    groups: dict[str, list] = {}
    for p in env.store.list("Pod"):
        if p.spec.node_name:
            groups.setdefault(p.spec.node_name, []).append(p.metadata.name)
    shape = []
    for name, pods in groups.items():
        labels = nodes[name].metadata.labels if name in nodes else {}
        shape.append(
            (labels.get(wk.INSTANCE_TYPE_LABEL_KEY) or "", labels.get(wk.ZONE_LABEL_KEY) or "", sorted(pods))
        )
    shape.sort()
    return hashlib.sha256(json.dumps(shape, sort_keys=True).encode()).hexdigest()


class ShardRing:
    """Consistent-hash ring mapping tenant ids onto shard ids. Each shard
    contributes `replicas` vnodes; a tenant is owned by its clockwise
    successor. Points come from blake2b (process/seed-independent — the
    builtin hash() is PYTHONHASHSEED-randomized and would break cross-
    process agreement), so the whole assignment is a pure, bit-stable
    function of the shard-id set. Not itself thread-safe: the router
    mutates it only under the shard-router lock."""

    def __init__(self, shards=(), replicas: int = 64):
        self.replicas = int(replicas)
        self._points: list[tuple[int, str]] = []
        self._shards: set[str] = set()
        for s in shards:
            self.add(s)

    @staticmethod
    def _point(key: str) -> int:
        return int.from_bytes(hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")

    def add(self, shard_id: str) -> None:
        shard_id = str(shard_id)
        if shard_id in self._shards:
            return
        self._shards.add(shard_id)
        for r in range(self.replicas):
            bisect.insort(self._points, (self._point(f"shard:{shard_id}:{r}"), shard_id))

    def remove(self, shard_id: str) -> None:
        shard_id = str(shard_id)
        if shard_id not in self._shards:
            return
        self._shards.discard(shard_id)
        self._points = [p for p in self._points if p[1] != shard_id]

    def shards(self) -> list[str]:
        return sorted(self._shards)

    def assign(self, tenant_id: str) -> str:
        if not self._points:
            raise ValueError("ShardRing has no shards")
        p = self._point(f"tenant:{tenant_id}")
        # (p,) sorts before every (p, shard) pair, so bisect_right lands on
        # the first vnode with point >= p — the clockwise successor
        i = bisect.bisect_right(self._points, (p,))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def assignments(self, tenant_ids) -> dict[str, str]:
        return {t: self.assign(t) for t in tenant_ids}


class ShardDead(RuntimeError):
    """The shard process is gone (EOF/broken pipe/never started)."""


class ShardError(RuntimeError):
    """The shard is alive but the command failed (ok=false response)."""


def _http_get(port: int, path: str, timeout: float = 5.0) -> str:
    import urllib.request

    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.read().decode()


class ShardHandle:
    """The router's end of one shard worker process: owns the Popen and
    serializes the line protocol. All pipe I/O runs under the handle lock,
    so two router threads calling into the same shard can never interleave
    request/response frames (the readline is plain pipe I/O, not a listed
    blocking call — safe under a leaf lock)."""

    GUARDED_FIELDS = {"_proc": "_lock"}

    def __init__(self, shard_id: str, cmd: list[str], env: dict):
        self.shard_id = shard_id
        self._lock = make_lock("shard-handle")
        with self._lock:
            self._proc = subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True, bufsize=1
            )

    @staticmethod
    def _read_msg(proc) -> dict:
        # skip any non-protocol line a library printed to stdout; EOF means
        # the worker died (crash cmd, kill, import failure)
        while True:
            line = proc.stdout.readline()
            if not line:
                raise ShardDead("worker closed its protocol stream")
            if line.startswith(_WIRE):
                return json.loads(line[len(_WIRE):])

    def wait_ready(self) -> dict:
        """Block for the worker's boot banner (emitted before any heavy
        import, so a successful spawn acks fast; a failed interpreter start
        surfaces as EOF→ShardDead rather than a hang)."""
        return self.call("__ready__")

    def call(self, cmd: str, **kw) -> dict:
        with self._lock:
            proc = self._proc
            if proc is None or proc.poll() is not None:
                raise ShardDead(f"shard {self.shard_id} is not running")
            try:
                if cmd != "__ready__":
                    proc.stdin.write(json.dumps({"cmd": cmd, **kw}) + "\n")
                    proc.stdin.flush()
                resp = self._read_msg(proc)
            except (OSError, ValueError) as e:
                raise ShardDead(f"shard {self.shard_id} died mid-call: {e}") from e
        if not resp.get("ok"):
            raise ShardError(f"shard {self.shard_id}: {resp.get('error', 'unknown shard error')}")
        return resp

    def alive(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    def kill(self) -> None:
        """Hard-kill the worker (shard-death injection for tests/bench)."""
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()
        if proc is not None:
            proc.wait(timeout=10)

    def close(self, graceful: bool = True) -> None:
        if graceful and self.alive():
            try:
                self.call("shutdown")
            except (ShardDead, ShardError):
                pass  # already dying — the kill below reaps it either way
        self.kill()


class ShardRouter:
    """The fleet-of-fleets front: spawns N shard worker processes, assigns
    tenants by consistent hashing, shares one persistent compile cache
    across them, aggregates their debug/metric surfaces, and re-homes a
    dead shard's tenants by tenant-filtered log replay (see module doc).
    Deterministic drivers call run_all()/run_tenant(); live deployments
    call start_serving() + start_monitor()."""

    GUARDED_FIELDS = {
        "_handles": "_lock",
        "_ports": "_lock",
        "_indexes": "_lock",
        "_tenants": "_lock",
        "_breakers": "_lock",
        "_monitor_thread": "_lock",
        "_monitor_stop": "_lock",
    }

    def __init__(
        self,
        n_shards: int = 2,
        registry=None,
        cache_dir: str | None = None,
        solver: str = "tpu",
        worker_env: dict | None = None,
        breaker_failures: int = 1,
        breaker_backoff_seconds: float = 0.2,
        breaker_backoff_max: float = 30.0,
    ):
        from ..metrics import make_registry

        self.n_shards = int(n_shards)
        self.registry = registry if registry is not None else make_registry()
        self.cache_dir = cache_dir
        self.solver = solver
        self.worker_env = dict(worker_env or {})
        self.breaker_failures = int(breaker_failures)
        self.breaker_backoff_seconds = float(breaker_backoff_seconds)
        self.breaker_backoff_max = float(breaker_backoff_max)
        self.ring = ShardRing()
        self._lock = make_lock("shard-router")
        self._handles: dict[str, ShardHandle] = {}
        self._ports: dict[str, int] = {}
        self._indexes: dict[str, int] = {}
        # tenant registry: log/overrides/solver for re-homing replay, owning
        # shard, and the last known placement digest
        self._tenants: dict[str, dict] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        self._monitor_thread = None
        self._monitor_stop = None

    # -- shard lifecycle -------------------------------------------------------
    def spawn(self) -> list[str]:
        """Spawn all N shard workers and seat them on the ring."""
        for i in range(self.n_shards):
            self._spawn_shard(f"shard-{i}", i)
        self._publish_topology()
        return self.shards()

    def _spawn_shard(self, shard_id: str, index: int) -> ShardHandle:
        env = dict(os.environ)
        env.update(self.worker_env)
        env["KARPENTER_SOLVER_SHARD_ID"] = shard_id
        # contiguous device slice i of N (parallel.sharded.default_mesh)
        env["KARPENTER_SOLVER_SHARD_DEVICES"] = f"{index}/{self.n_shards}"
        if self.cache_dir:
            env["KARPENTER_SOLVER_COMPILE_CACHE"] = self.cache_dir
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        handle = ShardHandle(shard_id, [sys.executable, "-m", "karpenter_tpu.serving.shard"], env)
        handle.wait_ready()
        breaker = CircuitBreaker(
            failures_to_open=self.breaker_failures,
            backoff_seconds=self.breaker_backoff_seconds,
            backoff_max=self.breaker_backoff_max,
        )
        with self._lock:
            self._handles[shard_id] = handle
            self._indexes[shard_id] = index
            # a respawned shard keeps its breaker history (opens count)
            self._breakers.setdefault(shard_id, breaker)
            self.ring.add(shard_id)
        return handle

    def respawn(self, shard_id: str) -> ShardHandle:
        """Replace a dead shard's process (the breaker's probe path brings
        it back to healthy on the next successful check)."""
        from .. import metrics as m

        with self._lock:
            old = self._handles.pop(shard_id, None)
            self._ports.pop(shard_id, None)
            index = self._indexes.get(shard_id, len(self._indexes))
        if old is not None:
            old.kill()
        handle = self._spawn_shard(shard_id, index)
        self.registry.counter(m.SOLVER_SHARD_RESTARTS_TOTAL).inc(shard=shard_label(shard_id))
        self._publish_topology()
        return handle

    def shards(self) -> list[str]:
        with self._lock:
            return sorted(self._handles)

    def _handle(self, shard_id: str) -> ShardHandle:
        with self._lock:
            handle = self._handles.get(shard_id)
        if handle is None:
            raise ShardDead(f"shard {shard_id} has no process")
        return handle

    def ready(self) -> bool:
        """Router readiness: every seated shard's breaker is healthy and at
        least one shard process is up."""
        with self._lock:
            handles = dict(self._handles)
            breakers = dict(self._breakers)
        if not handles:
            return False
        alive = any(h.alive() for h in handles.values())
        return alive and all(b.state_name() == "healthy" for b in breakers.values())

    # -- tenant placement ------------------------------------------------------
    def assign(self, tenant_id: str) -> str:
        with self._lock:
            return self.ring.assign(tenant_id)

    def add_tenant(self, tenant_id: str, log_path: str | None = None, overrides: dict | None = None, solver: str | None = None) -> str:
        """Seat a tenant on its ring-assigned shard. With `log_path`, the
        shard builds a ChurnHarness replaying that log filtered to this
        tenant (the deterministic drive + re-homing substrate); without it,
        a live wall-clock tenant session."""
        sid = self.assign(tenant_id)
        handle = self._handle(sid)
        resp = handle.call(
            "add_tenant",
            tenant=tenant_id,
            log=log_path,
            overrides=dict(overrides or {}),
            solver=solver or self.solver,
        )
        with self._lock:
            self._tenants[tenant_id] = {
                "log": log_path,
                "overrides": dict(overrides or {}),
                "solver": solver or self.solver,
                "shard": sid,
                "digest": None,
            }
            if resp.get("port"):
                self._ports[sid] = int(resp["port"])
        return sid

    def tenants(self) -> dict[str, str]:
        with self._lock:
            return {t: rec["shard"] for t, rec in self._tenants.items()}

    # -- deterministic drive ---------------------------------------------------
    def run_tenant(self, tenant_id: str) -> dict:
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        resp = self._handle(rec["shard"]).call("run_tenant", tenant=tenant_id)
        with self._lock:
            self._tenants[tenant_id]["digest"] = resp.get("digest")
        return resp

    def run_all(self) -> dict[str, dict]:
        """Replay every shard's tenants, all shards IN PARALLEL (each shard
        is its own process — this is the scale-out measurement path).
        Returns {shard_id: run_all response}; failed shards get an
        ok=False row and a breaker failure."""
        with self._lock:
            handles = dict(self._handles)
        results: dict[str, dict] = {}
        threads = [
            spawn_thread(self._drive_shard, name=f"karpenter-shard-drive-{sid}", args=(sid, h, results))
            for sid, h in sorted(handles.items())
        ]
        for t in threads:
            t.join()
        with self._lock:
            for sid, res in results.items():
                if res.get("ok"):
                    for tid, row in (res.get("tenants") or {}).items():
                        if tid in self._tenants:
                            self._tenants[tid]["digest"] = row.get("digest")
        return results

    def _drive_shard(self, sid: str, handle: ShardHandle, results: dict) -> None:
        # one writer per key: this thread exclusively owns results[sid]
        try:
            results[sid] = handle.call("run_all")
        except (ShardDead, ShardError) as e:
            results[sid] = {"ok": False, "error": str(e)}
            self._record_shard_failure(sid, e)

    # -- failure domains -------------------------------------------------------
    def _breaker(self, shard_id: str) -> CircuitBreaker | None:
        with self._lock:
            return self._breakers.get(shard_id)

    def _record_shard_failure(self, shard_id: str, err) -> None:
        breaker = self._breaker(shard_id)
        if breaker is not None:
            breaker.record_failure(err)
            self._publish_shard_states()

    def check_shards(self) -> dict[str, str]:
        """One health pass: ping every shard whose breaker admits traffic;
        failures quarantine the shard (backoff-gated re-probes, exactly the
        per-tenant faultline ladder). Returns {shard_id: breaker state}."""
        with self._lock:
            rows = [(sid, self._handles.get(sid), self._breakers.get(sid)) for sid in sorted(self._breakers)]
        out: dict[str, str] = {}
        for sid, handle, breaker in rows:
            if breaker is None:
                continue
            if not breaker.allow():
                out[sid] = breaker.state_name()
                continue
            try:
                if handle is None:
                    raise ShardDead(f"shard {sid} has no process")
                handle.call("ping")
                breaker.record_success()
            except (ShardDead, ShardError) as e:
                breaker.record_failure(e)
            out[sid] = breaker.state_name()
        self._publish_shard_states()
        return out

    def rehome_tenants(self, shard_id: str, respawn: bool = False) -> dict:
        """Re-home a dead shard's tenants (the shard-death contract): pull
        the shard off the ring (or respawn it fresh), then for each
        orphaned tenant replay its recorded log — filtered to that tenant —
        into its new ring home and check the rebuilt placement digests
        BIT-IDENTICALLY against the dead shard's last run. Returns
        {tenant: {shard, digest, matches}}."""
        from .. import metrics as m

        with self._lock:
            handle = self._handles.pop(shard_id, None)
            self._ports.pop(shard_id, None)
            orphans = [(t, dict(rec)) for t, rec in self._tenants.items() if rec.get("shard") == shard_id]
            if not respawn:
                # decommission: off the ring AND out of the breaker map — a
                # shard that no longer exists must not hold ready() hostage
                self.ring.remove(shard_id)
                self._breakers.pop(shard_id, None)
                self._indexes.pop(shard_id, None)
        if handle is not None:
            handle.close(graceful=False)
        if not respawn:
            # stale-series hygiene (the remove_tenant pattern): zero every
            # state series for the decommissioned shard
            g = self.registry.gauge(m.SOLVER_SHARD_STATE)
            for s in TENANT_STATES:
                g.set(0.0, shard=shard_label(shard_id), state=s)  # solverlint: ok(metric-label-cardinality): state iterates the static TENANT_STATES enum (shard is already the bounded shard_label producer)
        if respawn:
            self.respawn(shard_id)
        self._publish_topology()
        out: dict[str, dict] = {}
        for tid, rec in sorted(orphans):
            new_sid = self.assign(tid)
            new_handle = self._handle(new_sid)
            new_resp = new_handle.call(
                "add_tenant", tenant=tid, log=rec["log"], overrides=rec["overrides"], solver=rec["solver"]
            )
            row: dict = {"shard": new_sid}
            if rec.get("log"):
                replay = new_handle.call("run_tenant", tenant=tid)
                row["digest"] = replay.get("digest")
                row["matches"] = rec.get("digest") is None or rec["digest"] == row["digest"]
            with self._lock:
                self._tenants[tid]["shard"] = new_sid
                if "digest" in row:
                    self._tenants[tid]["digest"] = row["digest"]
                if new_resp.get("port"):
                    self._ports[new_sid] = int(new_resp["port"])
            self.registry.counter(m.SOLVER_SHARD_REHOMED_TOTAL).inc(shard=shard_label(new_sid))
            out[tid] = row
        return out

    # -- aggregation -----------------------------------------------------------
    def _shard_ports(self) -> dict[str, int]:
        with self._lock:
            return {sid: p for sid, p in self._ports.items() if p}

    def debug_tenants(self) -> dict:
        """The merged /debug/tenants payload: every shard's per-tenant
        breaker/backlog rows, each stamped with its shard id; tenants whose
        shard is unreachable still get a row naming the owner."""
        out: dict = {}
        for sid, port in sorted(self._shard_ports().items()):
            try:
                body = json.loads(_http_get(port, "/debug/tenants"))
            except (OSError, ValueError) as e:
                out[f"__shard_{sid}__"] = {"shard": sid, "error": str(e)}
                continue
            for tid, row in (body.get("tenants") or {}).items():
                row["shard"] = sid
                out[tid] = row
        for tid, sid in self.tenants().items():
            out.setdefault(tid, {"shard": sid, "error": "shard unreachable"})
        return out

    def debug_shards(self) -> dict:
        """Per-shard router rows: liveness, breaker snapshot, debug port,
        ring index, and seated tenants."""
        with self._lock:
            sids = sorted(set(self._handles) | set(self._breakers))
            handles = dict(self._handles)
            ports = dict(self._ports)
            indexes = dict(self._indexes)
            breakers = dict(self._breakers)
            owners: dict[str, list] = {}
            for tid, rec in self._tenants.items():
                owners.setdefault(rec["shard"], []).append(tid)
        out: dict = {}
        for sid in sids:
            handle = handles.get(sid)
            row = {
                "index": indexes.get(sid),
                "port": ports.get(sid, 0),
                "alive": handle.alive() if handle is not None else False,
                "tenants": sorted(owners.get(sid, [])),
            }
            breaker = breakers.get(sid)
            if breaker is not None:
                row.update(breaker.snapshot())
            out[sid] = row
        return out

    def _proxy(self, route: str, tenant: str, n=None) -> str:
        """Proxy a per-tenant debug route to the shard that serves it:
        owner-first (by registered tenant id), then fan out — queries
        address tenants by their metric LABEL, which each shard assigns
        locally, so the id→label mapping is only a heuristic."""
        import urllib.parse

        query = f"?tenant={urllib.parse.quote(str(tenant))}"
        if n is not None:
            query += f"&n={int(n)}"
        ports = self._shard_ports()
        owner = self.tenants().get(tenant)
        order = ([owner] if owner in ports else []) + [s for s in sorted(ports) if s != owner]
        last_err: Exception | None = None
        for sid in order:
            try:
                return _http_get(ports[sid], route + query)
            except OSError as e:
                last_err = e
        raise KeyError(f"no shard serves tenant {tenant!r}: {last_err}")

    def debug_solves(self, tenant: str, n=None) -> str:
        return self._proxy("/debug/solves", tenant, n)

    def debug_events(self, tenant: str, n=None) -> str:
        return self._proxy("/debug/events", tenant, n)

    def merged_metrics(self) -> str:
        """The router's /metrics body: its own registry (shard topology,
        restarts, re-homed counts) plus every shard's
        `karpenter_solver_fleet_*` samples with an injected bounded
        `shard` label, HELP/TYPE headers deduplicated across shards."""
        parts = [self.registry.expose()]
        # the router's own registry registers the same metric families every
        # make_registry() build does, so its HELP/TYPE headers seed the dedupe
        seen_meta: set = set()
        for line in parts[0].splitlines():
            if line.startswith("#"):
                toks = line.split()
                if len(toks) >= 3:
                    seen_meta.add((toks[1], toks[2]))
        for sid, port in sorted(self._shard_ports().items()):
            try:
                text = _http_get(port, "/metrics")
            except OSError:
                continue  # a dead shard's series simply drop out of the scrape
            label = shard_label(sid)
            lines = []
            for line in text.splitlines():
                if line.startswith("#"):
                    toks = line.split()
                    if len(toks) >= 3 and toks[2].startswith("karpenter_solver_fleet_"):
                        if (toks[1], toks[2]) not in seen_meta:
                            seen_meta.add((toks[1], toks[2]))
                            lines.append(line)
                    continue
                if not line.startswith("karpenter_solver_fleet_"):
                    continue
                if "{" in line:
                    name, rest = line.split("{", 1)
                    lines.append(f'{name}{{shard="{label}",{rest}')
                else:
                    name, _, val = line.partition(" ")
                    lines.append(f'{name}{{shard="{label}"}} {val}')
            if lines:
                parts.append("\n".join(lines))
        return "\n".join(parts)

    def stats(self) -> dict:
        """Cross-shard stats merge (the deterministic-driver counterpart of
        merged_metrics): {shard: stats response or error row}."""
        out: dict = {}
        for sid in self.shards():
            try:
                out[sid] = self._handle(sid).call("stats")
            except (ShardDead, ShardError) as e:
                out[sid] = {"ok": False, "error": str(e)}
                self._record_shard_failure(sid, e)
        return out

    # -- live serving ----------------------------------------------------------
    def start_serving(self, tick_seconds: float = 0.5) -> None:
        """Start every shard's wall-clock serve loop + env tick thread."""
        for sid in self.shards():
            self._handle(sid).call("start", tick_seconds=tick_seconds)

    def start_monitor(self, interval_seconds: float = 1.0) -> None:
        with self._lock:
            if self._monitor_thread is not None:
                return
            self._monitor_stop = make_event()
            self._monitor_thread = spawn_thread(
                self._monitor_loop,
                name="karpenter-shard-monitor",
                args=(self._monitor_stop, float(interval_seconds)),
            )

    def stop_monitor(self) -> None:
        with self._lock:
            t, self._monitor_thread = self._monitor_thread, None
            stop, self._monitor_stop = self._monitor_stop, None
        if stop is not None:
            stop.set()
        if t is not None:
            t.join(timeout=5)

    def _monitor_loop(self, stop, interval: float) -> None:
        while not stop.wait(timeout=interval):
            self.check_shards()

    def close(self) -> None:
        self.stop_monitor()
        with self._lock:
            handles = dict(self._handles)
            self._handles.clear()
            self._ports.clear()
        for h in handles.values():
            h.close()

    # -- metric publication ----------------------------------------------------
    def _publish_topology(self) -> None:
        from .. import metrics as m

        with self._lock:
            n = len(self._handles)
        self.registry.gauge(m.SOLVER_FLEET_SHARDS).set(n)

    def _publish_shard_states(self) -> None:
        from .. import metrics as m

        with self._lock:
            states = {sid: b.state_name() for sid, b in self._breakers.items()}
        g = self.registry.gauge(m.SOLVER_SHARD_STATE)
        for sid, state in states.items():
            label = shard_label(sid)
            for s in TENANT_STATES:
                g.set(1.0 if s == state else 0.0, shard=label, state=s)  # solverlint: ok(metric-label-cardinality): state iterates the static TENANT_STATES enum; shard label is a shard_label() output


# -- the shard worker process -------------------------------------------------


def _emit(payload: dict) -> None:
    sys.stdout.write(_WIRE + json.dumps(payload) + "\n")
    sys.stdout.flush()


def _tick_loop(stop, fleet, tick_seconds: float) -> None:
    """Live-mode controller tick for every tenant env in this shard
    (lifecycle/binder progress; the serve loop owns solves). Registered in
    [tool.solverlint] thread-shared."""
    while not stop.wait(timeout=tick_seconds):
        for sess in fleet.sessions().values():
            sess.env.tick(provision=False)


class _ShardWorker:
    """One shard process's command executor: a private FleetFrontend over
    this shard's tenants, a ChurnHarness per replay-driven tenant, and a
    lazily-started loopback OperatorServer the router scrapes. Heavy
    imports (jax, the fleet) are deferred to the first add_tenant so spawn
    acks fast. Single protocol thread: commands execute strictly in
    arrival order, so no locking beyond what fleet/loop already carry."""

    def __init__(self, shard_id: str):
        self.shard_id = shard_id
        self.fleet = None
        self.harnesses: dict[str, object] = {}
        self.server = None
        self.port = 0
        self._tick_stop = None
        self._tick_thread = None

    def _ensure_fleet(self):
        if self.fleet is None:
            from .fleet import FleetFrontend

            self.fleet = FleetFrontend()
        return self.fleet

    def _ensure_server(self) -> int:
        if self.server is None and self.fleet is not None:
            sessions = self.fleet.sessions()
            if sessions:
                from ..operator.server import OperatorServer

                sess = next(iter(sessions.values()))
                self.server = OperatorServer(sess.env, port=0, bind="127.0.0.1")
                self.port = self.server.start()
        return self.port

    # -- commands (cmd_<name>, dispatched by _worker_main) ---------------------
    def cmd_ping(self, req: dict) -> dict:
        return {"shard": self.shard_id, "pid": os.getpid(), "tenants": sorted(self.harnesses)}

    def cmd_add_tenant(self, req: dict) -> dict:
        from ..cloudprovider.fake import instance_types_assorted
        from ..operator.options import Options
        from .churn import ChurnHarness, ChurnSpec

        fleet = self._ensure_fleet()
        tid = req["tenant"]
        solver = req.get("solver", "tpu")
        overrides = dict(req.get("overrides") or {})
        if req.get("log"):
            # replay-driven tenant: the recorded log, filtered to THIS
            # tenant's ops (the re-homing contract), drives the harness
            spec = ChurnSpec.from_event_log(req["log"], tenant=tid, **overrides)
            opts = Options(
                solver_backend=solver,
                batch_idle_duration=spec.batch_idle_seconds,
                batch_max_duration=10.0,
            )
            sess = fleet.add_tenant(tid, options=opts, instance_types=instance_types_assorted(spec.n_types))
            self.harnesses[tid] = ChurnHarness(spec).attach(sess, fleet=fleet)
        else:
            from ..utils.clock import Clock

            fleet.add_tenant(tid, options=Options(solver_backend=solver), clock=Clock())
        return {"tenant": tid, "port": self._ensure_server()}

    def _run_one(self, tid: str) -> dict:
        import random

        h = self.harnesses[tid]
        # re-seed per REPLAY, not just per attach: successive replays in one
        # worker consume the global RNG, so without this a tenant's placement
        # would depend on its position in the run order — and a re-homed
        # replay on a warm survivor shard could never digest bit-identically
        random.seed(h.spec.seed)
        rep = h.run()
        return {"report": rep.as_dict(), "digest": placement_digest(h.env)}

    def cmd_run_tenant(self, req: dict) -> dict:
        tid = req["tenant"]
        if tid not in self.harnesses:
            raise KeyError(f"tenant {tid!r} has no replay harness on shard {self.shard_id}")
        row = self._run_one(tid)
        return {"tenant": tid, **row}

    def cmd_run_all(self, req: dict) -> dict:
        t0 = time.perf_counter()
        rows = {tid: self._run_one(tid) for tid in sorted(self.harnesses)}
        events = sum(r["report"]["events"] for r in rows.values())
        return {"tenants": rows, "events": events, "wall_seconds": round(time.perf_counter() - t0, 3)}

    def cmd_stats(self, req: dict) -> dict:
        fleet = self.fleet
        return {
            "shard": self.shard_id,
            "port": self.port,
            "tenants": sorted(self.harnesses),
            "fleet": fleet.stats() if fleet is not None else {},
        }

    def cmd_start(self, req: dict) -> dict:
        from ..obs.racecheck import make_event as mk_event

        fleet = self._ensure_fleet()
        fleet.start()
        if self._tick_thread is None:
            self._tick_stop = mk_event()
            self._tick_thread = spawn_thread(
                _tick_loop,
                name="karpenter-shard-tick",
                args=(self._tick_stop, fleet, float(req.get("tick_seconds", 0.5))),
            )
        return {"serving": True}

    def cmd_crash(self, req: dict) -> dict:
        # shard-death injection: die WITHOUT a response, so the router's
        # in-flight call sees EOF (ShardDead), exactly like a real crash
        os._exit(1)

    def cmd_shutdown(self, req: dict) -> dict:
        return {"bye": True}

    def close(self) -> None:
        if self._tick_stop is not None:
            self._tick_stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        if self.server is not None:
            self.server.stop()
        if self.fleet is not None:
            self.fleet.close()


def _worker_main() -> int:
    shard_id = os.environ.get("KARPENTER_SOLVER_SHARD_ID", "shard-0")
    # boot banner BEFORE any heavy import: the router's wait_ready acks on
    # this line, so spawn latency is interpreter start, not jax import
    _emit({"ok": True, "event": "ready", "shard": shard_id, "pid": os.getpid()})
    worker = _ShardWorker(shard_id)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        cmd = ""
        try:
            req = json.loads(line)
            cmd = req.get("cmd", "")
            fn = getattr(worker, f"cmd_{cmd}", None)
            if fn is None or cmd.startswith("_"):
                _emit({"ok": False, "error": f"unknown command {cmd!r}"})
                continue
            resp = fn(req)
        except Exception as e:
            # recorded (stderr log) and serialized onto the wire — the
            # router raises it as ShardError and its breaker counts it
            logging.getLogger("karpenter.shard").error("shard %s command %r failed: %s", shard_id, cmd, e)
            _emit({"ok": False, "error": f"{type(e).__name__}: {e}"})
            continue
        _emit({"ok": True, **(resp or {})})
        if cmd == "shutdown":
            break
    worker.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(_worker_main())
