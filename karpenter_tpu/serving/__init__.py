"""Steady-state churn serving: the solver as a long-lived service.

Every bench before this subsystem measured one-shot or single-warm-re-solve
latency; a production deployment is a long-lived `Provisioner`+`TPUSolver`
under sustained pod arrivals/departures from millions of users. This package
makes that regime first-class:

- `prestage.PendingPrestager` — the serving loop's double buffer: the NEXT
  solve's host-side encode/classify work (pod clone, validation verdict,
  signature stamping) runs while the CURRENT solve's device pack is in
  flight, and clone identity is preserved across solves so the encoder can
  classify consecutive serving snapshots as pod deltas.
- `loop.ServingLoop` — wires a prestager into a live Provisioner and pumps
  coalesced solves (the batcher's in-flight-aware drain: N triggers during a
  solve cost ONE batched follow-up solve).
- `churn.ChurnHarness` — drives sustained arrivals/departures against the
  live stack and reports throughput (pod-events/sec), P50/P99 re-solve
  latency, delta-hit rate, and the recompile count (the zero-steady-state
  gate, via the solvetrace sentinel). Gains record/replay: the generated
  event stream dumps as JSONL and `ChurnSpec.from_event_log()` replays it
  deterministically (one recorded log can drive K fleet tenants).
- every stage of that journey is flight-recorded per EVENT by podtrace
  (obs/podtrace.py): watch-event arrival (store delivery seam) ->
  coalescing-window residency -> fleet DRR sched wait -> prestage
  staged/missed -> solve (linked to the SolveTrace by seq) -> bind, with
  per-tenant per-stage quantiles, an SLO budget, and `/debug/events`.
- `fleet.FleetFrontend` / `fleet.TenantSession` — the multi-tenant front
  end: ONE solver process multiplexes many tenant clusters (per-tenant
  Store/Provisioner/EncodeCache/resident carry), watch events wake the
  fleet loop push-style (the batcher idle/max window becomes a coalescing
  bound, not a latency floor), a deficit-round-robin policy keeps bursty
  tenants from starving the rest, and tenants share jitted pack-kernel
  SHAPES (process-global high-water marks + signature interning — never
  tensors; `isolation_audit()` enforces the split). With
  KARPENTER_SOLVER_COMPILE_CACHE=<dir> compiled executables persist across
  process restarts and replicas.
- `shard.ShardRouter` / `shard.ShardRing` — shardfleet: the tenant→PROCESS
  scale-out. The router spawns N shard worker processes (each its own
  FleetFrontend serve loop over a consistent-hash slice of the tenants),
  shares one persistent compile cache so shard N+1 cold-starts
  compile-free, partitions visible devices per shard
  (KARPENTER_SOLVER_SHARD_DEVICES), aggregates /debug/tenants +
  /debug/solves + /debug/events + the fleet metric families across shards
  (bounded `shard` label), and re-homes a dead shard's tenants by
  tenant-filtered recorded-log replay under per-shard circuit breakers.
- `faults.FaultSpec` / `faults.FaultInjector` / `faults.CircuitBreaker` —
  faultline: deterministic seeded fault injection at the named serving
  seams (solve exception / decode failure / slow solve, watch-stream
  drop·dup·reorder, prestager-worker death, spot-style capacity
  revocation), per-tenant circuit breakers at the fleet dispatch seam (K
  consecutive pump failures QUARANTINE one tenant; exponential-backoff
  half-open probes re-admit it; healthy tenants never miss a round), and
  the solver's graceful-degradation ladder (delta -> quarantined full
  re-encode -> host FFD) behind `TPUSolver.solve`. Observable via
  `karpenter_solver_tenant_state{tenant,state}`,
  `karpenter_solver_recovery_total{stage}`, and `/debug/tenants`.

Escape hatches: KARPENTER_SOLVER_DOUBLEBUF=0 disables the prestager (clones
rebuilt per pass, the pre-serving-loop behavior); KARPENTER_SOLVER_BUCKET=0
disables high-water shape bucketing (models/scheduler_model.py).

Thread-and-lock inventory (racecheck, ISSUE 11)
===============================================

This is the inventory the `lock-order` rule and the runtime sanitizer
(obs/racecheck.py, KARPENTER_SOLVER_RACECHECK=1) enforce. Threads first —
the serving stack's long-lived ones, every entry a reviewed seam in the
`[tool.solverlint] thread-shared` registry:

- the SOLVE thread (whoever pumps ServingLoop / Environment.tick);
- `karpenter-fleet` (FleetFrontend._serve_loop): the multi-tenant DRR
  scheduling loop — sleeps on the fleet wake event (or the nearest batcher
  `eta()`), then pumps runnable tenants; all solves in fleet mode run here;
- `karpenter-prestage` (PendingPrestager._run): drains watch events into the
  clone cache, overlapping the device pack;
- `churn-driver` (churn._churn_driver): the harness's concurrent event
  source, mutating only the store and the harness's atomic deques;
- `karpenter-operator-http` (+ per-request ThreadingHTTPServer workers):
  /metrics, /debug/solves, probes — read-only surfaces over lock-guarded
  state;
- `karpenter-lease-renewer` (LeaderElector.renew_loop): renews the lease
  through the store's optimistic concurrency;
- `karpenter-shard-drive-*` (ShardRouter._drive_shard): shardfleet run_all
  fan-out — one thread per shard, each exclusively owning its results key
  and its shard's handle (pipe I/O serialized under shard-handle);
- `karpenter-shard-monitor` (ShardRouter._monitor_loop): the router's
  breaker-driven shard health prober — pings through ShardHandle.call and
  mutates only breaker/registry state;
- `karpenter-shard-tick` (shard._tick_loop, worker process): live-mode
  controller rounds (env.tick(provision=False)) over the shard's tenant
  sessions, same division of labor as __main__._run_fleet's main loop;
- watch DELIVERY runs on whatever thread committed the store write, under
  `Store._deliver_lock` — every watch callback executes there.

Locks (constructed via obs.racecheck make_lock/make_rlock; the name is the
lock CLASS — instances share a graph node) and who guards what:

==================  =======================================================
lock name           guards
==================  =======================================================
store               Store._objects/_watchers/_rv/_kind_rv/_pending (RLock)
store-deliver       watch-event FIFO delivery (RLock; reentrant for
                    watchers that write back to the store)
cluster             Cluster's node/binding/ack mirrors (RLock)
batcher             Batcher trigger + in-flight bracket counters
fleet               FleetFrontend tenant registry + runnable set + DRR
                    deficits + breakers map + shed stamps + serve-thread
                    handle (leaf: only container ops run under it; solves
                    always run unlocked)
fleet-session       TenantSession wake-signal stats (leaf)
fleet-labels        the bounded tenant-label assignment table (leaf)
fleet-registry      the process-global fleet list backing /debug/tenants
                    (leaf)
faults              FaultInjector seam indices / fired counts / reorder
                    hold slot (leaf; metric emission runs OUTSIDE it)
breaker             CircuitBreaker state machine — pump-loop writes,
                    /debug/tenants HTTP reads (leaf)
prestage            PendingPrestager clone cache + staged/reused/misses
                    stats + worker thread handle
metric / metric-    every _Metric's series maps / Registry._metrics (RLock)
registry
trace               TraceRecorder ring, windows, seq, dropped
podtrace            PodTracer active/awaiting maps, completed ring, stage
                    windows, SLO + wake stats (leaf; metric emission runs
                    OUTSIDE it), plus the module-level tenant-surface
                    registry in obs/podtrace.py
events              Recorder.events + dedupe map (RLock)
clock               FakeClock._t
leader              LeaderElector._leading/_last_renew
nodepool-health     registration-health trackers (RLock)
operator-server     OperatorServer httpd/thread handles
shard-router        ShardRouter handle/port/tenant/breaker maps + ring +
                    monitor-thread handle (leaf: shard calls and breaker
                    methods always run unlocked)
shard-handle        one ShardHandle's Popen + pipe protocol framing (leaf;
                    the readline is plain pipe I/O, not a blocking call in
                    the lock-order sense)
shard-labels        the bounded shard-label assignment table (leaf)
==================  =======================================================

SANCTIONED ORDER (acquire left before right; the dynamic graph must stay a
DAG, and the sanitizer raises on the first acquisition that closes a
cycle):

    store-deliver  ->  { store, cluster, batcher, prestage, clock, metric*,
                         fleet-session, fleet, podtrace, faults }
    cluster        ->  { store, clock }
    trace          ->  { metric-registry, metric }
    events | store | batcher | prestage  ->  clock

(store-deliver -> podtrace is the arrival-stamp seam: `Store._drain` hands
every delivered event to the installed PodTracer before the watcher fan-out;
every other podtrace touch point — dispatch/solved on the solve thread,
prestage stamps after the prestage lock releases, wake counts after the
fleet lock releases — acquires it as a leaf. store-deliver -> faults is the
faultline watch-stream seam: `_drain` asks the installed FaultInjector to
drop/dup/reorder each Pod delivery; the solver/prestager/revocation seams
acquire `faults` as a leaf from their own threads.)

(The fleet edges are the push-wake path: watch delivery -> batcher trigger
-> wake_hook -> TenantSession stats -> FleetFrontend runnable set, each
lock RELEASED before the next is taken except the ambient store-deliver.)

Everything else is leaf-only. Two rules keep it that way: (1) never WRITE
to the store while holding `cluster` (a write drains watches under
store-deliver — the reverse edge); (2) never solve, device-sync, or call
`store._drain` while holding ANY lock (the lock-order rule flags those
statically) — the fleet loop obeys the same discipline: `FleetFrontend.pump`
releases the fleet lock around every `ServingLoop.pump`.
"""

from .churn import ChurnHarness, ChurnReport, ChurnSpec  # noqa: F401
from .faults import CircuitBreaker, FaultInjector, FaultRule, FaultSpec  # noqa: F401
from .fleet import FleetFrontend, TenantSession, tenant_label  # noqa: F401
from .loop import ServingLoop, doublebuf_enabled  # noqa: F401
from .prestage import PendingPrestager  # noqa: F401
from .shard import ShardRing, ShardRouter, placement_digest, shard_label  # noqa: F401
