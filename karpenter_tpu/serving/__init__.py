"""Steady-state churn serving: the solver as a long-lived service.

Every bench before this subsystem measured one-shot or single-warm-re-solve
latency; a production deployment is a long-lived `Provisioner`+`TPUSolver`
under sustained pod arrivals/departures from millions of users. This package
makes that regime first-class:

- `prestage.PendingPrestager` — the serving loop's double buffer: the NEXT
  solve's host-side encode/classify work (pod clone, validation verdict,
  signature stamping) runs while the CURRENT solve's device pack is in
  flight, and clone identity is preserved across solves so the encoder can
  classify consecutive serving snapshots as pod deltas.
- `loop.ServingLoop` — wires a prestager into a live Provisioner and pumps
  coalesced solves (the batcher's in-flight-aware drain: N triggers during a
  solve cost ONE batched follow-up solve).
- `churn.ChurnHarness` — drives sustained arrivals/departures against the
  live stack and reports throughput (pod-events/sec), P50/P99 re-solve
  latency, delta-hit rate, and the recompile count (the zero-steady-state
  gate, via the solvetrace sentinel).

Escape hatches: KARPENTER_SOLVER_DOUBLEBUF=0 disables the prestager (clones
rebuilt per pass, the pre-serving-loop behavior); KARPENTER_SOLVER_BUCKET=0
disables high-water shape bucketing (models/scheduler_model.py).
"""

from .churn import ChurnHarness, ChurnReport, ChurnSpec  # noqa: F401
from .loop import ServingLoop, doublebuf_enabled  # noqa: F401
from .prestage import PendingPrestager  # noqa: F401
