"""faultline: deterministic fault injection + failure-domain primitives for
the fleet serving stack.

Nothing in a long-lived serving process gets to assume a solve succeeds: a
`DecodeError` out of a poisoned delta base, a worker thread dying, a spot
reclaim yanking capacity mid-churn — the ROADMAP's sustained-disruption
regime. This module provides the two halves the stack composes:

- **FaultSpec / FaultInjector** — a SEEDED, deterministic fault plan that
  injects at named seams (the bounded `FAULT_SEAMS` enum):

  ==================  =======================================================
  seam                where it fires / what it models
  ==================  =======================================================
  solve-exception     `TPUSolver.solve` raises before the tensor path runs —
                      an arbitrary in-solve crash (driver bug, OOM-ish)
  decode-failure      a `tensor placement failed validation`-class failure:
                      the solver raises after its caches may be poisoned
  slow-solve          injected latency around the solve (`arg` seconds) —
                      the pathological-tenant input for overload protection
  watch-drop          a store watch event is never delivered (lossy stream)
  watch-dup           a store watch event is delivered twice (at-least-once)
  watch-reorder       a store watch event is deferred behind its successor
  prestage-death      the PendingPrestager worker thread dies (supervised +
                      restarted — the fix this fault forces)
  revocation          spot-style capacity revocation: `arg` nodes reclaimed
                      as forced departures through the ChurnHarness
  ==================  =======================================================

  Rules are index-scheduled (`at` / `every` / `count`) against per-seam
  monotone counters (solve attempts, delivered pod events, worker loop
  iterations, churn cycles), so the same spec against the same event stream
  injects at exactly the same points — recordable/replayable through the
  ChurnHarness JSONL event-log contract (the spec rides the log header;
  revocations ride the log as explicit `revoke` ops).

- **CircuitBreaker** — the per-tenant failure-domain gate the
  `FleetFrontend.pump()` dispatch seam consults: K consecutive pump
  failures open it (tenant QUARANTINED — the fleet keeps serving everyone
  else), exponential-backoff half-open probes re-admit it, and its state is
  observable (`karpenter_solver_tenant_state{tenant,state}`,
  `/debug/tenants`).

Determinism contract: with no FaultSpec installed every seam is a `None`
check — placements are bit-identical to a build without this module (tests
pin it), and an injected-then-recovered run converges to the same
placements as a clean run of the same event log.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

from ..obs.racecheck import make_lock, touch

# the bounded seam enum: every `seam` metric label value and every
# FaultRule.seam is validated against this tuple at construction
FAULT_SEAMS = (
    "solve-exception",
    "decode-failure",
    "slow-solve",
    "watch-drop",
    "watch-dup",
    "watch-reorder",
    "prestage-death",
    "revocation",
)
_SOLVE_SEAMS = frozenset({"solve-exception", "decode-failure", "slow-solve"})
_WATCH_SEAMS = frozenset({"watch-drop", "watch-dup", "watch-reorder"})

# the breaker's bounded state enum — the `state` metric label values on
# karpenter_solver_tenant_state / karpenter_solver_breaker_transitions_total
TENANT_STATES = ("healthy", "quarantined", "probing")


class FaultInjected(RuntimeError):
    """An injected fault. `unrecoverable=True` models a hard failure the
    solver's degradation ladder must NOT absorb (it re-raises, so the fault
    escapes to the fleet's dispatch seam and trips the tenant breaker)."""

    def __init__(self, msg: str, seam: str = "solve-exception", unrecoverable: bool = False):
        super().__init__(msg)
        self.seam = seam
        self.unrecoverable = unrecoverable


@dataclass(frozen=True)
class FaultRule:
    """One scheduled fault: fire at index `at` of the seam's counter, then
    every `every` (0 = only at `at`), at most `count` times total. `arg` is
    the seam parameter (slow-solve sleep seconds; revocation node count).
    `ladder` is the number of solver ladder stages the fault poisons: 1 =
    the first attempt only (full-re-encode recovery succeeds), 2 = the
    re-encode retry fails too (host-FFD serves), 0 = UNRECOVERABLE (the
    ladder re-raises and the tenant breaker takes over)."""

    seam: str
    at: int = 0
    every: int = 0
    count: int = 1
    arg: float = 0.0
    ladder: int = 1

    def __post_init__(self):
        if self.seam not in FAULT_SEAMS:
            raise ValueError(f"unknown fault seam {self.seam!r} (have {FAULT_SEAMS})")

    def due(self, index: int, fired: int) -> bool:
        if fired >= self.count or index < self.at:
            return False
        if index == self.at:
            return True
        return self.every > 0 and (index - self.at) % self.every == 0

    def to_dict(self) -> dict:
        return {"seam": self.seam, "at": self.at, "every": self.every, "count": self.count, "arg": self.arg, "ladder": self.ladder}


@dataclass(frozen=True)
class FaultSpec:
    """A seeded, deterministic fault plan (tuple of FaultRule). Serializes
    to/from a plain dict so it rides the ChurnHarness JSONL log header."""

    rules: tuple = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "rules", tuple(self.rules))

    def to_dict(self) -> dict:
        return {"seed": self.seed, "rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        return cls(rules=tuple(FaultRule(**r) for r in d.get("rules", ())), seed=int(d.get("seed", 0)))

    @classmethod
    def randomized(cls, seed: int, solves: int = 20, events: int = 1000, cycles: int = 8) -> "FaultSpec":
        """A randomized-but-seeded chaos plan across every seam, scaled to
        the run's expected solve/event/cycle counts (the chaos-soak spec's
        input). The plan stays SURVIVABLE by construction: solver faults are
        recoverable (ladder <= 2) and revocations reclaim one node at a
        time, so a no-fault run of the same event stream must converge to
        the same placements."""
        rng = random.Random(seed)
        rules = [
            FaultRule("solve-exception", at=rng.randrange(max(1, solves // 4), max(2, solves // 2)), ladder=rng.choice((1, 1, 2))),
            FaultRule("decode-failure", at=rng.randrange(max(1, solves // 2), max(2, solves)), ladder=1),
            FaultRule("watch-drop", at=rng.randrange(0, max(1, events // 2)), every=max(7, events // 11), count=rng.randrange(2, 6)),
            FaultRule("watch-dup", at=rng.randrange(0, max(1, events // 2)), every=max(5, events // 13), count=rng.randrange(2, 6)),
            FaultRule("watch-reorder", at=rng.randrange(0, max(1, events // 2)), every=max(11, events // 7), count=rng.randrange(2, 5)),
            FaultRule("prestage-death", at=rng.randrange(0, 3), count=1),
            FaultRule("revocation", at=rng.randrange(1, max(2, cycles)), count=1, arg=1),
        ]
        return cls(rules=tuple(rules), seed=seed)


class FaultInjector:
    """The runtime half of a FaultSpec: installed at the named seams
    (solver.fault_hook, Store.set_fault_injector, PendingPrestager
    .fault_hook, ChurnHarness.take_revocations) and consulted with per-seam
    monotone indices. Thread-safe: seam calls arrive from the solve thread,
    watch-delivery threads, and the prestager worker concurrently."""

    # racecheck guarded-field registry: indices/fired counts are bumped from
    # multiple threads (watch delivery vs solve vs worker)
    GUARDED_FIELDS = {
        "_indices": "_lock",
        "_fired": "_lock",
        "_armed_depth": "_lock",
        "_deferred": "_lock",
        "injected": "_lock",
        "log": "_lock",
    }

    def __init__(self, spec: FaultSpec, registry=None):
        self.spec = spec
        self.registry = registry
        self.rng = random.Random(spec.seed)
        self._lock = make_lock("faults")
        self._indices: dict[str, int] = {"solve": 0, "watch": 0, "prestage": 0, "cycle": 0}
        self._fired: list[int] = [0] * len(spec.rules)
        # ladder stages left to poison within the CURRENT solve (armed by a
        # solve-seam firing with ladder > 1, consumed by the recovery hook)
        self._armed_depth = 0
        # the watch-reorder hold slot (at most one event deferred at a time)
        self._deferred: list = []
        self.injected: dict[str, int] = {}
        self.log: list[dict] = []

    # -- bookkeeping -----------------------------------------------------------
    def _fire(self, ri: int, rule: FaultRule, index: int, unit: str) -> None:  # solverlint: ok(guarded-field-access): caller-holds contract — every call site sits inside `with self._lock` (solver_hook / on_watch_event / prestage_hook / take_revocations)
        self._fired[ri] += 1
        touch(self, "injected")
        self.injected[rule.seam] = self.injected.get(rule.seam, 0) + 1
        self.log.append({"seam": rule.seam, unit: index})

    def _emit(self, seam: str, n: float = 1) -> None:
        # metric emission OUTSIDE the injector lock (metric locks are leaves,
        # but the injector must never hold its lock across foreign code)
        if self.registry is not None:
            from ..metrics import SOLVER_FAULT_INJECTIONS_TOTAL

            self.registry.counter(SOLVER_FAULT_INJECTIONS_TOTAL).inc(n, seam=seam)  # solverlint: ok(metric-label-cardinality): seam is a FaultRule.seam validated against the static FAULT_SEAMS enum at construction

    def summary(self) -> dict:
        with self._lock:
            return dict(self.injected)

    # -- the solver seam (TPUSolver.fault_hook) --------------------------------
    def solver_hook(self, stage: str = "solve") -> None:
        """`stage="solve"`: the solve-attempt seam (indexed per solve).
        `stage="reencode"`: the degradation ladder's re-encode retry — fires
        only while a ladder>1 solve fault left poison armed."""
        if stage == "reencode":
            with self._lock:
                armed = self._armed_depth > 0
                if armed:
                    self._armed_depth -= 1
            if armed:
                raise FaultInjected("faultline: injected re-encode failure", seam="solve-exception")
            return
        fired_rule = None
        with self._lock:
            i = self._indices["solve"]
            self._indices["solve"] = i + 1
            for ri, rule in enumerate(self.spec.rules):
                if rule.seam in _SOLVE_SEAMS and rule.due(i, self._fired[ri]):
                    self._fire(ri, rule, i, "solve")
                    if rule.seam != "slow-solve":
                        self._armed_depth = max(0, int(rule.ladder) - 1)
                    fired_rule = rule
                    break
        if fired_rule is None:
            return
        self._emit(fired_rule.seam)
        if fired_rule.seam == "slow-solve":
            time.sleep(fired_rule.arg or 0.05)
            return
        unrecoverable = int(fired_rule.ladder) <= 0
        if fired_rule.seam == "decode-failure":
            raise FaultInjected(
                "faultline: injected decode-validation failure", seam="decode-failure", unrecoverable=unrecoverable
            )
        raise FaultInjected("faultline: injected solve exception", seam="solve-exception", unrecoverable=unrecoverable)

    # -- the watch-stream seam (Store._drain) ----------------------------------
    def on_watch_event(self, event: str, obj, t_commit: float, seq: int = 0) -> list:
        """Transform one about-to-be-delivered Pod event into the list of
        events actually delivered: `[]` (drop / deferred for reorder), the
        event twice (dup), or the event followed by a previously deferred
        one (the reorder swap: the OLDER event arrives after its successor).
        `seq` is the store's per-kind delivery sequence number — it travels
        with the event untouched, so the store's gap tracker sees exactly
        what a lossy stream's consumer would (a dropped seq never arrives,
        a dup arrives twice, a reorder arrives late)."""
        fired = None
        out: list = [(event, obj, t_commit, seq)]
        with self._lock:
            i = self._indices["watch"]
            self._indices["watch"] = i + 1
            for ri, rule in enumerate(self.spec.rules):
                if rule.seam in _WATCH_SEAMS and rule.due(i, self._fired[ri]):
                    self._fire(ri, rule, i, "event")
                    fired = rule.seam
                    break
            if fired == "watch-drop":
                out = []
            elif fired == "watch-dup":
                out = [(event, obj, t_commit, seq), (event, obj, t_commit, seq)]
            elif fired == "watch-reorder":
                touch(self, "_deferred")
                self._deferred.append((event, obj, t_commit, seq))
                out = []
            elif self._deferred:
                # the reorder swap completes: successor first, deferred after
                out = out + self._deferred
                self._deferred = []
        if fired is not None:
            self._emit(fired)
        return out

    def take_deferred(self):
        """Drain one reorder-deferred event once the store queue empties, so
        a reorder at the tail of a burst delays delivery, never loses it."""
        with self._lock:
            if not self._deferred:
                return None
            touch(self, "_deferred")
            return self._deferred.pop(0)

    # -- the prestager seam (PendingPrestager.fault_hook) ----------------------
    def prestage_hook(self) -> None:
        """Called per worker loop iteration; a due prestage-death rule kills
        the worker thread (SystemExit exits the thread silently — exactly
        the no-signal death the supervisor must detect and restart)."""
        fire = False
        with self._lock:
            i = self._indices["prestage"]
            self._indices["prestage"] = i + 1
            for ri, rule in enumerate(self.spec.rules):
                if rule.seam == "prestage-death" and rule.due(i, self._fired[ri]):
                    self._fire(ri, rule, i, "iteration")
                    fire = True
                    break
        if fire:
            self._emit("prestage-death")
            raise SystemExit("faultline: injected prestager worker death")

    # -- the revocation seam (ChurnHarness cycle boundary) ---------------------
    def take_revocations(self) -> int:
        """Nodes to revoke this churn cycle (consumes due revocation rules;
        indexed per cycle). The harness decodes them as forced departures."""
        n = 0
        with self._lock:
            i = self._indices["cycle"]
            self._indices["cycle"] = i + 1
            for ri, rule in enumerate(self.spec.rules):
                if rule.seam == "revocation" and rule.due(i, self._fired[ri]):
                    self._fire(ri, rule, i, "cycle")
                    nodes = max(1, int(rule.arg))
                    # the injected tally counts NODES revoked, not firings
                    self.injected["revocation"] += nodes - 1
                    n += nodes
        if n:
            self._emit("revocation", n)
        return n


class CircuitBreaker:
    """Per-tenant circuit breaker for the fleet dispatch seam.

    States (the bounded TENANT_STATES enum): `healthy` -> after K
    consecutive failures -> `quarantined` (no dispatch; the fleet keeps
    serving everyone else) -> once the backoff elapses, `allow()` admits ONE
    half-open `probing` dispatch -> success closes it (`healthy`, backoff
    reset), failure re-quarantines with the backoff DOUBLED (capped).
    `now_fn` defaults to time.monotonic; deterministic drivers inject a fake
    clock's now."""

    # racecheck guarded-field registry: the pump loop mutates, /debug/tenants
    # HTTP workers read — every touch under the breaker's leaf lock
    GUARDED_FIELDS = {
        "state": "_lock",
        "consecutive": "_lock",
        "opens": "_lock",
        "probes": "_lock",
        "opened_at": "_lock",
        "backoff": "_lock",
        "last_error": "_lock",
    }

    def __init__(self, failures_to_open: int = 3, backoff_seconds: float = 0.5, backoff_max: float = 30.0, now_fn=None):
        self._lock = make_lock("breaker")
        self.now = now_fn if now_fn is not None else time.monotonic
        self.failures_to_open = max(1, int(failures_to_open))
        self.backoff_base = float(backoff_seconds)
        self.backoff_max = float(backoff_max)
        self.state = "healthy"
        self.consecutive = 0
        self.opens = 0  # total quarantine episodes
        self.probes = 0  # half-open probes dispatched
        self.opened_at = 0.0
        self.backoff = self.backoff_base
        self.last_error = ""

    def allow(self) -> bool:
        """May a solve dispatch now? Transitions quarantined -> probing when
        the backoff has elapsed (admitting exactly one probe)."""
        with self._lock:
            if self.state == "healthy":
                return True
            if self.state == "quarantined" and (self.now() - self.opened_at) >= self.backoff:
                touch(self, "state")
                self.state = "probing"
                self.probes += 1
                return True
            return False

    def record_success(self) -> bool:
        """A dispatched solve succeeded. Returns True when this re-admitted
        a quarantined/probing tenant (the caller publishes the transition)."""
        with self._lock:
            self.consecutive = 0
            if self.state != "healthy":
                touch(self, "state")
                self.state = "healthy"
                self.backoff = self.backoff_base
                self.last_error = ""
                return True
            return False

    def record_failure(self, err: object = "") -> str | None:
        """A dispatched solve raised. Returns the new state when this opened
        (or re-opened) the breaker, else None. A probe failure doubles the
        backoff (capped); K consecutive failures open from healthy."""
        with self._lock:
            self.consecutive += 1
            self.last_error = f"{type(err).__name__}: {err}"[:200] if isinstance(err, BaseException) else str(err)[:200]
            if self.state == "probing":
                touch(self, "state")
                self.state = "quarantined"
                self.opens += 1
                self.opened_at = self.now()
                self.backoff = min(self.backoff_max, self.backoff * 2.0)
                return "quarantined"
            if self.state == "healthy" and self.consecutive >= self.failures_to_open:
                touch(self, "state")
                self.state = "quarantined"
                self.opens += 1
                self.opened_at = self.now()
                self.backoff = self.backoff_base
                return "quarantined"
            return None

    def probe_inconclusive(self) -> None:
        """The admitted probe never produced a verdict (e.g. the reconcile
        declined to solve): re-quarantine WITHOUT doubling, so the next
        backoff window admits another probe instead of wedging in probing."""
        with self._lock:
            if self.state == "probing":
                touch(self, "state")
                self.state = "quarantined"
                self.opened_at = self.now()

    def state_name(self) -> str:
        with self._lock:
            return self.state

    def remaining_backoff(self) -> float:
        """Seconds until a quarantined tenant's next probe window (0 when
        dispatchable) — the fleet serve loop folds this into its sleep."""
        with self._lock:
            if self.state != "quarantined":
                return 0.0
            return max(0.0, self.backoff - (self.now() - self.opened_at))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive,
                "opens": self.opens,
                "probes": self.probes,
                "backoff_seconds": round(self.backoff, 3),
                "last_error": self.last_error,
            }
