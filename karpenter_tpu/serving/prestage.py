"""PendingPrestager: the serving loop's double buffer for host-side encode
prep.

The solver's hot path is one fused device->host landing (enforced by
solverlint), so while a pack is executing on device the host thread is
blocked in that landing and the host CPU is otherwise idle. The next solve's
host-side work, however, is already known: every pod that triggered the
batcher during the in-flight solve will be in the next batch, and its
per-pod encode prep — the snapshot clone `get_pending_pods` must make, the
PVC validation verdict, and the signature stamp (`encode._batch_stamp`) —
is a pure function of the pod's content. The prestager runs that prep on a
worker thread concurrently with the pack, so by the time the coalesced
follow-up solve drains, its batch is already cloned and stamped.

Clone identity is the second effect: the cache hands out the SAME clone
object for a pod while its (uid, resourceVersion) is unchanged.
`encode._try_delta_encode` walks the previous solve's pod list by OBJECT
identity — with per-pass fresh clones (the pre-serving behavior) no pod
matches and every surviving pod classifies as removed-and-re-added, so the
"delta" degenerates to a full remove-all/add-all turnover (admissible since
the cap widened, but it re-credits and re-packs the entire backlog every
solve). With the prestager, a pod pending across two solves IS the same
object and the delta is exactly the true arrivals/cancellations.

The decode-delta memo (`TPUSolver._decode`) leans on the same contract from
the other end: a reused slot hands back the PRIOR decode's claim built over
the prior solve's pod objects, and its correctness argument — "slot count
unchanged + no assignment row touched it ⇒ identical member set" — holds
because an unchanged pod ((uid, resourceVersion) stable) is the same clone
in both solves. A pod whose content changed gets a NEW clone here, which
re-keys its encode signature and moves its assignment row, so the decode
marks every slot it touches dirty and re-materializes them; clone identity
is what makes "row untouched" equivalent to "membership unchanged".

Safety:
- Clones are never mutated by a solve: the host scheduler deep-copies a pod
  before its first preference relaxation and leaves the caller's object
  pristine (scheduler._try_schedule), and the tensor path only reads.
- Only pods without claim-backed volumes are staged (`take` returns None for
  the rest): their PVC validation verdict depends on store content the
  (uid, rv) key cannot see, and their signatures extend with resolved volume
  components only the sequential encode path builds.
- Worker-thread writes are private until published under the lock; signature
  stamping/interning is the same idempotent content-addressed work the
  encode would do, so a race between worker and an in-flight encode is at
  worst duplicated effort, never a different placement.
"""

from __future__ import annotations

from collections import deque

from ..kube.clone import fast_deepcopy
from ..obs.racecheck import make_event, make_lock, spawn_thread, touch
from ..utils import pods as pod_utils

_MAX_ENTRIES = 500_000  # hard bound; a clear just re-stages on demand


def _stampable(pod) -> bool:
    from ..solver.volumes import has_pvc_volumes

    return not has_pvc_volumes(pod)


def _rv_newer(a, b) -> bool:
    """True when resource_version `a` is strictly newer than `b`. Store RVs
    are monotone ints; non-int doubles fall back to inequality (any change
    counts as newer — at worst a redundant restage, never a stale keep)."""
    try:
        return int(a) > int(b)
    except (TypeError, ValueError):
        return a != b


class PendingPrestager:
    """(uid -> (resourceVersion, clone)) cache of pre-staged pending pods,
    filled by a worker thread (double-buffer mode) and authoritatively on
    `take` misses, evicted by store watch events (bind/delete)."""

    # racecheck guarded-field registry (analysis: guarded-field-access;
    # runtime: obs.racecheck.touch at the stat increments). The cache AND
    # the stat counters are written by the worker thread and the solve
    # thread concurrently; `_queue` is deliberately absent — deque
    # append/popleft are atomic and the queue is single-consumer.
    GUARDED_FIELDS = {
        "_cache": "_lock",
        "_thread": "_lock",
        "_stop": "_lock",
        "_worker_wanted": "_lock",
        "staged": "_lock",
        "reused": "_lock",
        "misses": "_lock",
        "restarts": "_lock",
    }

    def __init__(self):
        self._lock = make_lock("prestage")
        self._cache: dict[str, tuple[str, object]] = {}
        self._queue: deque = deque()
        self._wake = make_event()
        self._stop = make_event()
        self._thread = None
        # podtrace (obs/podtrace.py): staged-vs-missed stamps per event —
        # adopted from the attached store's delivery seam; the tracer's own
        # lock guards its state, so stamping needs no prestage lock
        self.podtracer = None
        # stats (read by the churn harness/loop for attribution), guarded by
        # _lock like the cache they describe
        self.staged = 0  # clones prepared by the worker ahead of a take
        self.reused = 0  # takes served by an existing clone (delta identity)
        self.misses = 0  # takes that cloned inline (arrived un-staged)
        # supervision (faultline): start() records that a worker is WANTED;
        # ensure_worker() restarts a dead-but-wanted worker and counts it —
        # before this, a worker death silently degraded every later solve to
        # synchronous prep with no signal
        self._worker_wanted = False
        self.restarts = 0
        # metrics registry for the restart counter (installed by ServingLoop)
        self.metrics = None
        # fault-injection seam (serving/faults.FaultInjector.prestage_hook):
        # called once per worker loop iteration; an injected death raises
        # SystemExit so the thread exits exactly like an unhandled crash
        self.fault_hook = None

    # -- store integration -----------------------------------------------------
    def attach(self, store) -> None:
        store.watch("Pod", self._on_event)
        tracer = store.event_tracer() if hasattr(store, "event_tracer") else None
        if tracer is not None and getattr(tracer, "enabled", False):
            self.podtracer = tracer

    def _on_event(self, event: str, pod) -> None:
        self._queue.append((event, pod))
        self._wake.set()

    # -- worker ----------------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            touch(self, "_worker_wanted")
            self._worker_wanted = True
            if self._thread is not None:
                return
            # a FRESH stop event per worker generation: a start() racing the
            # join window of a concurrent stop() must not resurrect the OLD
            # worker by clearing the event it polls — each worker owns the
            # event it was spawned with, so a set() stops exactly that one
            self._stop = make_event()
            self._thread = spawn_thread(self._run, name="karpenter-prestage", args=(self._stop,))

    def stop(self) -> None:
        """Idempotent and double-call-safe: the thread handle is claimed
        atomically, so two racing stop() calls join once and a stop() after
        stop() is a no-op (the operator shutdown path can hit both)."""
        with self._lock:
            touch(self, "_worker_wanted")
            self._worker_wanted = False
            t, self._thread = self._thread, None
            stop = self._stop
        stop.set()
        self._wake.set()
        if t is not None:
            t.join(timeout=5)

    def worker_running(self) -> bool:
        with self._lock:
            return self._thread is not None

    def worker_alive(self) -> bool:
        """True only when the worker THREAD is actually alive — a dead
        thread leaves the handle set, which is exactly the silent-death
        state worker_running() cannot see."""
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    def ensure_worker(self) -> bool:
        """Supervision: restart a wanted-but-dead worker (injected fault or
        real crash). Called by the serving loop before every pump, so a
        death costs at most one solve of synchronous prep — detected,
        counted (karpenter_solver_prestage_worker_restarts_total), and
        healed instead of silently degrading forever. Returns True when a
        restart happened."""
        with self._lock:
            t = self._thread
            if not self._worker_wanted or (t is not None and t.is_alive()):
                return False
            touch(self, "restarts")
            self.restarts += 1
            # a fresh generation, exactly like start(): new stop event so a
            # racing stop() of the DEAD generation cannot stop this one
            self._stop = make_event()
            self._thread = spawn_thread(self._run, name="karpenter-prestage", args=(self._stop,))
        if self.metrics is not None:
            from ..metrics import SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL

            self.metrics.counter(SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL).inc()
        return True

    def _run(self, stop) -> None:
        # `stop` is this worker generation's own event (see start)
        while not stop.is_set():
            hook = self.fault_hook
            if hook is not None:
                try:
                    hook()
                except SystemExit:
                    # the injected worker death: the thread exits exactly
                    # like an unhandled crash would leave it (dead, handle
                    # still set, no signal) — ensure_worker must notice
                    return
            self._wake.wait(timeout=0.05)
            self._wake.clear()
            self.pump()

    def pump(self) -> int:
        """Drain the event queue (worker body; callable inline for
        deterministic single-threaded runs). Returns pods staged."""
        n = 0
        staged_uids: list[str] = []
        while self._queue:
            try:
                event, pod = self._queue.popleft()
            except IndexError:  # pragma: no cover - racing close
                break
            uid = pod.metadata.uid
            if event == "DELETED" or not pod_utils.is_provisionable(pod):
                with self._lock:
                    self._cache.pop(uid, None)
                continue
            if not _stampable(pod):
                continue
            rv = pod.metadata.resource_version
            with self._lock:
                e = self._cache.get(uid)
            if e is not None and not _rv_newer(rv, e[0]):
                # already staged at this (or a NEWER) version: a lagging
                # worker must never overwrite a take-miss entry the current
                # solve just handed out with a stale queued event — that
                # would break clone identity for an unchanged pod
                continue
            # watch events deliver a store-made snapshot clone (shared with
            # the other watchers under the read-only contract) — adopt it as
            # the staged clone instead of cloning again; stamping only adds
            # the signature attribute
            self._stamp(pod)
            with self._lock:
                if len(self._cache) >= _MAX_ENTRIES:
                    self._cache.clear()
                e2 = self._cache.get(uid)
                if e2 is None or _rv_newer(rv, e2[0]):
                    self._cache[uid] = (rv, pod)
                    touch(self, "staged")
                    self.staged += 1
                    n += 1
                    staged_uids.append(uid)
        if staged_uids and self.podtracer is not None:
            # one batched stamp OUTSIDE the prestage lock (tracer is a leaf)
            self.podtracer.on_prestaged_batch(staged_uids)
        return n

    @staticmethod
    def _stamp(pod):
        from ..solver.encode import _batch_stamp

        _batch_stamp([pod])

    @classmethod
    def _clone_and_stamp(cls, pod):
        # the stamp does not survive the clone (deliberately — see _SigStamp);
        # restamp the clone so the encode's columnar grouping path reads it
        clone = fast_deepcopy(pod)
        cls._stamp(clone)
        return clone

    # -- the provisioner-facing surface ---------------------------------------
    def take(self, pod):
        """Return the staged clone for a provisionable store pod, or None
        when the pod must go through the inline path (claim-backed volumes —
        their PVC validation verdict and signature depend on store content
        the (uid, rv) key cannot see; stageable pods trivially validate).
        While (uid, resourceVersion) holds, repeated takes return the SAME
        clone object — the delta-identity contract. A miss clones inline and
        caches the result, so the cache is authoritative for stageable pods
        even when the worker lags."""
        if not _stampable(pod):
            return None
        uid = pod.metadata.uid
        rv = pod.metadata.resource_version
        with self._lock:
            e = self._cache.get(uid)
            if e is not None and e[0] == rv:
                # stats mutate under the SAME lock as the cache: the worker
                # thread bumps `staged` concurrently, and unlocked `+= 1`
                # read-modify-writes lose updates under contention (the
                # guarded-field-access rule pins these to _lock)
                touch(self, "reused")
                self.reused += 1
                return e[1]
        clone = self._clone_and_stamp(pod)
        with self._lock:
            if len(self._cache) >= _MAX_ENTRIES:
                self._cache.clear()
            # same guard as pump(): never overwrite a same-or-newer entry a
            # racing worker just staged (that would flip the pod's clone
            # identity on the next solve); on an equal-rv race the staged
            # clone wins and we hand IT out
            e2 = self._cache.get(uid)
            if e2 is not None and e2[0] == rv:
                touch(self, "reused")
                self.reused += 1
                return e2[1]
            if e2 is None or _rv_newer(rv, e2[0]):
                self._cache[uid] = (rv, clone)
            touch(self, "misses")
            self.misses += 1
        if self.podtracer is not None:
            self.podtracer.on_take_miss(uid)
        return clone

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)
