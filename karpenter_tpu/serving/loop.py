"""ServingLoop: the steady-state solve pump around a live Provisioner.

One `pump()` is one serving iteration: run the provisioner's reconcile if
its batcher window (or a coalesced drain generation) is ready, then the
caller-supplied post-solve controllers (lifecycle/binder/... — whatever the
deployment runs between solves). The loop itself adds no policy beyond
wiring the two serving-mode mechanisms in:

- wake-up coalescing lives in the Batcher (begin_solve/end_solve bracket,
  installed by Provisioner.reconcile): triggers arriving during an in-flight
  solve fold into ONE batched follow-up solve, which `pump` picks up on its
  next call with no idle-window stall;
- double-buffering lives in the PendingPrestager, installed here: the next
  batch's host-side clone+stamp work overlaps the current device pack on a
  worker thread (KARPENTER_SOLVER_DOUBLEBUF=0 disables — clones rebuilt per
  pass, restoring the pre-serving-loop provisioner behavior exactly);
- event-lifecycle observability rides the same wiring (obs/podtrace.py):
  the Environment installs one PodTracer on the store's delivery seam and
  the provisioner, and `PendingPrestager.attach` adopts it for its
  staged-vs-missed stamps — so every pump here closes the
  arrival -> coalesce -> [sched-wait] -> solve legs of the per-event trace
  without the loop itself holding any tracer state.

None of these mechanisms may change placements: tests pin bit-identical
results against serial one-solve-per-batch execution with the hatches off
and with podtrace disabled.
"""

from __future__ import annotations

import os

from .prestage import PendingPrestager


def doublebuf_enabled() -> bool:
    return os.environ.get("KARPENTER_SOLVER_DOUBLEBUF", "1").strip().lower() not in ("0", "false", "off")


class ServingLoop:
    def __init__(self, provisioner, store, double_buffer: bool | None = None, post_solve=(), worker: bool = True):
        """`post_solve`: zero-arg callables run after every successful solve
        (in order). `worker=False` keeps the prestager synchronous (its queue
        drains via `prestager.pump()`/take-miss fills) for deterministic
        single-threaded runs — same results, no overlap."""
        self.provisioner = provisioner
        self.post_solve = list(post_solve)
        self.double_buffer = doublebuf_enabled() if double_buffer is None else bool(double_buffer)
        self.solves = 0
        self.prestager: PendingPrestager | None = None
        if self.double_buffer:
            self.prestager = PendingPrestager()
            self.prestager.attach(store)
            self.prestager.metrics = provisioner.metrics
            provisioner.prestager = self.prestager
            if worker:
                self.prestager.start()

    def pump(self, force: bool = False):
        """One serving iteration. Returns the solve's Results or None when
        the batcher window has not closed."""
        if self.prestager is not None:
            # supervision (faultline): a worker thread that DIED (injected
            # fault or real crash) is restarted here — detected and counted,
            # never a silent permanent downgrade to synchronous prep
            self.prestager.ensure_worker()
            if not self.prestager.worker_alive():
                self.prestager.pump()  # synchronous mode: drain before the solve
        results = self.provisioner.reconcile(force=force)
        if results is not None:
            self.solves += 1
            for fn in self.post_solve:
                fn()
        return results

    def drain(self, max_solves: int = 64) -> int:
        """Pump until the batcher goes quiet (coalesced generations included);
        returns the number of solves run."""
        n = 0
        while n < max_solves and self.provisioner.batcher.ready():
            if self.pump() is None:
                break
            n += 1
        return n

    def close(self) -> None:
        if self.prestager is not None:
            self.prestager.stop()
            self.provisioner.prestager = None
            self.prestager = None
