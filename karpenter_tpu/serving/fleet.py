"""FleetFrontend: push-driven wake + multi-tenant solver multiplexing.

PR 10/12 made the solver a long-lived service, but the arrival path's
latency floor was still the poll-based idle-window reconcile, and one
process served exactly one cluster. This module is the horizontal-scaling
shape the ROADMAP names: ONE solver process multiplexing MANY tenant
clusters, with watch events flowing push-style into each tenant's Batcher
and on into the fleet loop.

Mechanisms, in dependency order:

- PUSH WAKE: every tenant Environment already routes store watch events
  into its provisioner's Batcher (`Provisioner.trigger`). The fleet
  completes the push path with two seams: the Batcher's `wake_hook` (fires
  on every trigger, after the batcher lock releases) and a per-tenant store
  watch callback (`TenantSession._on_watch_event`, so deletions — which
  never trigger the batcher — still wake promptly). Both mark the tenant
  RUNNABLE under the fleet lock and set the fleet's wake event; the serve
  loop sleeps on that event with a timeout of `min(batcher.eta())` over
  tenants with an open generation, so the idle/max batching window remains
  a COALESCING bound while the poll interval stops being a latency floor.
- FAIRNESS: one deficit-round-robin pass over the runnable tenants per
  `pump()` round. Each runnable tenant is credited `quantum` solve credits
  (banked deficit capped at `backlog_solve_cap`), and a solve costs one
  credit — a bursty tenant whose batcher re-arms after every solve (the
  coalesced-drain pattern) can run at most `backlog_solve_cap` solves per
  round before the ring moves on, so it cannot starve the rest.
- SHARED JITTED KERNELS: the bucket high-water marks
  (models.scheduler_model._BUCKET_HW), the signature intern table and the
  row-artifact cache (solver.encode) are process-global, i.e. FLEET-scoped.
  Tenants share compiled pack-kernel SHAPES — tenant N+1's first solve at
  the fleet's established marks records zero new compiles — while actual
  tensor DATA stays per-tenant: row artifacts are keyed by each cluster's
  process-unique epoch and every EncodeCache/resident carry lives on the
  tenant's own solver. `isolation_audit()` verifies that discipline.
- PERSISTENT COMPILE CACHE: ``KARPENTER_SOLVER_COMPILE_CACHE=<dir>``
  (solver.tpu.configure_compile_cache) persists compiled executables to
  disk, so a RESTARTED process or a fresh replica skips the cold compile
  storm entirely — the cross-process arm of the warm-start story.

Determinism contract: `pump()` runs each tenant's ordinary
`ServingLoop.pump()` — the same reconcile the single-tenant poll loop runs
— so push-vs-poll and fleet-vs-solo placements are bit-identical for
identical event streams (tests pin this). The fleet changes WHEN solves
run, never what they compute.
"""

from __future__ import annotations

import time

from ..obs.racecheck import make_event, make_lock, spawn_thread, touch
from ..obs.trace import TraceRecorder
from .faults import TENANT_STATES, CircuitBreaker
from .loop import ServingLoop

# distinct tenant label values the bounded `tenant` metric label may carry
# before collapsing to "overflow" — kept under solverlint's
# max-label-values cap so the fleet can never become a cardinality leak
TENANT_LABEL_CAP = 12
_TENANT_LABELS: dict[str, str] = {}
# module-scoped (the label assignment is process-global like the caches it
# labels); constructed through the sanctioned factory
_TENANT_LABELS_LOCK = make_lock("fleet-labels")


def tenant_label(tenant_id: str) -> str:
    """The BOUNDED metric label for a tenant id: the first TENANT_LABEL_CAP
    distinct ids keep their sanitized form, later ones collapse to
    "overflow". Distinct ids NEVER share a label short of the cap — two ids
    whose sanitized forms collide ("team/a" vs "team:a") get a numeric
    disambiguator instead of silently merging their metric series. This is
    the `bounded_label_producers` entry solverlint's metric-label-
    cardinality rule trusts — every `tenant=` label value in the repo must
    come from here (or carry a justified pragma)."""
    tenant_id = str(tenant_id)
    with _TENANT_LABELS_LOCK:
        label = _TENANT_LABELS.get(tenant_id)
        if label is not None:
            return label
        if len(_TENANT_LABELS) >= TENANT_LABEL_CAP:
            label = "overflow"
        else:
            base = "".join(c if (c.isalnum() or c in "-_.") else "-" for c in tenant_id)[:60] or "default"
            # "overflow" is RESERVED for the past-the-cap bucket: an in-cap
            # tenant whose id sanitizes to it gets disambiguated instead of
            # merging its series with every capped tenant's
            used = set(_TENANT_LABELS.values()) | {"overflow"}
            label, n = base, 2
            while label in used:
                label, n = f"{base}-{n}", n + 1
        _TENANT_LABELS[tenant_id] = label
        return label


def reset_tenant_labels() -> None:
    """Drop the process-global label assignments (test isolation)."""
    with _TENANT_LABELS_LOCK:
        _TENANT_LABELS.clear()


# process-global fleet registry backing the operator's /debug/tenants
# surface (mirrors obs.podtrace's tenant-surface registry): FleetFrontend
# registers itself at construction and unregisters on close()
_FLEETS: list = []
_FLEETS_LOCK = make_lock("fleet-registry")


def _register_fleet(fleet: "FleetFrontend") -> None:
    with _FLEETS_LOCK:
        _FLEETS.append(fleet)


def _unregister_fleet(fleet: "FleetFrontend") -> None:
    with _FLEETS_LOCK:
        if fleet in _FLEETS:
            _FLEETS.remove(fleet)


def fleet_debug_surfaces() -> dict:
    """{tenant_id: breaker/backlog row} merged across every live fleet in
    this process — the /debug/tenants payload."""
    with _FLEETS_LOCK:
        fleets = list(_FLEETS)
    out: dict = {}
    for f in fleets:
        out.update(f.debug_tenants())
    return out


class TenantSession:
    """One tenant cluster resident in the fleet process: its own Store /
    Cluster / Provisioner / solver (own EncodeCache + device-resident carry,
    keyed per cluster the way `_row_cache_key` already keys rows) plus a
    private TraceRecorder so latency quantiles are per-tenant. Only jitted
    kernel SHAPES are shared with other tenants, never tensors."""

    # racecheck guarded-field registry: wake stats are written from watch-
    # delivery threads (the wake_hook / _on_watch_event seams) and read by
    # the fleet loop and stats() callers
    GUARDED_FIELDS = {
        "wakes": "_lock",
        "last_wake_monotonic": "_lock",
    }

    def __init__(self, fleet: "FleetFrontend", tenant_id: str, env, loop: ServingLoop, recorder: TraceRecorder, label: str):
        self.fleet = fleet
        self.tenant_id = tenant_id
        self.label = label
        self.env = env
        self.loop = loop
        self.recorder = recorder
        self._lock = make_lock("fleet-session")
        self.wakes = 0  # wake SIGNALS delivered (watch + trigger seams; a
        # watch-driven trigger legitimately signals through both)
        self.last_wake_monotonic = 0.0

    # -- the push seams --------------------------------------------------------
    def _on_watch_event(self, event: str, obj) -> None:
        """Store watch -> fleet wake (runs on the committing thread under
        the store's delivery lock; registered in the thread-shared registry).
        Covers DELETED events, which never reach the batcher trigger."""
        self.on_trigger("watch-event")

    def _on_batcher_trigger(self) -> None:
        """The batcher's wake_hook seam (fires per trigger, after its lock
        releases) — the second push path, attributed separately so the wake
        split can tell trigger-driven wakes from raw watch deliveries."""
        self.on_trigger("batcher-window")

    def on_trigger(self, cause: str = "watch-event") -> None:
        """Push seam: record the signal and mark this tenant runnable with
        its bounded wake cause (obs.podtrace.WAKE_CAUSES). Cheap and
        leaf-locked by design — it runs on watch delivery threads."""
        with self._lock:
            touch(self, "wakes")
            self.wakes += 1
            self.last_wake_monotonic = time.monotonic()
        self.fleet._mark_runnable(self.tenant_id, cause)

    # -- fleet-facing surface --------------------------------------------------
    def ready(self) -> bool:
        return self.env.provisioner.batcher.ready()

    def pending(self) -> int:
        return self.env.provisioner.batcher.pending()

    def eta(self) -> float | None:
        return self.env.provisioner.batcher.eta()

    def wake_count(self) -> int:
        with self._lock:
            return self.wakes

    def close(self) -> None:
        self.env.provisioner.batcher.wake_hook = None
        self.env.store.unwatch("Pod", self._on_watch_event)
        self.env.store.unwatch("Node", self._on_watch_event)
        self.loop.close()


class FleetFrontend:
    """The multi-tenant serving front-end: tenant registry, push wake, and
    the deficit-round-robin scheduling loop."""

    # racecheck guarded-field registry: the tenant registry and runnable/
    # deficit state are written from watch-delivery threads (_mark_runnable)
    # and the fleet loop concurrently
    GUARDED_FIELDS = {
        "_sessions": "_lock",
        "_order": "_lock",
        "_runnable": "_lock",
        "_deficit": "_lock",
        "_runnable_since": "_lock",
        "_runnable_cause": "_lock",
        "_breakers": "_lock",
        "_shed_first": "_lock",
        "_age_labels": "_lock",
        "_thread": "_lock",
        "_stop": "_lock",
    }

    def __init__(
        self,
        registry=None,
        quantum: float | None = None,
        backlog_solve_cap: float = 4.0,
        poll_floor_seconds: float = 0.5,
        breaker_failures: int = 3,
        breaker_backoff_seconds: float = 0.5,
        breaker_backoff_max: float = 30.0,
        overload_backlog_cap: int | None = None,
        watchdog_age_seconds: float = 5.0,
    ):
        """`quantum`: solve credits added per runnable tenant per `pump()`
        round (deficit round-robin: a solve costs one credit, unspent credit
        banks across rounds, and the bank is capped at `backlog_solve_cap` —
        so a bursty tenant can never run more than the cap's worth of solves
        in one round, and a fractional quantum rate-limits a tenant across
        rounds). Default: the cap itself, so an uncontended tenant drains
        its whole coalesced backlog in one round. `poll_floor_seconds` is
        only the serve loop's LIVENESS backstop — arrivals wake it
        push-style, window closes wake it via `eta()`.

        Failure domains (faultline): each tenant gets a CircuitBreaker —
        `breaker_failures` consecutive pump exceptions QUARANTINE the tenant
        (the fleet keeps serving everyone else) and exponential-backoff
        half-open probes (`breaker_backoff_seconds`, doubling up to
        `breaker_backoff_max`) re-admit it.

        Overload protection: with `overload_backlog_cap` set, a tenant whose
        pending trigger backlog exceeds the cap has its batch generation
        SHED (its pending pods are served later — the tenant degrades
        itself, not the fleet), bounded by the oldest-event-age watchdog:
        once a shedding tenant's backlog ages past `watchdog_age_seconds`
        it is force-served. None (the default) disables shedding entirely."""
        from ..metrics import make_registry
        from ..solver.tpu import configure_compile_cache

        self.registry = registry if registry is not None else make_registry()
        self.backlog_solve_cap = float(backlog_solve_cap)
        self.quantum = self.backlog_solve_cap if quantum is None else float(quantum)
        self.poll_floor = float(poll_floor_seconds)
        self.breaker_failures = int(breaker_failures)
        self.breaker_backoff_seconds = float(breaker_backoff_seconds)
        self.breaker_backoff_max = float(breaker_backoff_max)
        self.overload_backlog_cap = overload_backlog_cap
        self.watchdog_age = float(watchdog_age_seconds)
        self._lock = make_lock("fleet")
        self._wake = make_event()
        self._sessions: dict[str, TenantSession] = {}
        self._order: list[str] = []  # registration order = the DRR ring
        self._runnable: set[str] = set()
        self._deficit: dict[str, float] = {}
        self._runnable_since: dict[str, float] = {}
        # the bounded wake cause that OPENED each runnable episode — handed
        # to the tenant's podtrace at dispatch so per-event records carry it
        self._runnable_cause: dict[str, str] = {}
        # per-tenant circuit breakers (failure-domain isolation) and the
        # first-shed stamp the oldest-event-age watchdog bounds shedding by
        self._breakers: dict[str, CircuitBreaker] = {}
        self._shed_first: dict[str, float] = {}
        # tenant labels with a live oldest-age gauge series (zeroed on exit)
        self._age_labels: set = set()
        self._thread = None
        self._stop = make_event()
        self.pump_rounds = 0
        configure_compile_cache()
        _register_fleet(self)

    # -- tenant registry -------------------------------------------------------
    def add_tenant(
        self,
        tenant_id: str,
        options=None,
        instance_types=None,
        clock=None,
        env=None,
        double_buffer: bool | None = None,
        worker: bool = False,
        trace_capacity: int = 4096,
    ) -> TenantSession:
        """Build (or adopt, via `env`) a tenant control plane and wire it
        into the fleet: shared registry, per-tenant recorder, tenant-labeled
        solver, push-wake seams. The new tenant's first solve runs against
        the fleet's established kernel shapes — warm-start by construction."""
        from ..operator import Environment
        from ..operator.options import Options

        label = tenant_label(tenant_id)
        if env is None:
            env = Environment(
                options=options or Options(solver_backend="tpu"),
                clock=clock,
                instance_types=instance_types,
                registry=self.registry,
            )
        recorder = TraceRecorder(capacity=trace_capacity, enabled=True)
        if env.options.solver_backend == "tpu":
            from ..solver.tpu import TPUSolver

            env.provisioner.solver = TPUSolver(registry=self.registry, recorder=recorder, tenant=label)
        env.provisioner.tenant = label
        # relabel the environment's event tracer with the bounded fleet
        # label (it was built tenant="" before the session existed) and
        # register both per-tenant surfaces for ?tenant= debug routing
        tracer = getattr(env, "podtracer", None)
        if tracer is not None:
            tracer.tenant = label
            from ..obs.podtrace import register_tenant

            register_tenant(label, recorder, tracer)
        loop = ServingLoop(env.provisioner, env.store, double_buffer=double_buffer, worker=worker)
        sess = TenantSession(self, tenant_id, env, loop, recorder, label)
        # the failure-domain gate at the dispatch seam; deterministic
        # drivers get deterministic backoff through the tenant's own clock
        breaker = CircuitBreaker(
            failures_to_open=self.breaker_failures,
            backoff_seconds=self.breaker_backoff_seconds,
            backoff_max=self.breaker_backoff_max,
            now_fn=env.clock.now,
        )
        with self._lock:
            if tenant_id in self._sessions:
                raise ValueError(f"tenant {tenant_id!r} already registered")
            self._sessions[tenant_id] = sess
            self._order.append(tenant_id)
            self._deficit[tenant_id] = 0.0
            self._breakers[tenant_id] = breaker
        self._publish_tenant_state(sess, "healthy")
        # wire the push seams only after the session is registered, so a
        # wake racing registration can never reference an unknown tenant
        env.provisioner.batcher.wake_hook = sess._on_batcher_trigger
        env.store.watch("Pod", sess._on_watch_event)
        env.store.watch("Node", sess._on_watch_event)
        return sess

    def remove_tenant(self, tenant_id: str) -> None:
        with self._lock:
            sess = self._sessions.pop(tenant_id, None)
            if tenant_id in self._order:
                self._order.remove(tenant_id)
            self._runnable.discard(tenant_id)
            self._deficit.pop(tenant_id, None)
            self._runnable_since.pop(tenant_id, None)
            self._runnable_cause.pop(tenant_id, None)
            self._breakers.pop(tenant_id, None)
            self._shed_first.pop(tenant_id, None)
        if sess is not None:
            from .. import metrics as m
            from ..obs.podtrace import unregister_tenant

            # zero every state series for the departing tenant — a tenant
            # removed while quarantined (or mid-probe) must not report a
            # live breaker state forever (same stale-series hygiene as
            # _publish_oldest_ages)
            g = self.registry.gauge(m.SOLVER_TENANT_STATE)
            for s in TENANT_STATES:
                g.set(0.0, tenant=sess.label, state=s)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at registration; state iterates the static TENANT_STATES enum
            unregister_tenant(sess.label)
            sess.close()

    def sessions(self) -> dict[str, TenantSession]:
        with self._lock:
            return dict(self._sessions)

    def session(self, tenant_id: str) -> TenantSession | None:
        with self._lock:
            return self._sessions.get(tenant_id)

    # -- push wake -------------------------------------------------------------
    def _mark_runnable(self, tenant_id: str, cause: str = "rearm") -> int:
        """Mark a tenant runnable and wake the fleet loop. `cause` is the
        bounded wake attribution (obs.podtrace.WAKE_CAUSES) — only the FIRST
        signal of a runnable episode is attributed, so the split counts wake
        episodes, not raw triggers. Runs on watch-delivery threads: fleet
        lock only (leaf), metric emission outside."""
        with self._lock:
            sess = self._sessions.get(tenant_id)
            newly = sess is not None and tenant_id not in self._runnable
            if newly:
                self._runnable.add(tenant_id)
                self._runnable_since.setdefault(tenant_id, time.monotonic())
                self._runnable_cause.setdefault(tenant_id, cause)
            n_runnable = len(self._runnable)
        if newly:
            self._wake.set()
            from .. import metrics as m

            self.registry.counter(m.SOLVER_FLEET_WAKE_TOTAL).inc(tenant=sess.label, cause=cause)  # solverlint: ok(metric-label-cardinality): label is a tenant_label() output fixed at session registration and cause the static WAKE_CAUSES enum threaded from the wake seams
            self.registry.gauge(m.SOLVER_FLEET_RUNNABLE_TENANTS).set(n_runnable)
            tracer = getattr(sess.env, "podtracer", None)
            if tracer is not None:
                tracer.on_wake(cause)
        return 1 if newly else 0

    def runnable_tenants(self) -> list[str]:
        with self._lock:
            return [t for t in self._order if t in self._runnable]

    def rearm_ready(self, cause: str = "rearm") -> int:
        """Poll-fallback re-arm: mark every tenant whose batch window has
        closed (`ready()`) runnable, attributed to `cause` ("batcher-window"
        when the serve loop woke because the nearest eta elapsed,
        "poll-floor" on the liveness backstop, "rearm" for direct calls from
        deterministic drivers). A window that closed by TIME — no new event
        to push a wake — is still served through here."""
        n = 0
        for tid, sess in self.sessions().items():
            if sess.ready():
                self._mark_runnable(tid, cause)
                n += 1
        return n

    def next_eta(self) -> float | None:
        """Seconds until the nearest tenant batch window closes, or None
        when no tenant has an open generation. A quarantined tenant's eta is
        floored at its breaker's remaining backoff — its ready window cannot
        dispatch anyway, and returning its raw eta would hot-spin the serve
        loop against a tenant nothing will serve."""
        etas = []
        with self._lock:
            breakers = dict(self._breakers)
        for tid, s in self.sessions().items():
            e = s.eta()
            if e is None:
                continue
            breaker = breakers.get(tid)
            if breaker is not None:
                e = max(e, breaker.remaining_backoff())
            etas.append(e)
        return min(etas) if etas else None

    def rebalance(self, tenant_id: str, seed: int = 0) -> dict:
        """Opt-in global repack probe for ONE tenant (gated on the same
        KARPENTER_SOLVER_GLOBALPACK hatch as the disruption controller): ask
        the tenant's solver for a joint provisioning+retirement plan over
        its current consolidation candidates and pending pods, via
        `TPUSolver.global_repack_plan`. Returns the plan summary
        ({proposals, objective_improvement, rounded}) WITHOUT executing
        anything — the disruption controller owns exact validation and
        execution; this seam exists so fleet operators can see what a global
        solve would buy a tenant before enabling it there. Empty dict when
        the hatch is off, the tenant is unknown, or its solver lacks the
        tensor seam. Must run on the thread that owns the tenant's solver
        (the pump/operator thread) — same single-threaded solver contract as
        `pump`."""
        import os

        if os.environ.get("KARPENTER_SOLVER_GLOBALPACK", "0").strip().lower() not in ("1", "true", "on"):
            return {}
        sess = self.session(tenant_id)
        if sess is None:
            return {}
        env = sess.env
        solver = env.provisioner.solver
        if not hasattr(solver, "global_repack_plan"):
            return {}
        candidates = env.disruption.get_candidates()
        pending = env.provisioner.get_pending_pods()
        if len(candidates) < 2:
            return {"proposals": 0, "objective_improvement": 0.0, "rounded": 0}
        pools = {c.node_pool.metadata.name: c.node_pool for c in candidates}
        its = []
        for pool in pools.values():
            its.extend(env.provisioner.cloud_provider.get_instance_types(pool))
        subsets, info = solver.global_repack_plan(candidates, its, pending_pods=pending, seed=seed)
        return {"proposals": len(subsets), **info}

    # -- scheduling ------------------------------------------------------------
    def pump(self, force: bool = False, only: str | None = None) -> dict[str, int]:
        """One deficit-round-robin round over the runnable tenants; returns
        {tenant_id: solves run}. At round start every runnable tenant banks
        `quantum` solve credits (bank capped at `backlog_solve_cap`); each
        ring pass serves one solve per tenant with a whole credit, so a
        bursty tenant whose batcher re-arms after every solve (coalesced-
        drain churn) runs at most the cap's worth of solves per round —
        leftover backlog keeps it runnable for the next round — while a
        fractional-quantum tenant accrues across rounds. `force=True`
        treats the addressed tenants as runnable and forces their FIRST
        reconcile (deterministic drivers: harness base-fleet provisioning,
        bench warmup); `only` restricts the round to one tenant (the
        attached-harness drive path — avoids fanning a per-tenant warmup
        solve out across the whole fleet)."""
        self._rearm_overdue_shed()
        with self._lock:
            if force:
                self._runnable.update(self._sessions if only is None else [t for t in (only,) if t in self._sessions])
            ring = [t for t in self._order if t in self._runnable and (only is None or t == only)]
            for tid in ring:
                self._deficit[tid] = min(self._deficit.get(tid, 0.0) + self.quantum, self.backlog_solve_cap)
        self._publish_oldest_ages(ring)
        served: dict[str, int] = {}
        progress = True
        while progress:
            progress = False
            for tid in ring:
                with self._lock:
                    sess = self._sessions.get(tid)
                    active = sess is not None and tid in self._runnable
                    credit = self._deficit.get(tid, 0.0)
                    breaker = self._breakers.get(tid)
                if not active or credit < 1.0:
                    # out-of-credit tenants STAY runnable — the next round
                    # (or the serve loop's next wake) continues them
                    continue
                # force applies to the FIRST solve per tenant only: later
                # solves in the round are the batcher's own coalesced drain,
                # exactly like ServingLoop.pump + drain on the poll path
                eff_force = force and served.get(tid, 0) == 0
                if not (eff_force or sess.ready()):
                    self._retire(tid)
                    continue
                if breaker is not None and not breaker.allow():
                    # QUARANTINED failure domain: the tenant stays registered
                    # and runnable, but nothing dispatches until the backoff
                    # elapses and a half-open probe re-admits it — the ring
                    # moves on and healthy tenants keep being served
                    continue
                probing = breaker is not None and breaker.state_name() == "probing"
                if probing:
                    self._note_transition(sess, "probing")
                    # the gauge must show the half-open window too — a probe
                    # solve can run for seconds (e.g. a full-reencode
                    # recovery) and /metrics reporting `quarantined` for its
                    # whole duration would contradict the TENANT_STATES enum
                    self._publish_tenant_state(sess, "probing")
                # forced pumps are deterministic-driver overrides (harness
                # provisioning, bench warmup) — they bypass load shedding
                if not eff_force and self._should_shed(tid, sess):
                    if probing:
                        # the admitted probe was SHED, not dispatched:
                        # resolve it as inconclusive (re-quarantine without
                        # doubling) — otherwise the breaker wedges in
                        # `probing` (allow() admits exactly one probe per
                        # window) and the tenant can never dispatch again
                        breaker.probe_inconclusive()
                        self._publish_tenant_state(sess, breaker.state_name())
                    continue
                self._observe_sched_wait(tid, sess)
                try:
                    results = sess.loop.pump(force=eff_force)
                except Exception as e:  # solverlint: ok(swallowed-exception): the failure-domain seam — _on_tenant_failure records it on the breaker, the transitions counter, and the tenant-state gauge
                    self._on_tenant_failure(tid, sess, breaker, e)
                    with self._lock:
                        self._deficit[tid] = self._deficit.get(tid, 0.0) - 1.0
                    if not sess.ready():
                        self._retire(tid)
                    continue
                with self._lock:
                    # a declined reconcile (e.g. cluster mid-sync) still
                    # costs the credit, so a stuck tenant cannot pin the loop
                    self._deficit[tid] = self._deficit.get(tid, 0.0) - 1.0
                if results is not None:
                    served[tid] = served.get(tid, 0) + 1
                    progress = True
                    self._on_tenant_success(tid, sess, breaker)
                elif probing:
                    # the probe never produced a verdict — re-quarantine
                    # without doubling so the next window probes again
                    breaker.probe_inconclusive()
                    self._publish_tenant_state(sess, breaker.state_name())
                if not sess.ready():
                    self._retire(tid)
        self.pump_rounds += 1
        self._publish_runnable()
        return served

    # -- failure domains + overload protection (faultline) ---------------------
    def _on_tenant_failure(self, tenant_id: str, sess: TenantSession, breaker: CircuitBreaker | None, err: BaseException) -> None:
        """A tenant pump RAISED past the solver's own degradation ladder:
        record it on the tenant's breaker and publish the state — the
        exception is contained here, the fleet loop never dies."""
        if breaker is None:
            return
        opened = breaker.record_failure(err)
        if opened is not None:
            self._note_transition(sess, "quarantined")
        self._publish_tenant_state(sess, breaker.state_name())

    def _on_tenant_success(self, tenant_id: str, sess: TenantSession, breaker: CircuitBreaker | None) -> None:
        with self._lock:
            self._shed_first.pop(tenant_id, None)
        if breaker is None:
            return
        if breaker.record_success():
            self._note_transition(sess, "healthy")
            self._publish_tenant_state(sess, "healthy")

    def _note_transition(self, sess: TenantSession, state: str) -> None:
        from .. import metrics as m

        self.registry.counter(m.SOLVER_BREAKER_TRANSITIONS_TOTAL).inc(tenant=sess.label, state=state)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at registration; state literals at every call site come from the static TENANT_STATES enum

    def _publish_tenant_state(self, sess: TenantSession, state: str) -> None:
        from .. import metrics as m

        g = self.registry.gauge(m.SOLVER_TENANT_STATE)
        for s in TENANT_STATES:
            g.set(1.0 if s == state else 0.0, tenant=sess.label, state=s)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at registration; state iterates the static TENANT_STATES enum

    def _should_shed(self, tenant_id: str, sess: TenantSession) -> bool:
        """Per-tenant overload protection: when the tenant's pending trigger
        backlog exceeds the cap, SHED its batch generation (the triggers are
        dropped; the pods stay pending in the store and are served by a
        later, larger window) instead of solving — the overloaded tenant
        degrades its own latency, not the fleet's. Shedding is bounded by
        the oldest-event-age watchdog: once the tenant has been shedding
        for `watchdog_age` seconds it is force-served."""
        cap = self.overload_backlog_cap
        if not cap:
            return False
        pending = sess.pending()
        if pending <= cap:
            with self._lock:
                self._shed_first.pop(tenant_id, None)
            return False
        from .. import metrics as m

        # the tenant's OWN clock, same as its breaker's backoff: shedding
        # stays deterministic under a fake-clock driver and a slow CI
        # machine cannot trip the watchdog mid-test on wall time
        now = sess.env.clock.now()
        with self._lock:
            first = self._shed_first.setdefault(tenant_id, now)
        if now - first >= self.watchdog_age:
            # the watchdog bound: serve now, open the next shed window
            self.registry.counter(m.SOLVER_FLEET_WATCHDOG_TOTAL).inc(tenant=sess.label)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at session registration — the bounded fleet enum
            with self._lock:
                self._shed_first[tenant_id] = now
            return False
        sess.env.provisioner.batcher.reset()
        self.registry.counter(m.SOLVER_FLEET_SHED_TOTAL).inc(pending, tenant=sess.label)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at session registration — the bounded fleet enum
        self._retire(tenant_id)
        return True

    def _rearm_overdue_shed(self) -> None:
        """The watchdog's out-of-band half: a shed dropped the tenant's
        batch generation, so if arrivals then STOP nothing would ever
        re-open its window and the shed backlog (pods still pending in the
        store) would strand forever. For every shed-stamped tenant whose
        stamp aged past the watchdog bound with no window pending, fire one
        batcher trigger — the stranded pods are served by the normal window
        one idle-duration later. The stamp advances to now, so the re-arm
        fires at most once per watchdog period."""
        from .. import metrics as m

        with self._lock:
            stamped = [(t, self._sessions[t], f) for t, f in self._shed_first.items() if t in self._sessions]
        for tid, sess, first in stamped:
            now = sess.env.clock.now()
            if now - first < self.watchdog_age or sess.pending():
                continue
            with self._lock:
                self._shed_first[tid] = now
            self.registry.counter(m.SOLVER_FLEET_WATCHDOG_TOTAL).inc(tenant=sess.label)  # solverlint: ok(metric-label-cardinality): tenant is a tenant_label() output fixed at session registration — the bounded fleet enum
            sess.env.provisioner.trigger("shed-watchdog")

    def _publish_oldest_ages(self, ring: list) -> None:
        from .. import metrics as m

        now = time.monotonic()
        with self._lock:
            ages = {
                self._sessions[t].label: now - self._runnable_since.get(t, now)
                for t in ring
                if t in self._sessions
            }
            # zero the series of tenants that LEFT the ring: a drained
            # tenant's gauge must not freeze at its last nonzero age
            stale = self._age_labels - set(ages)
            self._age_labels = set(ages)
        g = self.registry.gauge(m.SOLVER_FLEET_OLDEST_EVENT_AGE)
        for label in sorted(stale):
            g.set(0.0, tenant=label)  # solverlint: ok(metric-label-cardinality): label is a tenant_label() output recorded at session registration — the capped fleet enum
        for label, age in ages.items():
            g.set(age, tenant=label)  # solverlint: ok(metric-label-cardinality): label is a tenant_label() output recorded at session registration — the capped fleet enum

    def debug_tenants(self) -> dict:
        """The /debug/tenants rows: per-tenant breaker state, backlog, and
        wake stats — the observable half of failure-domain isolation."""
        out: dict = {}
        for tid, sess in self.sessions().items():
            with self._lock:
                breaker = self._breakers.get(tid)
                runnable = tid in self._runnable
            row = {
                "label": sess.label,
                "runnable": runnable,
                "pending_triggers": sess.pending(),
                "wakes": sess.wake_count(),
            }
            if breaker is not None:
                row.update(breaker.snapshot())
            out[tid] = row
        return out

    def _retire(self, tenant_id: str) -> None:
        """The tenant's window is no longer ready: drop it from the runnable
        set and zero its banked deficit (DRR resets credit on empty)."""
        with self._lock:
            self._runnable.discard(tenant_id)
            self._deficit[tenant_id] = 0.0
            self._runnable_since.pop(tenant_id, None)
            self._runnable_cause.pop(tenant_id, None)

    def _observe_sched_wait(self, tenant_id: str, sess: TenantSession) -> None:
        with self._lock:
            since = self._runnable_since.pop(tenant_id, None)
            cause = self._runnable_cause.pop(tenant_id, "")
            credit = self._deficit.get(tenant_id, 0.0)
        if since is not None:
            wait = time.monotonic() - since
            from .. import metrics as m

            self.registry.histogram(m.SOLVER_FLEET_SCHED_WAIT_SECONDS).observe(
                wait, tenant=sess.label  # solverlint: ok(metric-label-cardinality): label is a tenant_label() output fixed at session registration — the bounded fleet enum
            )
            # hand the DRR wait (plus round + banked credit + the episode's
            # wake cause at dispatch) to the tenant's event tracer: the next
            # dispatch's events carry them on their records
            tracer = getattr(sess.env, "podtracer", None)
            if tracer is not None:
                tracer.note_sched_wait(wait, drr_round=self.pump_rounds, credit=credit, cause=cause)

    def _publish_runnable(self) -> None:
        with self._lock:
            n_runnable = len(self._runnable)
        from .. import metrics as m

        self.registry.gauge(m.SOLVER_FLEET_RUNNABLE_TENANTS).set(n_runnable)

    # -- the wall-clock serve loop --------------------------------------------
    def start(self) -> None:
        """Spawn the fleet serve loop (wall-clock deployments; deterministic
        drivers call `pump()` directly instead)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop = make_event()
            self._thread = spawn_thread(self._serve_loop, name="karpenter-fleet", args=(self._stop,))

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            stop = self._stop
        stop.set()
        self._wake.set()
        if t is not None:
            t.join(timeout=5)

    def serving(self) -> bool:
        with self._lock:
            return self._thread is not None

    def _serve_loop(self, stop) -> None:
        """Push-driven: sleep on the wake event until an arrival wakes us or
        the nearest batch window closes; then re-arm time-ready tenants and
        run one DRR round. The poll floor is only a liveness backstop."""
        while not stop.is_set():
            timeout = self.poll_floor
            eta = self.next_eta()
            if eta is not None:
                timeout = min(timeout, eta)
            if timeout > 0:
                self._wake.wait(timeout=timeout)
            self._wake.clear()
            if stop.is_set():
                return
            # wake attribution: push wakes attributed themselves at the
            # trigger seams; any tenant rearm_ready marks here is one whose
            # window closed by TIME — "batcher-window" whenever a window was
            # open (incl. timeout<=0 and push-coincident sweeps), the
            # "poll-floor" liveness backstop otherwise. The "rearm" cause
            # stays reserved for deterministic drivers calling rearm_ready
            # directly.
            self.rearm_ready("batcher-window" if eta is not None else "poll-floor")
            served = self.pump()
            if not served and (eta := self.next_eta()) is not None and eta <= 0:
                # a window is ready but its reconcile declined to solve —
                # e.g. the cluster is mid-registration and unsynced while
                # the tick thread catches up. eta()==0 would make the wait
                # above a no-op, so back off briefly instead of hot-spinning
                # against the very thread that clears the condition.
                self._wake.wait(timeout=0.005)

    def close(self) -> None:
        self.stop()
        for tid in list(self.sessions()):
            self.remove_tenant(tid)
        _unregister_fleet(self)

    # -- observability ---------------------------------------------------------
    def stats(self) -> dict:
        """Per-tenant serving stats from each session's private recorder
        (solves by mode, rolling quantiles, wakes, backlog)."""
        out: dict = {}
        for tid, sess in self.sessions().items():
            traces = [t for t in sess.recorder.traces() if t.mode not in ("", "consolidate")]
            modes: dict[str, int] = {}
            for t in traces:
                modes[t.mode] = modes.get(t.mode, 0) + 1
            out[tid] = {
                "label": sess.label,
                "solves": len(traces),
                "modes": modes,
                "quantiles": sess.recorder.stats(),
                "wakes": sess.wake_count(),
                "pending_triggers": sess.pending(),
            }
        return out

    def isolation_audit(self) -> dict:
        """Audit the fleet-scoped (process-global) solver caches for cross-
        tenant isolation: SHAPES and content-addressed pod-shape tuples are
        shared by design; row TENSORS must be keyed by a process-unique
        cluster epoch. Raises AssertionError when a registered tenant's
        cluster epoch collides with another's, or when a row-cache key does
        not lead with an epoch token — either would make one tenant's
        tensors reachable from another's lookups."""
        from ..models.scheduler_model import bucket_highwater
        from ..solver.encode import encode_shared_stats

        shared = encode_shared_stats()
        epochs: dict[int, str] = {}
        for tid, sess in self.sessions().items():
            epoch = getattr(sess.env.cluster, "epoch", None)
            assert epoch is not None, f"tenant {tid!r}: cluster has no epoch token — row-cache keys would be id()-recyclable"
            assert epoch not in epochs, f"tenants {epochs[epoch]!r} and {tid!r} share cluster epoch {epoch} — row artifacts would alias"
            epochs[epoch] = tid
        for e in shared["row_global_epochs"]:
            assert isinstance(e, int), f"row-cache key epoch {e!r} is not a process-unique token"
        return {
            "shared_shapes": bucket_highwater(),
            "shared_sig_intern": shared["sig_intern"],
            "row_artifacts": shared["row_global"],
            "tenant_epochs": {tid: e for e, tid in epochs.items()},
            "tenant_row_artifacts": {
                epochs[e]: n for e, n in shared["row_global_by_epoch"].items() if e in epochs
            },
        }
