"""The KWOK operator binary (reference: kwok/main.go:29-51).

Boots the full control plane against the in-process store with the embedded
KWOK instance-type catalog, serves health/metrics endpoints, and runs the
leader-elected reconcile loop on the wall clock until interrupted:

    python -m karpenter_tpu [--solver tpu] [--port 8080]

Options also come from the environment (operator/options.py from_env):
FEATURE_GATES, SOLVER_BACKEND, BATCH_*_DURATION, PREFERENCE_POLICY, ...
"""

from __future__ import annotations

import argparse
import signal

from .obs.racecheck import make_event
from .operator import Environment
from .operator.options import Options
from .operator.server import OperatorServer
from .utils.clock import Clock


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--solver", choices=("ffd", "tpu"), default=None, help="solver backend (SOLVER_BACKEND)")
    parser.add_argument("--port", type=int, default=None, help="health + metrics port (0 = ephemeral; default --metrics-port)")
    parser.add_argument("--bind", default="0.0.0.0", help="health + metrics bind address")
    parser.add_argument("--tick-seconds", type=float, default=1.0, help="controller round interval")
    parser.add_argument(
        "--fleet-tenants",
        type=int,
        default=0,
        help="N>0 boots the multi-tenant fleet front-end instead of the single-cluster "
        "loop: N tenant control planes in this process, push-driven wake, shared "
        "jitted kernels, one /metrics (tenant-labeled). Tenant ids are tenant-0..N-1; "
        "KARPENTER_SOLVER_COMPILE_CACHE=<dir> persists compiles across restarts.",
    )
    parser.add_argument(
        "--fleet-shards",
        type=int,
        default=0,
        help="N>0 boots the SHARDED fleet (serving/shard.py): N shard worker "
        "processes, each a FleetFrontend over its consistent-hash slice of the "
        "--fleet-tenants tenants, sharing one KARPENTER_SOLVER_COMPILE_CACHE. "
        "This process runs the ShardRouter + the aggregated /metrics, "
        "/debug/tenants, /debug/shards, and ?tenant=-proxied debug surfaces.",
    )
    # every reference flag (options.go AddFlags: --metrics-port,
    # --kube-client-qps, --log-level, --disable-leader-election,
    # --enable-profiling, --feature-gates, ...) parses via Options.from_args
    args, rest = parser.parse_known_args(argv)

    options = Options.from_args(rest)
    if args.solver:
        options.solver_backend = args.solver
    port = args.port if args.port is not None else options.metrics_port

    import logging
    import sys as _sys

    handlers = []
    for path in options.log_output_paths.split(","):
        path = path.strip()
        if path in ("stdout", "stderr"):
            handlers.append(logging.StreamHandler(getattr(_sys, path)))
        elif path:
            handlers.append(logging.FileHandler(path))
    logging.basicConfig(
        level={"debug": logging.DEBUG, "info": logging.INFO, "error": logging.ERROR}[options.log_level],
        handlers=handlers or None,
    )

    if args.fleet_shards > 0:
        return _run_sharded(args, options, port)
    if args.fleet_tenants > 0:
        return _run_fleet(args, options, port)

    env = Environment(options=options, clock=Clock())
    server = OperatorServer(env, port=port, enable_profiling=options.enable_profiling, bind=args.bind)
    port = server.start()
    # dedicated health-probe listener (options.go --health-probe-port) when it
    # differs from the metrics port, so k8s probes pointed at the flag work
    health_server = None
    if options.health_probe_port not in (port, 0):
        health_server = OperatorServer(env, port=options.health_probe_port, enable_profiling=False, bind=args.bind)
        try:
            health_server.start()
        except (OSError, OverflowError) as e:
            print(f"health-probe port {options.health_probe_port} unavailable: {e}", flush=True)
            health_server = None
    print(f"karpenter-tpu operator up: solver={options.solver_backend} http={args.bind}:{port}", flush=True)

    stop = make_event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # not the main thread
    try:
        env.run(
            stop_event=stop,
            tick_seconds=args.tick_seconds,
            leader_election=not options.disable_leader_election,
        )
    finally:
        server.stop()
        if health_server is not None:
            health_server.stop()
    return 0


def _run_sharded(args, options, port: int) -> int:
    """Sharded fleet mode: this process is the ShardRouter — it spawns
    --fleet-shards worker processes (each its own FleetFrontend serve loop
    over a consistent-hash slice of the tenants, sharing one persistent
    compile cache and a contiguous device slice), seats tenant-0..K-1 on
    the ring, starts every shard serving, and fronts the aggregated
    debug/metrics surfaces plus the breaker-driven health monitor."""
    import os

    from .serving.shard import ShardRouter

    n_tenants = args.fleet_tenants if args.fleet_tenants > 0 else args.fleet_shards
    router = ShardRouter(
        n_shards=args.fleet_shards,
        solver=options.solver_backend,
        cache_dir=os.environ.get("KARPENTER_SOLVER_COMPILE_CACHE", "").strip() or None,
    )
    router.spawn()
    server = None
    try:
        for i in range(n_tenants):
            router.add_tenant(f"tenant-{i}")
        router.start_serving(tick_seconds=args.tick_seconds)
        router.start_monitor()
        server = OperatorServer(None, port=port, enable_profiling=options.enable_profiling, bind=args.bind, router=router)
        port = server.start()
        print(
            f"karpenter-tpu sharded fleet up: shards={args.fleet_shards} tenants={n_tenants} "
            f"solver={options.solver_backend} http={args.bind}:{port}",
            flush=True,
        )
        stop = make_event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, lambda *_: stop.set())
            except ValueError:
                pass  # not the main thread
        stop.wait()
    finally:
        if server is not None:
            server.stop()
        router.close()
    return 0


def _run_fleet(args, options, port: int) -> int:
    """Fleet mode: one process, N tenant control planes, the push-driven
    DRR serve loop, and a single metrics/debug endpoint over the shared
    registry (the first tenant's environment fronts the HTTP surface — its
    registry IS the fleet registry). Leader election is per-cluster state
    the fleet does not arbitrate; run one fleet per shard."""
    from .metrics import make_registry
    from .serving.fleet import FleetFrontend

    registry = make_registry()
    fleet = FleetFrontend(registry=registry)
    sessions = []
    for i in range(args.fleet_tenants):
        sessions.append(fleet.add_tenant(f"tenant-{i}", options=options, clock=Clock()))
    server = OperatorServer(sessions[0].env, port=port, enable_profiling=options.enable_profiling, bind=args.bind)
    port = server.start()
    # same dedicated health-probe listener contract as the single-cluster
    # path: k8s probes pointed at --health-probe-port must answer
    health_server = None
    if options.health_probe_port not in (port, 0):
        health_server = OperatorServer(sessions[0].env, port=options.health_probe_port, enable_profiling=False, bind=args.bind)
        try:
            health_server.start()
        except (OSError, OverflowError) as e:
            print(f"health-probe port {options.health_probe_port} unavailable: {e}", flush=True)
            health_server = None
    print(
        f"karpenter-tpu fleet up: tenants={args.fleet_tenants} solver={options.solver_backend} "
        f"http={args.bind}:{port}",
        flush=True,
    )
    stop = make_event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # not the main thread
    fleet.start()
    try:
        # the fleet serve loop owns ALL solves; this thread runs the
        # per-tenant controller rounds (lifecycle/bind/GC) at the tick
        # cadence with provisioning skipped (tick(provision=False))
        while not stop.is_set():
            for sess in sessions:
                sess.env.tick(provision=False)
            stop.wait(args.tick_seconds)
    finally:
        fleet.close()
        server.stop()
        if health_server is not None:
            health_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
