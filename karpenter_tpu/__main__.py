"""The KWOK operator binary (reference: kwok/main.go:29-51).

Boots the full control plane against the in-process store with the embedded
KWOK instance-type catalog, serves health/metrics endpoints, and runs the
leader-elected reconcile loop on the wall clock until interrupted:

    python -m karpenter_tpu [--solver tpu] [--port 8080]

Options also come from the environment (operator/options.py from_env):
FEATURE_GATES, SOLVER_BACKEND, BATCH_*_DURATION, PREFERENCE_POLICY, ...
"""

from __future__ import annotations

import argparse
import signal

from .obs.racecheck import make_event
from .operator import Environment
from .operator.options import Options
from .operator.server import OperatorServer
from .utils.clock import Clock


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--solver", choices=("ffd", "tpu"), default=None, help="solver backend (SOLVER_BACKEND)")
    parser.add_argument("--port", type=int, default=None, help="health + metrics port (0 = ephemeral; default --metrics-port)")
    parser.add_argument("--bind", default="0.0.0.0", help="health + metrics bind address")
    parser.add_argument("--tick-seconds", type=float, default=1.0, help="controller round interval")
    # every reference flag (options.go AddFlags: --metrics-port,
    # --kube-client-qps, --log-level, --disable-leader-election,
    # --enable-profiling, --feature-gates, ...) parses via Options.from_args
    args, rest = parser.parse_known_args(argv)

    options = Options.from_args(rest)
    if args.solver:
        options.solver_backend = args.solver
    port = args.port if args.port is not None else options.metrics_port

    import logging
    import sys as _sys

    handlers = []
    for path in options.log_output_paths.split(","):
        path = path.strip()
        if path in ("stdout", "stderr"):
            handlers.append(logging.StreamHandler(getattr(_sys, path)))
        elif path:
            handlers.append(logging.FileHandler(path))
    logging.basicConfig(
        level={"debug": logging.DEBUG, "info": logging.INFO, "error": logging.ERROR}[options.log_level],
        handlers=handlers or None,
    )

    env = Environment(options=options, clock=Clock())
    server = OperatorServer(env, port=port, enable_profiling=options.enable_profiling, bind=args.bind)
    port = server.start()
    # dedicated health-probe listener (options.go --health-probe-port) when it
    # differs from the metrics port, so k8s probes pointed at the flag work
    health_server = None
    if options.health_probe_port not in (port, 0):
        health_server = OperatorServer(env, port=options.health_probe_port, enable_profiling=False, bind=args.bind)
        try:
            health_server.start()
        except (OSError, OverflowError) as e:
            print(f"health-probe port {options.health_probe_port} unavailable: {e}", flush=True)
            health_server = None
    print(f"karpenter-tpu operator up: solver={options.solver_backend} http={args.bind}:{port}", flush=True)

    stop = make_event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # not the main thread
    try:
        env.run(
            stop_event=stop,
            tick_seconds=args.tick_seconds,
            leader_election=not options.disable_leader_election,
        )
    finally:
        server.stop()
        if health_server is not None:
            health_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
