"""Static capacity: replica-count NodePools maintained as fixed fleets.

Reference: pkg/controllers/static/{provisioning,deprovisioning} — a NodePool
with spec.replicas set is excluded from demand-driven provisioning; these two
controllers scale the fleet up (create NodeClaims straight from the template)
and down (drain-priority-ordered NodeClaim deletion).
"""

from .provisioning import StaticProvisioningController  # noqa: F401
from .deprovisioning import StaticDeprovisioningController  # noqa: F401
