"""Static scale-down: delete surplus NodeClaims when replicas shrink.

Reference: static/deprovisioning/controller.go:84-135 + candidate selection
:185-313 — surplus = live claims minus spec.replicas; candidates are picked
cheapest-to-disrupt first: unlaunched claims (no providerID), then empty
nodes, then lowest rescheduling-cost x lifetime-remaining, with
do-not-disrupt-hosting nodes last.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...utils import disruption as disruption_utils
from ...utils import pods as pod_utils

TERMINATION_REASON = "overprovisioned"


class StaticDeprovisioningController:
    def __init__(self, store, cluster, cloud_provider, clock, recorder=None, metrics=None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        self.metrics = metrics

    def reconcile(self) -> None:
        for np in self.store.list("NodePool"):
            if not np.is_static() or np.metadata.deletion_timestamp is not None:
                continue
            self._reconcile_pool(np)

    def _reconcile_pool(self, np) -> None:
        from ...apis.nodeclaim import COND_DISRUPTION_REASON

        pool = np.metadata.name
        # claims already pending disruption don't count as running: the
        # disruption queue is mid-replacement and the fleet would otherwise
        # look transiently over-provisioned (deprovisioning controller.go:95-99)
        live = [
            nc
            for nc in self.store.list("NodeClaim")
            if nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == pool
            and nc.metadata.deletion_timestamp is None
            and not nc.status.conditions.is_true(COND_DISRUPTION_REASON)
        ]
        surplus = len(live) - (np.spec.replicas or 0)
        if surplus <= 0:
            return
        for nc in self._candidates(np, live, surplus):
            self.store.try_delete("NodeClaim", nc.metadata.name)
            self.cluster.mark_for_deletion([nc.status.provider_id or f"nodeclaim://{nc.metadata.name}"])
            if self.recorder is not None:
                self.recorder.publish(nc, "Deprovisioned", f"static nodepool {pool} {TERMINATION_REASON}")
            if self.metrics is not None:
                from ... import metrics as m

                self.metrics.counter(m.NODECLAIMS_TERMINATED_TOTAL).inc(
                    nodepool=pool,
                    capacity_type=nc.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY, ""),
                    zone=nc.metadata.labels.get(wk.ZONE_LABEL_KEY, ""),
                )

    def _candidates(self, np, live: list, count: int) -> list:
        """Selection priority (deprovisioning/controller.go:181-313)."""
        # 1. claims that never launched (no providerID)
        unresolved = [nc for nc in live if not nc.status.provider_id]
        picked = unresolved[:count]
        if len(picked) == count:
            return picked

        resolved = [nc for nc in live if nc.status.provider_id]
        empties, nonempty = [], []
        for nc in resolved:
            sn = self.cluster.node_for_claim(nc.metadata.name)
            if sn is None or sn.marked_for_deletion:
                continue
            pods = self._pods_on(sn.name())
            dnd = any(pod_utils.has_do_not_disrupt(p, self.clock.now()) for p in pods)
            non_daemon = [p for p in pods if not pod_utils.is_owned_by_daemonset(p)]
            if not non_daemon and not dnd:
                empties.append(nc)
            else:
                nonempty.append((nc, pods, dnd))

        # 2. empty nodes
        picked += empties[: count - len(picked)]
        if len(picked) == count:
            return picked

        # 3. cheapest-to-disrupt: rescheduling cost x lifetime remaining;
        #    do-not-disrupt hosts sort last regardless of cost
        from ...utils.durations import parse_duration

        expire_after = parse_duration(np.spec.template.expire_after)
        nonempty.sort(
            key=lambda t: (
                t[2],
                disruption_utils.rescheduling_cost(t[1])
                * disruption_utils.lifetime_remaining(self.clock.now(), expire_after, t[0].metadata.creation_timestamp),
            )
        )
        picked += [nc for nc, _, _ in nonempty[: count - len(picked)]]
        return picked

    def _pods_on(self, node_name: str) -> list:
        return [
            p
            for p in self.store.list("Pod")
            if p.spec.node_name == node_name and pod_utils.is_active(p)
        ]
