"""Static scale-up: keep each replica NodePool at its desired NodeClaim count.

Reference: static/provisioning/controller.go:75-123 — count the pool's live
NodeClaims, and when below spec.replicas create the difference directly from
the NodeClaim template (no pod-driven scheduling), capped by the pool's node
limit. Scale-down is the deprovisioning controller's job.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...apis.nodepool import COND_NODEPOOL_READY
from ..provisioning.scheduling.nodeclaim import NodeClaimTemplate, SchedulingNodeClaim


class _NullTopology:
    """SchedulingNodeClaim registers its hostname with the solve topology;
    a static claim has no solve, so registration is a no-op."""

    def register(self, key, value):
        pass


def build_static_claim(np, instance_types) -> SchedulingNodeClaim:
    """A pod-less NodeClaim straight from the pool template — how static
    fleets and their drift replacements are built (static/provisioning
    controller.go:109-115, staticdrift.go:92-96)."""
    template = NodeClaimTemplate(np)
    template.instance_type_options = instance_types
    claim = SchedulingNodeClaim(template, _NullTopology(), [], instance_types)
    claim.finalize()
    return claim


def node_limit_headroom(np, live: int) -> int:
    """How many more nodes the pool's limits.nodes allows; unbounded pools
    report a large sentinel."""
    if np.spec.limits and "nodes" in np.spec.limits:
        return max(0, int(np.spec.limits["nodes"].value) - live)
    return 1 << 30


class StaticProvisioningController:
    def __init__(self, store, cluster, cloud_provider, provisioner, clock, metrics=None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.provisioner = provisioner
        self.clock = clock
        self.metrics = metrics

    def reconcile(self) -> None:
        for np in self.store.list("NodePool"):
            if not np.is_static() or np.metadata.deletion_timestamp is not None:
                continue
            if np.status.conditions.is_false(COND_NODEPOOL_READY):
                continue
            self._reconcile_pool(np)

    def _reconcile_pool(self, np) -> None:
        running = self._live_claim_count(np.metadata.name)
        desired = np.spec.replicas or 0
        if running >= desired:
            return
        # node-count limit caps the fleet (controller.go:97-104)
        to_create = min(desired - running, node_limit_headroom(np, running))
        if to_create <= 0:
            return
        its = self.cloud_provider.get_instance_types(np)
        if not its:
            return
        for _ in range(to_create):
            claim = build_static_claim(np, its)
            if self.provisioner.create_node_claim(claim, reason="static_provisioned") is None:
                return

    def _live_claim_count(self, pool: str) -> int:
        return sum(
            1
            for nc in self.store.list("NodeClaim")
            if nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == pool and nc.metadata.deletion_timestamp is None
        )
