"""Disruption: consolidation (single/multi-node), emptiness, drift —
the reference's second computational heart (SURVEY.md §3.2).
"""

from .controller import DisruptionController  # noqa: F401
from .types import Candidate, Command  # noqa: F401
