"""Disruption methods, run in priority order: Emptiness -> Drift ->
MultiNodeConsolidation -> SingleNodeConsolidation.

Reference: disruption/{emptiness,drift,consolidation,multinodeconsolidation,
singlenodeconsolidation}.go. Each method computes Commands from candidates
under budget constraints; the controller executes the first non-empty one.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...apis.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED
from ...apis.nodepool import BALANCED, WHEN_EMPTY, WHEN_EMPTY_OR_UNDERUTILIZED
from ...cloudprovider.types import order_by_price
from .helpers import all_non_pending_scheduled, simulate_scheduling
from .types import REASON_DRIFTED, REASON_EMPTY, REASON_UNDERUTILIZED, Command

MULTI_NODE_CONSOLIDATION_CANDIDATE_CAP = 100  # multinodeconsolidation.go:35
# compute caps on the deterministic clock: one slow pool must not starve the
# 10s rounds forever (multinodeconsolidation.go:35, singlenodeconsolidation.go:33)
MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS = 60.0
SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS = 180.0
# how many ranked proposals the 15s exact Validator may be run against in one
# multi-node round: the winner plus fallbacks pulled lazily from the proposer's
# ladder when validation rejects (each attempt pays the full 15s wait, so the
# cap also bounds wall-clock alongside the shared deadline)
MULTI_NODE_VALIDATION_ATTEMPTS = 3


class Emptiness:
    """Delete nodes with no reschedulable pods (emptiness.go)."""

    reason = REASON_EMPTY
    consolidation_type = "empty"

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, candidate) -> bool:
        # every consolidation policy permits removing empty nodes; the
        # Consolidatable condition (consolidateAfter) is the only gate.
        # Static fleets hold their replica count (emptiness.go:43).
        if candidate.node_claim is None or candidate.owned_by_static_node_pool():
            return False
        if not candidate.node_claim.status.conditions.is_true(COND_CONSOLIDATABLE):
            return False
        # a node hosting virtual buffer pods is not empty: the provisioner put
        # headroom there on purpose (emptiness.go:51-57)
        if self.ctx.cluster.has_buffer_pods(candidate.state_node.provider_id()):
            return False
        return len(candidate.reschedulable_pods) == 0

    def compute_commands(self, candidates, budgets) -> list[Command]:
        empty = [c for c in candidates if self.should_disrupt(c)]
        allowed = dict(budgets)
        chosen = []
        for c in empty:
            pool = c.node_pool.metadata.name
            if allowed.get(pool, 0) > 0:
                chosen.append(c)
                allowed[pool] -= 1
        if not chosen:
            return []
        cmd = Command(reason=REASON_EMPTY, candidates=chosen)
        # 15s wait + live re-check; the command shrinks to surviving nodes
        # (emptiness.go:101, validation.go:134-148)
        from .validation import ValidationError, Validator

        try:
            cmd = Validator(self.ctx, self, mode="subset", metrics=self.ctx.metrics).validate(cmd)
        except ValidationError:
            return []
        return [cmd]


class StaticDrift:
    """Replace drifted static-fleet nodes 1:1 from the pool template
    (staticdrift.go:50-106): no scheduling simulation — the replacement is a
    fresh template claim, created before the drifted one drains."""

    reason = REASON_DRIFTED
    consolidation_type = ""

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, candidate) -> bool:
        return (
            candidate.node_claim is not None
            and candidate.owned_by_static_node_pool()
            and candidate.node_claim.status.conditions.is_true(COND_DRIFTED)
        )

    def compute_commands(self, candidates, budgets) -> list[Command]:
        from ..static.provisioning import build_static_claim, node_limit_headroom

        by_pool: dict[str, list] = {}
        for c in candidates:
            if self.should_disrupt(c):
                by_pool.setdefault(c.node_pool.metadata.name, []).append(c)
        out = []
        for pool_name, cs in by_pool.items():
            np = cs[0].node_pool
            allowed = budgets.get(pool_name, 0)
            if allowed <= 0:
                continue
            # don't churn while the pool is above its replica count — scale
            # down first (staticdrift.go:74-78)
            live = sum(
                1
                for sn in self.ctx.cluster.nodes()
                if sn.labels().get(wk.NODEPOOL_LABEL_KEY) == pool_name and not sn.deleted()
            )
            if live > (np.spec.replicas or 0):
                continue
            max_drifts = min(allowed, len(cs), node_limit_headroom(np, live))
            if max_drifts <= 0:
                continue
            its = self.ctx.provisioner.cloud_provider.get_instance_types(np)
            if not its:
                continue
            for c in cs[:max_drifts]:
                out.append(
                    Command(reason=REASON_DRIFTED, candidates=[c], replacements=[build_static_claim(np, its)])
                )
        return out


class Drift:
    """Replace drifted nodes (drift.go); drift is detected by the nodeclaim
    disruption controller setting the Drifted condition."""

    reason = REASON_DRIFTED
    consolidation_type = "drift"

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, candidate) -> bool:
        return (
            candidate.node_claim is not None
            and not candidate.owned_by_static_node_pool()  # StaticDrift's job (drift.go:59)
            and candidate.node_claim.status.conditions.is_true(COND_DRIFTED)
        )

    def compute_commands(self, candidates, budgets) -> list[Command]:
        drifted = sorted(
            (c for c in candidates if self.should_disrupt(c)),
            key=lambda c: c.disruption_cost,
        )
        allowed = dict(budgets)
        out = []
        for c in drifted:
            pool = c.node_pool.metadata.name
            if allowed.get(pool, 0) <= 0:
                continue
            results = simulate_scheduling(self.ctx.provisioner, self.ctx.cluster, [c], self.ctx.clock)
            if not all_non_pending_scheduled(results, [c]):
                continue
            allowed[pool] -= 1
            out.append(
                Command(
                    reason=REASON_DRIFTED,
                    candidates=[c],
                    replacements=[nc for nc in results.new_node_claims],
                    results=results,
                )
            )
        return out


class _ConsolidationBase:
    reason = REASON_UNDERUTILIZED

    def __init__(self, ctx):
        self.ctx = ctx

    def sort_candidates(self, eligible: list) -> list:
        """Shared consolidation order: highest savings per unit disruption
        first, so budget- and timeout-limited rounds spend themselves on the
        most impactful moves (consolidation.go:140-154 sortCandidates by
        SavingsRatio desc). Single-node layers its NodePool interweave on
        top; multi-node's prefix binary search windows over this order."""
        return sorted(eligible, key=lambda c: c.savings_ratio(), reverse=True)

    def should_disrupt(self, candidate) -> bool:
        if candidate.node_claim is None or candidate.owned_by_static_node_pool():
            return False
        policy = candidate.node_pool.spec.disruption.consolidation_policy
        if policy == WHEN_EMPTY:
            return False  # only emptiness may disrupt
        return candidate.node_claim.status.conditions.is_true(COND_CONSOLIDATABLE)

    def compute_consolidation(self, candidates, reuse=None) -> Command:
        """The consolidation decision (consolidation.go:159-254). `reuse` is
        the round's ConsolidationSimulator: proposal checks inside its
        correctness envelope run as masked sub-encode simulations; the 15s
        Validator never passes one."""
        if not candidates:
            # nothing to consolidate — don't burn a simulation on it
            return Command()
        ctx = self.ctx
        results = simulate_scheduling(ctx.provisioner, ctx.cluster, candidates, ctx.clock, reuse=reuse)
        if not all_non_pending_scheduled(results, candidates):
            return Command()
        if len(results.new_node_claims) == 0:
            return Command(reason=self.reason, candidates=list(candidates), results=results)
        if len(results.new_node_claims) != 1:
            return Command()

        candidate_price = sum(c.price for c in candidates)
        replacement = results.new_node_claims[0]
        replacement.instance_type_options = order_by_price(replacement.instance_type_options, replacement.requirements)

        all_spot = all(c.capacity_type == wk.CAPACITY_TYPE_SPOT for c in candidates)
        ct_req = replacement.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
        if all_spot and ct_req.has(wk.CAPACITY_TYPE_SPOT):
            return self._spot_to_spot(candidates, results, candidate_price)

        # keep only strictly cheaper replacement types (nodeclaim.go:411
        # RemoveInstanceTypeOptionsByPriceAndMinValues)
        kept = _filter_by_price(replacement, candidate_price)
        if not kept:
            return Command()
        replacement.instance_type_options = kept

        # if both spot and on-demand survive, force spot so a failed spot
        # launch doesn't fall back to a pricier on-demand node
        ct_req = replacement.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
        if ct_req.has(wk.CAPACITY_TYPE_SPOT) and ct_req.has(wk.CAPACITY_TYPE_ON_DEMAND):
            from ...scheduling.requirements import Requirement

            replacement.requirements.add(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_SPOT]))

        return Command(reason=self.reason, candidates=list(candidates), replacements=[replacement], results=results)

    def _spot_to_spot(self, candidates, results, candidate_price) -> Command:
        """Spot-to-spot consolidation (consolidation.go:261-343): gated on the
        feature flag; single-node requires >= 15 cheaper types and the current
        instance NOT among the 15 cheapest to avoid churn."""
        ctx = self.ctx
        if not ctx.options.feature_gates.spot_to_spot_consolidation:
            return Command()
        replacement = results.new_node_claims[0]
        kept = _filter_by_price(replacement, candidate_price)
        if not kept:
            return Command()
        if len(candidates) == 1:
            if len(kept) < 15:
                return Command()
            cheapest_names = {it.name for it in kept[:15]}
            if candidates[0].instance_type is not None and candidates[0].instance_type.name in cheapest_names:
                return Command()
            kept = kept[:15]
        replacement.instance_type_options = kept
        return Command(reason=self.reason, candidates=list(candidates), replacements=[replacement], results=results)

    def _passes_balanced(self, command: Command) -> bool:
        """Balanced policy gate (balanced.go:131-182): every Balanced pool the
        move touches must clear the 1/k score threshold against the per-pool
        totals the controller computed for this round."""
        if not any(c.node_pool.spec.disruption.consolidation_policy == BALANCED for c in command.candidates):
            return True
        from .balanced import evaluate_balanced_move

        return evaluate_balanced_move(command, _replacement_price(command), self.ctx.balanced_totals())

    def _can_pass_threshold(self, candidate) -> bool:
        """Best-case pre-filter (balanced.go:285-299 CanPassThreshold): a full
        DELETE is the upper bound on any move's balanced score — if even that
        fails the 1/k threshold, skip the expensive simulation entirely.
        Non-Balanced pools always pass."""
        if candidate.node_pool.spec.disruption.consolidation_policy != BALANCED:
            return True
        from .balanced import score_move

        totals = self.ctx.balanced_totals().get(candidate.node_pool.metadata.name)
        if totals is None or totals.total_cost <= 0:
            return True
        return score_move(candidate.price, candidate.reschedule_disruption_cost, totals).approved()

    def _count_timeout(self) -> None:
        if self.ctx.metrics is not None:
            from ... import metrics as m

            self.ctx.metrics.counter(m.DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL).inc(
                consolidation_type=self.consolidation_type
            )


class SingleNodeConsolidation(_ConsolidationBase):
    """Try candidates one at a time under a 3-minute budget, interweaving
    candidates across NodePools so one big pool cannot starve the rest; pools
    unseen when a round times out go first next round
    (singlenodeconsolidation.go:33-176)."""

    consolidation_type = "single"

    def __init__(self, ctx):
        super().__init__(ctx)
        # cross-round fairness carry-over (PreviouslyUnseenNodePools)
        self.previously_unseen_node_pools: set[str] = set()

    def sort_candidates(self, eligible: list) -> list:
        """The shared SavingsRatio sort, then round-robin interweave by
        NodePool with previously-unseen pools first
        (singlenodeconsolidation.go:141-176 SortCandidates calls the shared
        sortCandidates before shuffleCandidates)."""
        eligible = super().sort_candidates(eligible)
        by_pool: dict[str, list] = {}
        for c in eligible:
            by_pool.setdefault(c.node_pool.metadata.name, []).append(c)
        pool_order = sorted(p for p in self.previously_unseen_node_pools if p in by_pool)
        pool_order += [p for p in by_pool if p not in self.previously_unseen_node_pools]
        out = []
        width = max((len(cs) for cs in by_pool.values()), default=0)
        for i in range(width):
            for pool in pool_order:
                cs = by_pool[pool]
                if i < len(cs):
                    out.append(cs[i])
        return out

    def compute_commands(self, candidates, budgets) -> list[Command]:
        from .validation import ValidationError, Validator

        import time as _time

        eligible = self.sort_candidates([c for c in candidates if self.should_disrupt(c)])
        deadline = self.ctx.clock.now() + SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        # the reference's 3-minute budget is measured on a REAL clock; the
        # injected deterministic clock doesn't advance during compute, so the
        # wall bound must also apply or a large fleet makes one round unbounded
        wall_deadline = _time.monotonic() + SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        unseen = {c.node_pool.metadata.name for c in eligible}
        allowed = dict(budgets)
        for c in eligible:
            if self.ctx.clock.now() > deadline or _time.monotonic() > wall_deadline:
                # abandon the round; pools not yet reached get priority next
                # time (singlenodeconsolidation.go:61-74)
                self._count_timeout()
                self.previously_unseen_node_pools = unseen
                return []
            pool = c.node_pool.metadata.name
            unseen.discard(pool)
            if allowed.get(pool, 0) <= 0:
                continue
            # skip candidates that can't clear the balanced threshold even as
            # a pure delete (singlenodeconsolidation.go:88-90)
            if not self._can_pass_threshold(c):
                continue
            cmd = self.compute_consolidation([c])
            if cmd.candidates and self._passes_balanced(cmd):
                # 15s wait + re-simulation before execution
                # (singlenodeconsolidation.go:105, validation.go:192-263)
                # the reference persists unseen pools only on timeout and on a
                # full pass; a command or validation failure leaves the prior
                # set untouched (singlenodeconsolidation.go:61-74,105-115)
                try:
                    Validator(self.ctx, self, mode="strict", metrics=self.ctx.metrics).validate(cmd)
                except ValidationError:
                    return []
                return [cmd]
        self.previously_unseen_node_pools = unseen
        return []


class MultiNodeConsolidation(_ConsolidationBase):
    """Multi-node consolidation. DEFAULT (tpu backend): the relaxed-LP
    repack proposes candidate subsets on device over the FULL eligible fleet
    (`solver/consolidation.propose_subsets_lp`), each exact-validated through
    the scheduling simulation — served per-round as masked sub-encodes of one
    base encode (`solver/simulate.ConsolidationSimulator`). Escape hatches:
    `KARPENTER_CONSOLIDATE_LP=0` restores the reference's binary search over
    the cost-sorted prefix (multinodeconsolidation.go:52-191),
    `KARPENTER_CONSOLIDATE_LP=anneal` the r02 annealed subset search; the
    binary search also remains the in-band fallback whenever the device
    proposer produces no valid command.

    OPT-IN GLOBAL REPACK (`KARPENTER_SOLVER_GLOBALPACK=1`): one convex solve
    (models/globalpack via solver/consolidation.propose_subsets_global)
    co-optimizes pending-pod placement and node retirement — the round's
    pending pods enter the relaxation as unconditionally-placed class mass,
    so retirement choices see the provisioning they'd force. Defaults OFF,
    in which case this path is never entered and behavior is bit-identical
    to the two-phase default; when the global proposer yields no valid
    command the two-phase ladder below still runs unchanged."""

    consolidation_type = "multi"

    def compute_commands(self, candidates, budgets) -> list[Command]:
        import os

        eligible = self.sort_candidates([c for c in candidates if self.should_disrupt(c)])
        # budget filter up-front: take at most allowed per pool
        allowed = dict(budgets)
        filtered = []
        for c in eligible:
            pool = c.node_pool.metadata.name
            if allowed.get(pool, 0) > 0:
                filtered.append(c)
                allowed[pool] -= 1
        # the binary search pays a full simulation per probe, so it windows
        # over a 100-candidate prefix (multinodeconsolidation.go:35); the LP
        # proposer's device solve scales past the whole fleet and sees every
        # budget-eligible candidate
        filtered_bs = filtered[:MULTI_NODE_CONSOLIDATION_CANDIDATE_CAP]
        if len(filtered_bs) < 2:
            return []
        # ONE 1-minute budget covers the whole multi-node compute — the
        # device search and the binary-search fallback share it, so a slow
        # pool can't starve rounds regardless of backend
        deadline = self.ctx.clock.now() + MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        # TPU backend: device search proposes candidate sets; the winner is
        # exact-validated through the same simulation before use (stage 8).
        # The device proposers hand back LAZY ranked ladders — `producer`
        # holds the suspended continuation so a 15s-validation failure can
        # pull the next accepted proposal instead of abandoning the round.
        cmd = Command()
        producer = None
        lp_mode = os.environ.get("KARPENTER_CONSOLIDATE_LP", "1").strip().lower()
        gp_mode = os.environ.get("KARPENTER_SOLVER_GLOBALPACK", "0").strip().lower()
        try:
            if getattr(self.ctx.options, "solver_backend", "ffd") == "tpu" and lp_mode not in ("0", "false", "off"):
                if gp_mode in ("1", "true", "on"):
                    producer = self._globalpack_option_iter(filtered, deadline)
                    cmd = next(producer, Command())
                    if not (cmd.candidates and self._passes_balanced(cmd)):
                        cmd = Command()
                        producer.close()
                        producer = None
                if not cmd.candidates:
                    if lp_mode == "anneal":
                        cmd = self._annealed_option(filtered_bs, deadline)
                        if not (cmd.candidates and self._passes_balanced(cmd)):
                            cmd = Command()
                    else:
                        producer = self._lp_option_iter(filtered, deadline)
                        cmd = next(producer, Command())
                        if not (cmd.candidates and self._passes_balanced(cmd)):
                            cmd = Command()
                            producer.close()
                            producer = None
            if not cmd.candidates:
                if self.ctx.clock.now() > deadline:
                    # the device stage consumed the whole budget (and counted
                    # its timeout) — don't start the binary search, and never
                    # hand an empty command to the 15s validator
                    return []
                cmd = self._first_n_consolidation_option(filtered_bs, deadline)
                if not (cmd.candidates and self._passes_balanced(cmd)):
                    return []
            # 15s wait + re-simulation before execution
            # (multinodeconsolidation.go:103, validation.go:192-263). Every
            # emitted command passes this exact gate; when a ranked ladder is
            # live, a rejection falls back to the next accepted proposal
            # (bounded by MULTI_NODE_VALIDATION_ATTEMPTS and the deadline)
            # rather than ending the round empty-handed.
            from .validation import ValidationError, Validator

            validator = Validator(self.ctx, self, mode="strict", metrics=self.ctx.metrics)
            for _attempt in range(MULTI_NODE_VALIDATION_ATTEMPTS):
                try:
                    validator.validate(cmd)
                    return [cmd]
                except ValidationError:
                    if producer is None or self.ctx.clock.now() > deadline:
                        return []
                    cmd = next(producer, Command())
                    if not (cmd.candidates and self._passes_balanced(cmd)):
                        return []
            return []
        finally:
            if producer is not None:
                producer.close()

    def _candidate_instance_types(self, candidates) -> list:
        pools = {c.node_pool.metadata.name: c.node_pool for c in candidates}
        its = []
        for name in pools:
            its.extend(self.ctx.provisioner.cloud_provider.get_instance_types(pools[name]))
        return its

    def _lp_option(self, candidates, deadline: float) -> Command:
        """Best accepted command from the ranked LP ladder (compat surface
        over `_lp_option_iter` for callers that want exactly one proposal —
        the bench harness drives this directly)."""
        it = self._lp_option_iter(candidates, deadline)
        try:
            for cmd in it:
                return cmd
            return Command()
        finally:
            it.close()

    def _lp_option_iter(self, candidates, deadline: float):
        """The relaxed-LP repack proposer as a lazy ladder: yields every
        exactly-simulated ACCEPTED command in the proposer's ranked
        (best-first) order. The ladder is already ranked by the cheap
        masked-sim scores inside `propose_subsets_lp`, so the happy path
        pulls ONE command, hands it to the 15s exact Validator, and never
        simulates the rest; a validation failure resumes the generator to
        pull the next accepted proposal. The whole round is flight-recorded
        as one mode="consolidate" SolveTrace with per-phase spans
        (encode_candidates / lp_repack / round inside propose_subsets_lp,
        one "validate" span per exact probe — NOT around the yields, so the
        Validator's 15s wait while the generator is suspended never accrues
        into the phase split), and every proposal's simulation runs through
        the round's ConsolidationSimulator (masked sub-encodes where its
        envelope allows, from-scratch otherwise) plus its shared
        SchedulerRoundSeed for the from-scratch builds."""
        import logging

        from ... import metrics as m
        from ...obs.trace import default_recorder
        from ...solver.consolidation import LP_SOLVE_ITERATIONS, propose_subsets_lp
        from ...solver.simulate import ConsolidationSimulator

        ctx = self.ctx
        solver = ctx.provisioner.solver
        recorder = getattr(solver, "recorder", None) or default_recorder()
        trace = recorder.begin(n_pods=sum(len(c.reschedulable_pods) for c in candidates))
        trace.mode = "consolidate"
        trace.backend = "lp"
        reuse = ConsolidationSimulator(ctx.provisioner, ctx.cluster, ctx.clock, candidates)
        try:
            its = self._candidate_instance_types(candidates)
            try:
                proposals = propose_subsets_lp(candidates, its, trace=trace)
            except (ValueError, TypeError, RuntimeError) as e:
                logging.getLogger("karpenter.disruption").warning(
                    "LP consolidation repack failed, falling back: %s", e
                )
                return
            if ctx.metrics is not None and proposals:
                ctx.metrics.counter(m.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).inc(len(proposals), proposer="lp")
                ctx.metrics.counter(m.SOLVER_CONSOLIDATION_LP_ITERATIONS_TOTAL).inc(LP_SOLVE_ITERATIONS)
            trace.note(proposals=len(proposals))
            for subset in proposals:
                if ctx.clock.now() > deadline:
                    self._count_timeout()
                    return
                chosen = [candidates[i] for i in subset]
                with trace.span("validate"):
                    cmd = self.compute_consolidation(chosen, reuse=reuse)
                    accepted = bool(cmd.candidates) and not self._is_pointless_churn(cmd)
                if ctx.metrics is not None:
                    ctx.metrics.counter(m.SOLVER_CONSOLIDATION_VALIDATION_TOTAL).inc(
                        decision="accept" if accepted else "reject"
                    )
                if accepted:
                    if ctx.metrics is not None:
                        ctx.metrics.gauge(m.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).set(
                            _command_savings_per_hour(cmd), proposer="lp"
                        )
                    trace.note(accepted_subset=len(subset))
                    yield cmd
        finally:
            trace.note(
                sim_masked=reuse.masked_probes,
                sim_scratch=reuse.scratch_probes,
                sim_why_scratch=reuse.why_scratch,
                sched_seed_rejects=len(reuse.sched_seed.static_rejects) if reuse.sched_seed is not None else 0,
            )
            recorder.commit(trace, registry=ctx.metrics)

    def _globalpack_option(self, candidates, deadline: float) -> Command:
        """Best accepted command from the global-repack ladder (compat
        surface over `_globalpack_option_iter`, mirrors `_lp_option`)."""
        it = self._globalpack_option_iter(candidates, deadline)
        try:
            for cmd in it:
                return cmd
            return Command()
        finally:
            it.close()

    def _globalpack_option_iter(self, candidates, deadline: float):
        """The opt-in GLOBAL repack proposer (KARPENTER_SOLVER_GLOBALPACK=1)
        as the same lazy accepted-command ladder as `_lp_option_iter`: one
        convex solve over pending placement + retirement, then exact
        simulation per pulled proposal only — the round's
        ConsolidationSimulator already carries the pending pods in every
        probe, so a yielded command is exact for BOTH sides of the joint
        objective. Publishes the bounded karpenter_solver_globalpack_*
        family and rides the proposer="globalpack" enum value."""
        import logging

        from ... import metrics as m
        from ...obs.trace import default_recorder
        from ...solver.consolidation import LP_SOLVE_ITERATIONS, propose_subsets_global
        from ...solver.simulate import ConsolidationSimulator

        ctx = self.ctx
        solver = ctx.provisioner.solver
        recorder = getattr(solver, "recorder", None) or default_recorder()
        trace = recorder.begin(n_pods=sum(len(c.reschedulable_pods) for c in candidates))
        trace.mode = "consolidate"
        trace.backend = "globalpack"
        reuse = ConsolidationSimulator(ctx.provisioner, ctx.cluster, ctx.clock, candidates)
        try:
            its = self._candidate_instance_types(candidates)
            pending = ctx.provisioner.get_pending_pods()
            try:
                proposals, info = propose_subsets_global(candidates, its, pending_pods=pending, trace=trace)
            except (ValueError, TypeError, RuntimeError) as e:
                logging.getLogger("karpenter.disruption").warning(
                    "global repack failed, falling back to two-phase: %s", e
                )
                return
            if ctx.metrics is not None:
                ctx.metrics.counter(m.SOLVER_GLOBALPACK_ROUNDS_TOTAL).inc()
                ctx.metrics.counter(m.SOLVER_GLOBALPACK_ITERATIONS_TOTAL).inc(LP_SOLVE_ITERATIONS)
                ctx.metrics.gauge(m.SOLVER_GLOBALPACK_OBJECTIVE_IMPROVEMENT).set(info["objective_improvement"])
                if proposals:
                    ctx.metrics.counter(m.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).inc(
                        len(proposals), proposer="globalpack"
                    )
            trace.note(proposals=len(proposals))
            for subset in proposals:
                if ctx.clock.now() > deadline:
                    self._count_timeout()
                    return
                chosen = [candidates[i] for i in subset]
                with trace.span("validate"):
                    cmd = self.compute_consolidation(chosen, reuse=reuse)
                    accepted = bool(cmd.candidates) and not self._is_pointless_churn(cmd)
                if ctx.metrics is not None:
                    ctx.metrics.counter(m.SOLVER_CONSOLIDATION_VALIDATION_TOTAL).inc(
                        decision="accept" if accepted else "reject"
                    )
                if accepted:
                    if ctx.metrics is not None:
                        ctx.metrics.gauge(m.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).set(
                            _command_savings_per_hour(cmd), proposer="globalpack"
                        )
                    trace.note(accepted_subset=len(subset))
                    yield cmd
        finally:
            trace.note(
                sim_masked=reuse.masked_probes,
                sim_scratch=reuse.scratch_probes,
                sim_why_scratch=reuse.why_scratch,
                sched_seed_rejects=len(reuse.sched_seed.static_rejects) if reuse.sched_seed is not None else 0,
            )
            recorder.commit(trace, registry=ctx.metrics)

    def _annealed_option(self, candidates, deadline: float) -> Command:
        """The r02 annealed subset search + host exact validation
        (KARPENTER_CONSOLIDATE_LP=anneal comparison arm), under the shared
        1-minute compute budget."""
        import logging

        from ... import metrics as m
        from ...solver.consolidation import propose_subsets

        its = self._candidate_instance_types(candidates)
        try:
            proposals = propose_subsets(candidates, its)
        except (ValueError, TypeError, RuntimeError) as e:
            logging.getLogger("karpenter.disruption").warning("annealed consolidation search failed, falling back: %s", e)
            return Command()
        if self.ctx.metrics is not None and proposals:
            self.ctx.metrics.counter(m.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).inc(len(proposals), proposer="anneal")
        for subset in proposals:
            if self.ctx.clock.now() > deadline:
                self._count_timeout()
                return Command()
            chosen = [candidates[i] for i in subset]
            cmd = self.compute_consolidation(chosen)
            if cmd.candidates:
                if self._is_pointless_churn(cmd):
                    continue
                if self.ctx.metrics is not None:
                    self.ctx.metrics.gauge(m.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).set(
                        _command_savings_per_hour(cmd), proposer="anneal"
                    )
                return cmd
        return Command()

    @staticmethod
    def _is_pointless_churn(cmd: Command) -> bool:
        """Replacing with a node priced equal to one being removed is churn
        (multinodeconsolidation.go:150-170)."""
        if not cmd.replacements:
            return False
        rep = _replacement_price(cmd)
        return any(abs(c.price - rep) < 1e-9 for c in cmd.candidates)

    def _first_n_consolidation_option(self, candidates, deadline: float | None = None) -> Command:
        """firstNConsolidationOption (multinodeconsolidation.go:117-191): binary
        search on batch size under a 1-minute budget — on timeout return the
        last valid command found (or nothing)."""
        from ... import metrics as m

        min_n, max_n = 1, len(candidates)
        last_valid = Command()
        if deadline is None:
            deadline = self.ctx.clock.now() + MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        while min_n <= max_n:
            if self.ctx.clock.now() > deadline:
                self._count_timeout()
                return last_valid
            mid = (min_n + max_n) // 2
            if self.ctx.metrics is not None:
                self.ctx.metrics.counter(m.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).inc(proposer="binary-search")
            cmd = self.compute_consolidation(candidates[: mid + 1])
            if not cmd.candidates:
                max_n = mid - 1
                continue
            if self._is_pointless_churn(cmd):
                max_n = mid - 1
                continue
            last_valid = cmd
            min_n = mid + 1
        if last_valid.candidates and self.ctx.metrics is not None:
            self.ctx.metrics.gauge(m.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).set(
                _command_savings_per_hour(last_valid), proposer="binary-search"
            )
        return last_valid


def _filter_by_price(replacement, max_price: float):
    """Instance types strictly cheaper than max_price, preserving minValues
    satisfiability; returns [] when impossible."""
    from ...cloudprovider.types import satisfies_min_values

    kept = []
    for it in replacement.instance_type_options:
        compat = [
            o
            for o in it.offerings
            if o.available and replacement.requirements.intersects(o.requirements) is None
        ]
        if compat and min(o.price for o in compat) < max_price:
            kept.append(it)
    if kept and replacement.requirements.has_min_values():
        _, unsat = satisfies_min_values(kept, replacement.requirements)
        if unsat:
            return []
    return kept


def _command_savings_per_hour(command: Command) -> float:
    """Hourly price removed minus the replacement's cheapest launch price —
    the `karpenter_solver_consolidation_savings_per_hour` gauge value."""
    if not command.candidates:
        return 0.0
    removed = sum(c.price for c in command.candidates)
    return removed - (_replacement_price(command) if command.replacements else 0.0)


def _replacement_price(command: Command) -> float:
    total = 0.0
    for nc in command.replacements:
        best = float("inf")
        for it in nc.instance_type_options:
            for o in it.offerings:
                if o.available and nc.requirements.intersects(o.requirements) is None and o.price < best:
                    best = o.price
        if best < float("inf"):
            total += best
    return total
