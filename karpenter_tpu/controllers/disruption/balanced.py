"""Balanced consolidation scoring.

Reference: disruption/balanced.go:32-185 — a move is approved when, for every
Balanced pool it touches, (savings / pool_total_cost) divided by
(disruption_cost / pool_total_disruption_cost) meets the 1/k threshold.
Totals come from ClusterCost (precomputed) when available; disruption totals
sum over ALL nodes in the pool, not just candidates.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...apis import labels as wk
from ...apis.nodepool import BALANCED, BALANCED_K


@dataclass
class NodePoolTotals:
    total_cost: float = 0.0
    total_disruption_cost: float = 0.0


@dataclass
class ScoreResult:
    """balanced.go / types.go:93-111 — score = savings%/disruption%."""

    savings_fraction: float = 0.0
    disruption_fraction: float = 0.0
    k: int = BALANCED_K

    def score(self) -> float:
        if self.savings_fraction <= 0:
            return 0.0
        if self.disruption_fraction == 0:
            return float("inf")
        return self.savings_fraction / self.disruption_fraction

    def threshold(self) -> float:
        return 1.0 / self.k

    def approved(self) -> bool:
        return self.score() >= self.threshold()


def score_move(savings: float, disruption_cost: float, totals: NodePoolTotals, k: int = BALANCED_K) -> ScoreResult:
    """ScoreMove (balanced.go:106-124). Zero totals → nothing to normalise
    against → not approved."""
    if totals.total_cost <= 0 or totals.total_disruption_cost <= 0:
        return ScoreResult(k=k)
    return ScoreResult(
        savings_fraction=savings / totals.total_cost,
        disruption_fraction=disruption_cost / totals.total_disruption_cost,
        k=k,
    )


def compute_node_pool_totals(all_candidates, all_nodes, cluster_cost) -> dict[str, NodePoolTotals]:
    """computeNodePoolTotals (balanced.go:47-101): cost from ClusterCost with
    candidate-price fallback; disruption from every node in the pool — the
    accurate reschedule cost for candidates, the incrementally-maintained
    StateNode cost (plus the 1.0 per-node base) for the rest."""
    candidate_by_name = {c.name(): c for c in all_candidates}
    totals: dict[str, NodePoolTotals] = {}
    for c in all_candidates:
        t = totals.setdefault(c.node_pool.metadata.name, NodePoolTotals())
        t.total_cost += c.price  # fallback; replaced below when ClusterCost knows better
    for n in all_nodes:
        pool = n.labels().get(wk.NODEPOOL_LABEL_KEY)
        if pool is None or pool not in totals:
            continue
        c = candidate_by_name.get(n.name())
        if c is not None:
            totals[pool].total_disruption_cost += c.reschedule_disruption_cost
        else:
            totals[pool].total_disruption_cost += n.disruption_cost()
    if cluster_cost is not None:
        for pool, t in totals.items():
            cc = cluster_cost.get_nodepool_cost(pool)
            if cc > 0:
                t.total_cost = cc
    return totals


def evaluate_balanced_move(command, replacement_price: float, node_pool_totals: dict[str, NodePoolTotals]) -> bool:
    """EvaluateBalancedMove (balanced.go:131-182): each Balanced pool scores
    independently; approval requires every Balanced pool to approve.
    Cross-pool savings are attributed proportionally to source cost."""
    if not command.candidates:
        return False
    by_pool: dict[str, list] = {}
    for c in command.candidates:
        by_pool.setdefault(c.node_pool.metadata.name, []).append(c)
    source_cost = sum(c.price for c in command.candidates)
    savings = source_cost - replacement_price
    for pool, pool_candidates in by_pool.items():
        node_pool = pool_candidates[0].node_pool
        if node_pool.spec.disruption.consolidation_policy != BALANCED:
            continue
        disruption_cost = sum(c.reschedule_disruption_cost for c in pool_candidates)
        pool_savings = savings
        if source_cost > 0 and len(by_pool) > 1:
            pool_savings = savings * (sum(c.price for c in pool_candidates) / source_cost)
        result = score_move(pool_savings, disruption_cost, node_pool_totals.get(pool, NodePoolTotals()))
        if not result.approved():
            return False
    return True
