"""Disruption helpers: scheduling simulation and budget mapping
(reference: disruption/helpers.go:53-313)."""

from __future__ import annotations

from ...utils import pods as pod_utils
from .types import REASON_DRIFTED, REASON_EMPTY, REASON_UNDERUTILIZED


def simulate_scheduling(provisioner, cluster, candidates: list, clock, reuse=None, sched_seed=None):
    """Clone state minus the candidates, add their reschedulable pods to the
    pending set, and Solve (helpers.go:53-154). The Solver plugin (FFD or TPU)
    is reused for free — the simulation IS a solve on a modified snapshot.

    `reuse` (a solver.simulate.ConsolidationSimulator) serves the probe as a
    masked sub-encode of its round-base encode when the batch sits inside the
    simulator's correctness envelope — placement-identical, at a fraction of
    the per-probe host cost — and falls back to this from-scratch path
    otherwise. `sched_seed` (a scheduling.SchedulerRoundSeed) rides the probe
    snapshot so a from-scratch host build within the round reuses the
    probe-invariant fit-memo/PodData layers. The 15s command Validator never
    passes either: executed commands always re-validate against a fully
    independent from-scratch simulation."""
    if reuse is not None:
        return reuse.simulate(candidates)
    candidate_names = {c.name() for c in candidates}
    all_nodes = cluster.nodes_view()
    state_nodes = [
        n
        for n in all_nodes
        if n.name() not in candidate_names and not n.marked_for_deletion and not n.deleted()
    ]
    pending = provisioner.get_pending_pods()
    deleting_pods = []
    for n in all_nodes:
        if (n.marked_for_deletion or n.deleted()) and n.name() not in candidate_names:
            for key in n.pod_requests:
                ns, name = key.split("/", 1)
                pod = provisioner.store.try_get("Pod", name, ns)
                if pod is not None and pod_utils.is_reschedulable(pod):
                    deleting_pods.append(pod)
    reschedulable = [p for c in candidates for p in c.reschedulable_pods]
    pods = pending + deleting_pods + reschedulable
    snapshot = provisioner.make_snapshot(pods, state_nodes=state_nodes)
    snapshot.enforce_consolidate_after = True
    snapshot.deleting_node_names = candidate_names
    # consolidation must not fall back into reserved capacity it failed to
    # reserve (consolidation.go:45 DisableReservedCapacityFallback)
    snapshot.reserved_offering_mode = "strict"
    snapshot.collect_zone_metrics = False
    if sched_seed is not None:
        snapshot.sched_seed = sched_seed
    results = provisioner.solver.solve(snapshot)
    # prune claims that ended up empty
    results.new_node_claims = [nc for nc in results.new_node_claims if nc.pods]
    return results


def all_non_pending_scheduled(results, candidates) -> bool:
    """Every candidate pod must have found a home; pods that were already
    pending before the simulation don't block (helpers.go AllNonPendingPodsScheduled)."""
    candidate_pod_keys = {p.key() for c in candidates for p in c.reschedulable_pods}
    return not any(k in candidate_pod_keys for k in results.pod_errors)


def build_disruption_budget_mapping(store, cluster, clock, reason: str) -> dict[str, int]:
    """Per-pool allowed disruptions minus nodes already disrupting
    (helpers.go:262-313)."""
    mapping: dict[str, int] = {}
    deleting: dict[str, int] = {}
    counts: dict[str, int] = {}
    for n in cluster.nodes_view():
        pool = n.nodepool_name()
        if pool is None:
            continue
        counts[pool] = counts.get(pool, 0) + 1
        if n.marked_for_deletion or n.deleted():
            deleting[pool] = deleting.get(pool, 0) + 1
    for np in store.list("NodePool"):
        name = np.metadata.name
        allowed = np.allowed_disruptions(clock.now(), counts.get(name, 0), reason)
        mapping[name] = max(0, allowed - deleting.get(name, 0))
    return mapping
