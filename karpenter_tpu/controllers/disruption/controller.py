"""Disruption controller: the 10s-poll loop running methods in priority order
(reference: disruption/controller.go:101-183).
"""

from __future__ import annotations

from dataclasses import dataclass

from ...apis import labels as wk
from ...utils.pdb import PDBLimits
from .helpers import build_disruption_budget_mapping
from .methods import Drift, Emptiness, MultiNodeConsolidation, SingleNodeConsolidation, StaticDrift
from .queue import OrchestrationQueue
from .types import build_candidate

POLL_SECONDS = 10.0


@dataclass
class _Ctx:
    store: object
    cluster: object
    provisioner: object
    clock: object
    options: object
    cluster_cost: object = None
    # per-round candidate set + lazily-memoized balanced-scoring totals
    # (balanced.go computeNodePoolTotals); only consolidation methods touching
    # a Balanced pool ever pay for the totals pass
    round_candidates: list | None = None
    node_pool_totals: dict | None = None
    # live candidate rebuild for the 15s command validator (validation.go)
    get_candidates: object = None
    metrics: object = None

    def balanced_totals(self) -> dict:
        if self.node_pool_totals is None:
            from .balanced import compute_node_pool_totals

            self.node_pool_totals = compute_node_pool_totals(
                self.round_candidates or [], self.cluster.nodes(), self.cluster_cost
            )
        return self.node_pool_totals


class DisruptionController:
    def __init__(self, store, cluster, provisioner, cloud_provider, clock, options, recorder=None, metrics=None, cluster_cost=None):
        self.store = store
        self.cluster = cluster
        self.provisioner = provisioner
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.options = options
        self.cluster_cost = cluster_cost
        ctx = _Ctx(store, cluster, provisioner, clock, options, cluster_cost=cluster_cost, metrics=metrics)
        ctx.get_candidates = self.get_candidates
        self.ctx = ctx
        self.methods = [
            Emptiness(ctx),
            StaticDrift(ctx),
            Drift(ctx),
            MultiNodeConsolidation(ctx),
            SingleNodeConsolidation(ctx),
        ]
        self.queue = OrchestrationQueue(store, cluster, provisioner, clock, recorder)
        self.metrics = metrics
        self._last_run = -1e18

    def reconcile(self, force: bool = False) -> None:
        self.queue.reconcile()
        now = self.clock.now()
        if not force and now - self._last_run < POLL_SECONDS:
            return
        self._last_run = now
        if not self.cluster.synced():
            return
        if self.cluster.consolidated():
            return
        self._cleanup_leftover_taints()
        executed, budget_blocked = self.disrupt()
        if not executed and not budget_blocked:
            # a round that found nothing AND was not budget-limited marks the
            # cluster consolidated; budget-blocked candidates must keep the
            # poll alive — cron budget windows open without any object edit
            # (consolidation_test.go:714-934 "should not mark ... consolidated
            # if the candidates can't be disrupted due to budgets")
            self.cluster.mark_consolidated()

    def disrupt(self) -> tuple[bool, bool]:
        """Run methods in priority order; execute the first command batch
        (controller.go:166-179). Returns (executed, budget_blocked) where
        budget_blocked means a pool with candidates a method would disrupt
        had its budget exhausted this round."""
        import time as _time

        budget_blocked = False
        for method in self.methods:
            ctype = getattr(method, "consolidation_type", "")
            mname = type(method).__name__
            candidates = self.get_candidates()
            if self.metrics is not None:
                from ... import metrics as m

                self.metrics.gauge(m.DISRUPTION_ELIGIBLE_NODES).set(len(candidates), method=mname, consolidation_type=ctype)
            if not candidates:
                return False, budget_blocked
            self.ctx.round_candidates = candidates
            self.ctx.node_pool_totals = None
            budgets = build_disruption_budget_mapping(self.store, self.cluster, self.clock, method.reason)
            # budget-blocked only counts pools whose candidates THIS method
            # would actually disrupt (the reference ties the signal to the
            # method's own filtered set) — a reason-scoped zero budget for a
            # method with nothing to do must not suppress consolidated
            # pacing; the should_disrupt sweep runs only when some budget is
            # actually at zero (rare), never on the common all-positive path
            if not budget_blocked and any(v <= 0 for v in budgets.values()):
                zero_pools = {pool for pool, v in budgets.items() if v <= 0}
                if any(
                    c.node_pool is not None
                    and c.node_pool.metadata.name in zero_pools
                    and method.should_disrupt(c)
                    for c in candidates
                ):
                    budget_blocked = True
            t0 = _time.perf_counter()
            commands = method.compute_commands(candidates, budgets)
            started = False
            for cmd in commands:
                if cmd.candidates and self.queue.start_command(cmd):
                    started = True
            if self.metrics is not None:
                from ... import metrics as m

                self.metrics.histogram(m.DISRUPTION_DECISION_EVAL_DURATION).observe(_time.perf_counter() - t0, method=mname)
                for cmd in commands:
                    if cmd.candidates:
                        decision = "replace" if cmd.replacements else "delete"
                        self.metrics.counter(m.DISRUPTION_DECISIONS_TOTAL).inc(
                            decision=decision, method=mname, consolidation_type=ctype
                        )
            if started:
                return True, budget_blocked
        return False, budget_blocked

    def get_candidates(self) -> list:
        node_pools = {np.metadata.name: np for np in self.store.list("NodePool")}
        instance_types = {
            name: self.cloud_provider.get_instance_types(np) for name, np in node_pools.items()
        }
        pdb = PDBLimits(self.store)
        disrupting = self.queue.disrupting_names()
        out = []
        for sn in self.cluster.nodes_view():
            if sn.name() in disrupting:
                continue
            candidate, err = build_candidate(
                self.cluster, self.store, self.clock, sn, node_pools, instance_types, pdb
            )
            if candidate is not None:
                out.append(candidate)
        return out

    def _cleanup_leftover_taints(self) -> None:
        """Idempotently clear disruption taints on nodes that are not part of
        an in-flight command (controller.go:147-164)."""
        active = self.queue.disrupting_names()
        for node in self.store.list("Node"):
            if node.metadata.name in active or node.metadata.deletion_timestamp is not None:
                continue
            sn = self.cluster.node_for_name(node.metadata.name)
            if sn is not None and (sn.marked_for_deletion or sn.deleted()):
                continue  # mid-teardown nodes keep their taint (controller.go:151)
            if any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints):
                def untaint(n):
                    n.spec.taints = [t for t in n.spec.taints if t.key != wk.DISRUPTED_TAINT_KEY]

                self.store.patch("Node", node.metadata.name, untaint)
                sn = self.cluster.node_for_name(node.metadata.name)
                if sn is not None:
                    self.cluster.unmark_for_deletion([sn.provider_id()])
