"""Disruption candidates and commands (reference: disruption/types.go:75-283)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ...apis import labels as wk
from ...utils import disruption as disruption_utils
from ...utils import pods as pod_utils

REASON_UNDERUTILIZED = "Underutilized"
REASON_EMPTY = "Empty"
REASON_DRIFTED = "Drifted"


@dataclass
class Candidate:
    """A node eligible for disruption (types.go:75-211)."""

    state_node: object
    node_claim: object
    node_pool: object
    instance_type: Optional[object]
    capacity_type: str
    zone: str
    price: float
    reschedulable_pods: list
    disruption_cost: float
    # 1.0 base + sum of positive pod eviction costs; the numerator/denominator
    # unit of balanced scoring (types.go:85-89 RescheduleDisruptionCost)
    reschedule_disruption_cost: float = 1.0

    def savings_ratio(self) -> float:
        """Cost per unit disruption; higher = prefer to disrupt
        (types.go:144-145)."""
        return self.price / self.reschedule_disruption_cost

    def name(self) -> str:
        return self.state_node.name()

    def owned_by_static_node_pool(self) -> bool:
        """Static fleets are replaced 1:1 by StaticDrift, never consolidated
        (types.go:147)."""
        return self.node_pool is not None and self.node_pool.is_static()


@dataclass
class Command:
    """A validated disruption decision (types.go:227-283)."""

    reason: str = ""
    candidates: list = field(default_factory=list)
    replacements: list = field(default_factory=list)  # SchedulingNodeClaims
    results: object = None

    def decision(self) -> str:
        if not self.candidates:
            return "no-op"
        return "replace" if self.replacements else "delete"

    def candidate_names(self) -> list[str]:
        return [c.name() for c in self.candidates]


def build_candidate(cluster, store, clock, state_node, node_pools_by_name, instance_types_by_pool, pdb_limits, recorder=None) -> tuple[Optional[Candidate], str | None]:
    """Candidate construction with all the disqualification gates
    (types.go:160-211 NewCandidate)."""
    err = state_node.validate_node_disruptable(clock.now())
    if err is not None:
        return None, err
    pool_name = state_node.nodepool_name()
    node_pool = node_pools_by_name.get(pool_name)
    if node_pool is None:
        return None, f"nodepool {pool_name} not found"

    labels = state_node.labels()
    it_name = labels.get(wk.INSTANCE_TYPE_LABEL_KEY, "")
    instance_type = next((it for it in instance_types_by_pool.get(pool_name, []) if it.name == it_name), None)
    capacity_type = labels.get(wk.CAPACITY_TYPE_LABEL_KEY, "")
    zone = labels.get(wk.ZONE_LABEL_KEY, "")
    price = 0.0
    if instance_type is not None:
        p = instance_type.offering_price(zone, capacity_type)
        price = p if p is not None else 0.0

    # the candidate's pod set is every pod still tracked on the node —
    # terminating pods included (types.go:188-199 + statenode.go:244-264
    # ValidatePodsDisruptable reads the live bindings); is_reschedulable
    # below decides which of them reserve replacement capacity
    pods = []
    for key in state_node.pod_requests:
        ns, name = key.split("/", 1)
        pod = store.try_get("Pod", name, ns)
        if pod is not None and not pod_utils.is_terminal(pod):
            pods.append(pod)

    # pods that block disruption; do-not-disrupt only blocks for ACTIVE pods
    # (scheduling.go:115-117 IsDisruptable: a terminating pod cannot hold its
    # node hostage)
    for pod in pods:
        if not pod_utils.is_active(pod):
            continue
        if pod_utils.has_do_not_disrupt(pod, clock.now()) and node_pool.spec.template.termination_grace_period is None:
            return None, f"pod {pod.key()} has do-not-disrupt"
        ok, pdb = pdb_limits.can_evict(pod)
        if not ok and node_pool.spec.template.termination_grace_period is None:
            return None, f"pdb {pdb} prevents pod eviction"

    reschedulable = [p for p in pods if pod_utils.is_reschedulable(p)]
    cost = disruption_utils.rescheduling_cost(reschedulable) * disruption_utils.lifetime_remaining(
        clock.now(),
        state_node.node_claim.spec.expire_after if state_node.node_claim else None,
        state_node.node_claim.metadata.creation_timestamp if state_node.node_claim else clock.now(),
    )
    return (
        Candidate(
            state_node=state_node,
            node_claim=state_node.node_claim,
            node_pool=node_pool,
            instance_type=instance_type,
            capacity_type=capacity_type,
            zone=zone,
            price=price,
            reschedulable_pods=reschedulable,
            disruption_cost=cost,
            reschedule_disruption_cost=1.0
            + sum(max(0.0, disruption_utils.eviction_cost(p)) for p in reschedulable),
        ),
        None,
    )
