"""Disruption orchestration queue (reference: disruption/queue.go:313-391):
taint candidates, mark claims Disrupted, create replacement NodeClaims, and
delete the candidates only when every replacement is Initialized.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...apis import labels as wk
from ...apis.nodeclaim import COND_DISRUPTION_REASON
from ...scheduling.taints import NO_SCHEDULE, Taint
from .types import Command

DISRUPTED_TAINT = Taint(key=wk.DISRUPTED_TAINT_KEY, effect=NO_SCHEDULE)


@dataclass
class _Item:
    command: Command
    replacement_names: list[str] = field(default_factory=list)


class OrchestrationQueue:
    def __init__(self, store, cluster, provisioner, clock, recorder=None):
        self.store = store
        self.cluster = cluster
        self.provisioner = provisioner
        self.clock = clock
        self.recorder = recorder
        self._items: list[_Item] = []

    def disrupting_names(self) -> set[str]:
        return {name for item in self._items for name in item.command.candidate_names()}

    def start_command(self, command: Command) -> bool:
        """Taint + mark + create replacements (queue.go StartCommand)."""
        # taint all candidates NoSchedule and mark for deletion in state
        for c in command.candidates:
            node_name = c.name()

            def taint(n):
                if not any(t.key == wk.DISRUPTED_TAINT_KEY for t in n.spec.taints):
                    n.spec.taints.append(DISRUPTED_TAINT)

            node = self.store.try_get("Node", node_name)
            if node is None:
                return False
            self.store.patch("Node", node_name, taint)
            if c.node_claim is not None:
                def mark(nc):
                    nc.status.conditions.set_true(COND_DISRUPTION_REASON, reason=command.reason, now=self.clock.now())

                try:
                    self.store.patch("NodeClaim", c.node_claim.metadata.name, mark)
                except Exception as e:
                    # losing the DisruptionReason condition is benign (the
                    # claim may have been deleted out from under the command)
                    # but never silent: the event stream records it
                    if self.recorder is not None:
                        self.recorder.publish(c.node_claim, "DisruptionQueueError", f"marking Disrupted failed: {e}", type_="Warning")
        self.cluster.mark_for_deletion([c.state_node.provider_id() for c in command.candidates])

        item = _Item(command=command)
        for replacement in command.replacements:
            name = self.provisioner.create_node_claim(replacement, reason=command.reason or "provisioning")
            if name is None:
                self._rollback(command, created=item.replacement_names)
                return False
            item.replacement_names.append(name)
        self._items.append(item)
        return True

    def reconcile(self) -> None:
        """Advance in-flight commands; delete candidates once replacements are
        Initialized (queue.go:186-256)."""
        remaining = []
        for item in self._items:
            ready = True
            for name in item.replacement_names:
                nc = self.store.try_get("NodeClaim", name)
                if nc is None:
                    # replacement failed/was GC'd: roll the command back,
                    # removing the other replacements too
                    self._rollback(item.command, created=[n for n in item.replacement_names if n != name])
                    ready = None
                    break
                if not nc.is_initialized():
                    ready = False
            if ready is None:
                continue
            if not ready:
                remaining.append(item)
                continue
            for c in item.command.candidates:
                if c.node_claim is not None:
                    self.store.try_delete("NodeClaim", c.node_claim.metadata.name)
                else:
                    self.store.try_delete("Node", c.name())
        self._items = remaining

    def _rollback(self, command: Command, created: list[str] | None = None) -> None:
        """Undo a failed command: untaint + unmark candidates, clear their
        DisruptionReason condition, and delete any replacements already
        created (controller.go:159 ClearNodeClaimsCondition)."""
        for c in command.candidates:
            def untaint(n):
                n.spec.taints = [t for t in n.spec.taints if t.key != wk.DISRUPTED_TAINT_KEY]

            node = self.store.try_get("Node", c.name())
            if node is not None:
                self.store.patch("Node", c.name(), untaint)
            if c.node_claim is not None:
                def clear(nc):
                    nc.status.conditions.clear(COND_DISRUPTION_REASON)

                try:
                    self.store.patch("NodeClaim", c.node_claim.metadata.name, clear)
                except Exception as e:
                    # same contract as start_command's mark: benign (claim
                    # may be concurrently deleted), but recorded, not silent
                    if self.recorder is not None:
                        self.recorder.publish(c.node_claim, "DisruptionQueueError", f"clearing DisruptionReason failed: {e}", type_="Warning")
        self.cluster.unmark_for_deletion([c.state_node.provider_id() for c in command.candidates])
        for name in created or []:
            self.store.try_delete("NodeClaim", name)
