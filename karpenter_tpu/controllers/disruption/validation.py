"""Disruption command validation: wait, rebuild, re-simulate, re-check.

Reference: disruption/validation.go:116-355 + consolidation.go:45
(commandValidationDelay = 15s). Before any consolidation/emptiness command
executes, the validator waits out the validation window, then:

  a. rebuilds candidates from live cluster state and re-applies the method's
     filter — churn (a pod scheduled to a candidate, a condition cleared)
     invalidates it;
  b. re-checks pod nominations and disruption budgets, consuming budget per
     candidate;
  c. (consolidation only) re-runs the scheduling simulation and requires the
     same shape of result: every reschedulable pod placed, the same number of
     replacement nodes, and the command's replacement instance types a subset
     of what the fresh simulation allows (the simulation does no price
     filtering, so subset == still at-most-as-expensive);
  d. re-validates candidates once more after the simulation (reference
     mitigation for kubernetes-sigs/karpenter#1167).

The wait is `clock.sleep`: wall-clock in production, a deterministic step on
the FakeClock (tests interleave churn by subclassing sleep()).
"""

from __future__ import annotations

from .helpers import all_non_pending_scheduled, build_disruption_budget_mapping, simulate_scheduling
from .types import Command

VALIDATION_DELAY_SECONDS = 15.0  # consolidation.go:45


class ValidationError(Exception):
    """kind: churn | nominated | budget | scheduling (validation.go:358-380)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


class Validator:
    """mode="strict": every candidate must re-validate and the command is
    re-simulated (consolidation, validation.go:192-263). mode="subset": the
    command shrinks to the candidates that survive (emptiness,
    validation.go:134-148,223-252)."""

    def __init__(self, ctx, method, mode: str, metrics=None):
        self.ctx = ctx
        self.method = method
        self.mode = mode
        self.metrics = metrics

    def _count_failure(self, n: int = 1) -> None:
        if self.metrics is not None:
            from ... import metrics as m

            self.metrics.counter(m.DISRUPTION_FAILED_VALIDATIONS_TOTAL).inc(
                n, method=getattr(self.method, "consolidation_type", "") or type(self.method).__name__
            )

    def validate(self, cmd: Command, delay_seconds: float = VALIDATION_DELAY_SECONDS) -> Command:
        """Returns the validated command or raises ValidationError."""
        if not cmd.candidates:
            # a commandless validate can only ever raise — don't pay the 15s
            # wait to learn it. Same outcome (_count_failure bump + churn
            # raise) that _validate_candidates([]) produces after the sleep.
            self._count_failure(0)
            raise ValidationError("churn", "0 candidates are no longer valid")
        if delay_seconds > 0:
            self.ctx.clock.sleep(delay_seconds)
        validated = self._validate_candidates(cmd.candidates)
        if self.mode == "strict":
            self._validate_command(cmd, validated)
            # re-validate after the simulation (validation.go:215-219)
            validated = self._validate_candidates(validated)
            return cmd
        return Command(reason=cmd.reason, candidates=validated, replacements=cmd.replacements, results=cmd.results)

    def _validate_candidates(self, candidates: list) -> list:
        fresh = {c.name(): c for c in self.ctx.get_candidates() if self.method.should_disrupt(c)}
        mapped = [fresh[c.name()] for c in candidates if c.name() in fresh]
        if self.mode == "strict" and len(mapped) != len(candidates):
            self._count_failure(len(candidates))
            raise ValidationError("churn", f"{len(candidates) - len(mapped)} candidates are no longer valid")
        if not mapped:
            self._count_failure(len(candidates))
            raise ValidationError("churn", f"{len(candidates)} candidates are no longer valid")
        budgets = build_disruption_budget_mapping(self.ctx.store, self.ctx.cluster, self.ctx.clock, self.method.reason)
        now = self.ctx.clock.now()
        valid = []
        for c in mapped:
            sn = c.state_node
            if sn.nominated(now):
                if self.mode == "strict":
                    self._count_failure(len(candidates))
                    raise ValidationError("nominated", f"candidate {c.name()} was nominated during validation")
                self._count_failure()
                continue
            pool = c.node_pool.metadata.name
            if budgets.get(pool, 0) <= 0:
                if self.mode == "strict":
                    self._count_failure(len(candidates))
                    raise ValidationError("budget", f"disrupting {c.name()} would violate {pool}'s budget")
                self._count_failure()
                continue
            budgets[pool] -= 1
            valid.append(c)
        if not valid:
            self._count_failure(len(candidates))
            raise ValidationError("budget", "no candidate can be disrupted within budgets")
        return valid

    def _validate_command(self, cmd: Command, candidates: list) -> None:
        """Re-simulate against CURRENT state; the result must still justify
        the command (validation.go:297-355)."""
        if not candidates:
            raise ValidationError("churn", "no candidates")
        results = simulate_scheduling(self.ctx.provisioner, self.ctx.cluster, candidates, self.ctx.clock)
        if not all_non_pending_scheduled(results, candidates):
            self._count_failure(len(cmd.candidates))
            raise ValidationError("scheduling", results.non_pending_pod_scheduling_errors())
        n_new = len(results.new_node_claims)
        if n_new == 0:
            if not cmd.replacements:
                return  # delete-only command still needs no replacement: valid
            self._count_failure(len(cmd.candidates))
            raise ValidationError("scheduling", "simulation no longer needs a replacement node")
        if n_new > 1 or not cmd.replacements:
            self._count_failure(len(cmd.candidates))
            raise ValidationError("scheduling", "scheduling simulation produced new results")
        # the command's launchable types must be a subset of what the fresh
        # simulation allows — subset == no pricier than planned
        sim_names = {it.name for it in results.new_node_claims[0].instance_type_options}
        if not all(it.name in sim_names for it in cmd.replacements[0].instance_type_options):
            self._count_failure(len(cmd.candidates))
            raise ValidationError("scheduling", "scheduling simulation produced new results")
