"""NodeClaim consistency controller.

Reference: pkg/controllers/nodeclaim/consistency/{controller,nodeshape}.go —
periodically (10m scan period) verifies the invariants between a NodeClaim
and its Node; today the single check is NodeShape: the node's actual capacity
must be >=90% of what the claim was promised per requested resource. Failures
publish an event; a clean scan sets ConsistentStateFound=True.
"""

from __future__ import annotations

from ...apis.nodeclaim import COND_CONSISTENT_STATE_FOUND, COND_INITIALIZED

SCAN_PERIOD_SECONDS = 10 * 60


def node_shape_issues(node, nc) -> list[str]:
    """nodeshape.go:34-60."""
    if nc.metadata.deletion_timestamp is not None or not nc.status.conditions.is_true(COND_INITIALIZED):
        return []
    issues = []
    for name, requested in nc.spec.resources.items():
        expected = nc.status.capacity.get(name)
        actual = node.status.capacity.get(name)
        if not requested or expected is None or not expected:
            continue
        pct = (actual.as_float() if actual is not None else 0.0) / expected.as_float()
        if pct < 0.90:
            issues.append(f"expected {expected} of resource {name}, but found {actual} ({pct * 100:.1f}% of expected)")
    return issues


class ConsistencyController:
    def __init__(self, store, clock, recorder=None):
        self.store = store
        self.clock = clock
        self.recorder = recorder
        self._last_scanned: dict[str, float] = {}  # claim uid -> time

    def reconcile(self) -> None:
        claims = self.store.list("NodeClaim")
        live = {nc.metadata.uid for nc in claims}
        self._last_scanned = {uid: t for uid, t in self._last_scanned.items() if uid in live}
        for nc in claims:
            if not nc.status.provider_id:
                continue
            last = self._last_scanned.get(nc.metadata.uid)
            if last is not None and self.clock.now() - last < SCAN_PERIOD_SECONDS:
                continue
            self._last_scanned[nc.metadata.uid] = self.clock.now()
            node = self.store.try_get("Node", nc.status.node_name) if nc.status.node_name else None
            if node is None:
                continue
            issues = node_shape_issues(node, nc)
            for issue in issues:
                if self.recorder is not None:
                    self.recorder.publish(nc, "FailedConsistencyCheck", issue)
            if not issues and not nc.status.conditions.is_true(COND_CONSISTENT_STATE_FOUND):
                def apply(obj):
                    obj.status.conditions.set_true(COND_CONSISTENT_STATE_FOUND, now=self.clock.now())

                self.store.patch("NodeClaim", nc.metadata.name, apply)
