"""NodeClaim garbage collection (reference: nodeclaim/garbagecollection):
deletes claims whose cloud instance disappeared, and cloud instances with no
claim (leak protection).
"""

from __future__ import annotations

from ...cloudprovider.errors import NodeClaimNotFoundError
from .lifecycle import _node_ready


class GarbageCollectionController:
    def __init__(self, store, cluster, cloud_provider, clock):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self) -> None:
        claims = self.store.list("NodeClaim")
        by_pid = {nc.status.provider_id: nc for nc in claims if nc.status.provider_id}
        nodes_by_pid = {n.spec.provider_id: n for n in self.store.list("Node") if n.spec.provider_id}

        # claims whose instance is gone -> delete claim, UNLESS the node is
        # there and Ready (controller.go:97-100: a Ready node means the
        # kubelet still runs, so "instance gone" is a transient cloud blip).
        # Unregistered claims are the liveness controller's case and are
        # filtered above, matching the registered-only scan.
        for nc in claims:
            if not nc.status.provider_id or not nc.is_registered():
                continue
            if nc.metadata.deletion_timestamp is not None:
                continue
            node = nodes_by_pid.get(nc.status.provider_id)
            if node is not None and _node_ready(node):
                continue
            try:
                self.cloud_provider.get(nc.status.provider_id)
            except NodeClaimNotFoundError:
                self.store.try_delete("NodeClaim", nc.metadata.name)

        # cloud instances with no claim -> delete instance (leaked)
        for cloud_nc in self.cloud_provider.list():
            if cloud_nc.status.provider_id not in by_pid:
                try:
                    self.cloud_provider.delete(cloud_nc)
                except NodeClaimNotFoundError:
                    pass
