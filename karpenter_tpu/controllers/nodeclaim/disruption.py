"""NodeClaim disruption-readiness controller: sets the Consolidatable and
Drifted status conditions (reference: nodeclaim/disruption/{consolidation.go:40,
drift.go:51-86}).
"""

from __future__ import annotations

import math

from ...apis import labels as wk
from ...apis.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED, COND_INITIALIZED
from ...scheduling.requirements import Requirements


class NodeClaimDisruptionController:
    def __init__(self, store, cluster, cloud_provider, clock):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self) -> None:
        pools = {np.metadata.name: np for np in self.store.list("NodePool")}
        for nc in self.store.list("NodeClaim"):
            if nc.metadata.deletion_timestamp is not None:
                continue
            pool = pools.get(nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""))
            if pool is None:
                continue
            changed = self._consolidatable(nc, pool)
            changed |= self._drifted(nc, pool)
            if changed:
                try:
                    self.store.update(nc)
                    self.cluster.update_node_claim(nc)
                except Exception:
                    pass

    def _consolidatable(self, nc, pool) -> bool:
        """Consolidatable flips true once consolidateAfter has elapsed since
        the last pod event (or initialization)."""
        if not nc.status.conditions.is_true(COND_INITIALIZED):
            return nc.status.conditions.clear(COND_CONSOLIDATABLE)
        ca = pool.spec.disruption.consolidate_after_seconds()
        if ca == math.inf:  # Never
            return nc.status.conditions.clear(COND_CONSOLIDATABLE)
        init = nc.status.conditions.get(COND_INITIALIZED)
        base = nc.status.last_pod_event_time or init.last_transition_time
        if self.clock.now() - base >= ca:
            return nc.status.conditions.set_true(COND_CONSOLIDATABLE, now=self.clock.now())
        return nc.status.conditions.set_false(
            COND_CONSOLIDATABLE, "NotConsolidatable", now=self.clock.now()
        )

    def _drifted(self, nc, pool) -> bool:
        """Drift = cloud-provider drift, nodepool static-hash drift, or
        requirement drift (drift.go:51-150)."""
        if not nc.is_launched():
            return False
        reason = ""
        cp_reason = self.cloud_provider.is_drifted(nc)
        if cp_reason:
            reason = cp_reason
        elif self._static_drift(nc, pool):
            reason = "NodePoolStaticDrift"
        elif self._requirement_drift(nc, pool):
            reason = "RequirementsDrifted"
        if reason:
            return nc.status.conditions.set_true(COND_DRIFTED, reason=reason, now=self.clock.now())
        return nc.status.conditions.clear(COND_DRIFTED)

    @staticmethod
    def _static_drift(nc, pool) -> bool:
        claim_hash = nc.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION_KEY)
        return claim_hash is not None and claim_hash != pool.hash()

    @staticmethod
    def _requirement_drift(nc, pool) -> bool:
        """compatible(), not intersects(): a NodePool requirement on a key the
        claim lacks entirely must flag drift (drift.go:175)."""
        pool_reqs = Requirements.from_node_selector_terms(pool.spec.template.requirements)
        pool_reqs.add(*Requirements.from_labels(pool.spec.template.labels).values())
        claim_labels = Requirements.from_labels(nc.metadata.labels)
        return claim_labels.compatible(pool_reqs) is not None
