"""NodeClaim disruption-readiness controller: sets the Consolidatable and
Drifted status conditions (reference: nodeclaim/disruption/{consolidation.go:40,
drift.go:51-86}).
"""

from __future__ import annotations

import math

from ...apis import labels as wk
from ...apis.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED, COND_INITIALIZED
from ...kube.store import Conflict, NotFound
from ...scheduling.requirements import Requirements


class NodeClaimDisruptionController:
    def __init__(self, store, cluster, cloud_provider, clock):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._it_index_by_pool: dict[str, dict] = {}

    def reconcile(self) -> None:
        pools = {np.metadata.name: np for np in self.store.list("NodePool")}
        self._it_index_by_pool = {}  # per-reconcile: pool -> {it.name: it}
        for nc in self.store.list("NodeClaim"):
            if nc.metadata.deletion_timestamp is not None:
                continue
            pool = pools.get(nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""))
            if pool is None:
                continue
            changed = self._consolidatable(nc, pool)
            changed |= self._drifted(nc, pool)
            if changed:
                try:
                    self.store.update(nc)
                    self.cluster.update_node_claim(nc)
                except (Conflict, NotFound):
                    # a concurrent writer won (or the claim vanished): the
                    # next reconcile recomputes the conditions from fresh
                    # state — only the EXPECTED optimistic-concurrency
                    # failures are absorbed, anything else propagates
                    pass

    def _consolidatable(self, nc, pool) -> bool:
        """Consolidatable flips true once consolidateAfter has elapsed since
        the last pod event (or initialization)."""
        if not nc.status.conditions.is_true(COND_INITIALIZED):
            return nc.status.conditions.clear(COND_CONSOLIDATABLE)
        ca = pool.spec.disruption.consolidate_after_seconds()
        if ca == math.inf:  # Never
            return nc.status.conditions.clear(COND_CONSOLIDATABLE)
        init = nc.status.conditions.get(COND_INITIALIZED)
        base = nc.status.last_pod_event_time or init.last_transition_time
        if self.clock.now() - base >= ca:
            return nc.status.conditions.set_true(COND_CONSOLIDATABLE, now=self.clock.now())
        return nc.status.conditions.set_false(
            COND_CONSOLIDATABLE, "NotConsolidatable", now=self.clock.now()
        )

    # the reference postpones instance-type staleness checks until an hour
    # after claim creation (drift.go:93-96)
    INSTANCE_TYPE_DRIFT_DELAY_SECONDS = 3600.0

    def _drifted(self, nc, pool) -> bool:
        """Drift detection in the reference's precedence (drift.go:86-113):
        nodepool static-hash drift, then requirement drift, then stale
        instance-type drift (delayed 1h from creation), then cloud-provider
        drift last. An unlaunched claim CLEARS the condition (drift.go:57-62)."""
        if not nc.is_launched():
            return nc.status.conditions.clear(COND_DRIFTED)
        reason = ""
        if self._static_drift(nc, pool):
            reason = "NodePoolDrifted"
        elif self._requirement_drift(nc, pool):
            reason = "RequirementsDrifted"
        elif self._instance_type_not_found(nc, pool):
            reason = "InstanceTypeNotFound"
        else:
            reason = self.cloud_provider.is_drifted(nc) or ""
        if reason:
            return nc.status.conditions.set_true(COND_DRIFTED, reason=reason, now=self.clock.now())
        return nc.status.conditions.clear(COND_DRIFTED)

    def _instance_type_not_found(self, nc, pool) -> bool:
        """Stale instance-type drift (drift.go:116-149): the claim's instance
        type vanished from the provider, or no longer has an offering
        compatible with the claim's labels. Reserved claims may be demoted to
        on-demand post-creation, so both capacity types pass."""
        created = nc.metadata.creation_timestamp or 0.0
        if self.clock.now() - created < self.INSTANCE_TYPE_DRIFT_DELAY_SECONDS:
            return False
        it_name = nc.metadata.labels.get(wk.INSTANCE_TYPE_LABEL_KEY)
        if not it_name:
            return True
        index = self._it_index_by_pool.get(pool.metadata.name)
        if index is None:
            index = {x.name: x for x in self.cloud_provider.get_instance_types(pool)}
            self._it_index_by_pool[pool.metadata.name] = index
        it = index.get(it_name)
        if it is None:
            return True
        from ...scheduling.requirements import Requirement

        reqs = Requirements.from_labels(nc.metadata.labels)
        if nc.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY) == wk.CAPACITY_TYPE_RESERVED:
            reqs.replace(
                Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_RESERVED, wk.CAPACITY_TYPE_ON_DEMAND])
            )
            reqs.remove(wk.RESERVATION_ID_LABEL_KEY)
        return not any(reqs.compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) is None for o in it.offerings)

    @staticmethod
    def _static_drift(nc, pool) -> bool:
        """Hash drift gated on matching hash VERSIONS on both sides
        (drift.go:154-168)."""
        pool_hash = pool.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION_KEY)
        pool_ver = pool.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY)
        claim_hash = nc.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION_KEY)
        claim_ver = nc.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY)
        # all four annotations must exist and the versions must match before
        # hashes are comparable — cross-version hashes are never compared
        if pool_hash is None or pool_ver is None or claim_hash is None or claim_ver is None:
            return False
        if pool_ver != claim_ver:
            return False
        return claim_hash != pool_hash

    @staticmethod
    def _requirement_drift(nc, pool) -> bool:
        """compatible(), not intersects(): a NodePool requirement on a key the
        claim lacks entirely must flag drift (drift.go:175)."""
        pool_reqs = Requirements.from_node_selector_terms(pool.spec.template.requirements)
        pool_reqs.add(*Requirements.from_labels(pool.spec.template.labels).values())
        claim_labels = Requirements.from_labels(nc.metadata.labels)
        return claim_labels.compatible(pool_reqs) is not None
