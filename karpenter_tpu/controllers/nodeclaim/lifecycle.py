"""NodeClaim lifecycle: Launch -> Registration -> Initialization -> Liveness.

Reference: nodeclaim/lifecycle/{controller,launch,registration,initialization,
liveness}.go (call stack SURVEY.md §3.3). Each phase is an idempotent
sub-reconciler flipping a status condition; conditions are the durable
checkpoints of the system.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...apis.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from ...cloudprovider.errors import InsufficientCapacityError, NodeClassNotReadyError
from ...kube.objects import OwnerReference
from ...kube.store import NotFound
from ...scheduling.taints import is_known_ephemeral_taint
from ...utils import resources as res

REGISTRATION_TTL_SECONDS = 15 * 60  # liveness.go:39 registrationTTL
LAUNCH_TIMEOUT_SECONDS = 5 * 60  # liveness.go:57-59 LaunchTimeout


class LifecycleController:
    def __init__(self, store, cluster, cloud_provider, clock, recorder=None, np_state=None, metrics=None, registration_hooks=None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        self.np_state = np_state  # nodepoolhealth.NodePoolHealthState
        self.metrics = metrics
        # provider-supplied registration gates: each hook has .name and
        # .registered(node_claim) -> bool; ALL must pass before the
        # unregistered taint drops (cloudprovider types.go:111-118
        # NodeLifecycleHook, controllers.go:78-84 WithRegistrationHook)
        self.registration_hooks = list(registration_hooks or [])

    def reconcile_all(self) -> None:
        for nc in self.store.borrow_list("NodeClaim"):
            # per-item error isolation (controller-runtime semantics: a
            # reconcile error requeues THAT item; it never kills the manager)
            # — a cloud-provider outage on one claim must not stall the fleet
            try:
                self.reconcile(nc.metadata.name)
            except Exception as e:  # noqa: BLE001
                if self.recorder is not None:
                    self.recorder.publish(nc, "ReconcileError", str(e), type_="Warning")

    def reconcile(self, name: str) -> None:
        try:
            nc = self.store.get("NodeClaim", name)
        except NotFound:
            return
        if nc.metadata.deletion_timestamp is not None:
            self._terminate(nc)
            return
        changed = False
        changed |= self._launch(nc)
        changed |= self._register(nc)
        changed |= self._initialize(nc)
        if changed:
            try:
                self.store.update(nc)
                self.cluster.update_node_claim(nc)
            except NotFound:
                return
        self._liveness(nc)

    # -- Launch (launch.go): cloudProvider.Create -> providerID ----------------
    def _launch(self, nc: NodeClaim) -> bool:
        if nc.is_launched() or nc.status.provider_id:
            return False
        try:
            created = self.cloud_provider.create(nc)
        except InsufficientCapacityError as e:
            # terminal for this claim: delete so the provisioner retries
            nc.status.conditions.set_false(COND_LAUNCHED, "InsufficientCapacity", str(e), now=self.clock.now())
            self.store.update(nc)
            self.store.delete("NodeClaim", nc.metadata.name, grace=False)
            return False
        except NodeClassNotReadyError as e:
            nc.status.conditions.set_false(COND_LAUNCHED, "NodeClassNotReady", str(e), now=self.clock.now())
            return True
        nc.status.provider_id = created.status.provider_id
        nc.status.image_id = created.status.image_id
        nc.status.capacity = dict(created.status.capacity)
        nc.status.allocatable = dict(created.status.allocatable)
        # adopt resolved labels (instance type, zone, capacity type)
        for k, v in created.metadata.labels.items():
            nc.metadata.labels.setdefault(k, v)
        nc.status.conditions.set_true(COND_LAUNCHED, now=self.clock.now())
        return True

    # -- Registration (registration.go): node with matching providerID joined --
    def _register(self, nc: NodeClaim) -> bool:
        if nc.is_registered() or not nc.is_launched():
            return False
        node = self._node_for(nc)
        if node is None:
            # anchor the registration-timeout window at the condition's
            # transition time, not the claim's creation (registration.go:68
            # SetUnknownWithReason; liveness_test.go:264)
            return nc.status.conditions.set(
                COND_REGISTERED, "Unknown", "NodeNotFound",
                "Node not registered with cluster", now=self.clock.now(),
            )
        # every registration hook must pass before the unregistered taint
        # drops; until then the sync still runs (labels/annotations/taints)
        # but the node stays unschedulable (registration.go:93-116)
        pending_hooks = [h.name for h in self.registration_hooks if not h.registered(nc)]

        # sync labels/taints/annotations from the claim onto the node; drop
        # the unregistered taint only once the hooks clear
        def apply(n):
            # the claim owns its node (nodeclaim.go:271-287
            # UpdateNodeOwnerReferences; registration_test.go:142-196) —
            # added once, keyed on the claim's uid
            if not any(
                ref.kind == "NodeClaim" and ref.uid == nc.metadata.uid
                for ref in n.metadata.owner_references
            ):
                n.metadata.owner_references.append(
                    OwnerReference(
                        kind="NodeClaim",
                        name=nc.metadata.name,
                        uid=nc.metadata.uid,
                        api_version="karpenter.sh/v1",
                        block_owner_deletion=True,
                    )
                )
            for k, v in nc.metadata.labels.items():
                n.metadata.labels.setdefault(k, v)
            for k, v in nc.metadata.annotations.items():
                n.metadata.annotations.setdefault(k, v)
            # a provider that manages taints itself sets do-not-sync-taints;
            # the unregistered taint is still ours to remove
            # (registration.go:211-217)
            if n.metadata.labels.get(wk.NODE_DO_NOT_SYNC_TAINTS_LABEL_KEY) != "true":
                existing = {(t.key, t.effect) for t in n.spec.taints}
                for t in list(nc.spec.taints) + list(nc.spec.startup_taints):
                    if (t.key, t.effect) not in existing:
                        n.spec.taints.append(t)
            if not pending_hooks:
                n.metadata.labels[wk.NODE_REGISTERED_LABEL_KEY] = "true"
                n.spec.taints = [t for t in n.spec.taints if t.key != wk.UNREGISTERED_TAINT_KEY]
            if wk.TERMINATION_FINALIZER not in n.metadata.finalizers:
                n.metadata.finalizers.append(wk.TERMINATION_FINALIZER)

        self.store.patch("Node", node.metadata.name, apply)
        if pending_hooks:
            # UNKNOWN like the node-missing state (registration.go:171
            # SetUnknownWithReason): flipping to False here would bounce the
            # Registered status Unknown↔False as nodes come and go, resetting
            # the liveness anchor each time and letting a never-registering
            # claim evade the TTL. Transition-only return keeps a steadily
            # unready hook from writing the claim every round.
            return nc.status.conditions.set(
                COND_REGISTERED,
                "Unknown",
                "RegistrationHookPending",
                f"waiting on registration hooks: {', '.join(sorted(pending_hooks))}",
                now=self.clock.now(),
            )
        nc.status.node_name = node.metadata.name
        nc.status.conditions.set_true(COND_REGISTERED, now=self.clock.now())
        self._record_registration_outcome(nc, success=True)
        if self.metrics is not None:
            from ... import metrics as m

            self.metrics.counter(m.NODES_CREATED_TOTAL).inc(
                nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
                zone=nc.metadata.labels.get(wk.ZONE_LABEL_KEY, ""),
            )
        return True

    # -- Initialization (initialization.go): node ready + resources registered -
    def _initialize(self, nc: NodeClaim) -> bool:
        if nc.is_initialized() or not nc.is_registered():
            return False
        node = self.store.try_get("Node", nc.status.node_name)
        if node is None:
            return False
        if not _node_ready(node):
            return False
        # startup taints must have cleared — MatchTaint (key + effect)
        # semantics, consistent with StateNode.taints()' scheduling filter
        startup = {(t.key, t.effect) for t in nc.spec.startup_taints}
        if any((t.key, t.effect) in startup for t in node.spec.taints):
            return False
        # known EPHEMERAL taints must have lifted too: not-ready/unreachable/
        # cloud-provider-uninitialized and readiness.k8s.io/ controller gates
        # (initialization.go:78-79,104-112 KnownEphemeralTaintsRemoved)
        if any(is_known_ephemeral_taint(t) for t in node.spec.taints):
            return False
        # every non-zero requested resource must be REGISTERED on the node:
        # kubelet zeroes extended resources at startup, so a zero allocatable
        # for a requested resource means the device plugin hasn't published
        # yet (initialization.go:131-146 RequestedResourcesRegistered)
        for name, q in nc.spec.resources.items():
            if name == "pods" or q.milli == 0:
                continue
            have = node.status.allocatable.get(name)
            if have is None or have.milli == 0:
                return False

        def apply(n):
            n.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] = "true"

        self.store.patch("Node", node.metadata.name, apply)
        nc.status.conditions.set_true(COND_INITIALIZED, now=self.clock.now())
        return True

    # -- Liveness (liveness.go:62): kill claims that never register ------------
    def _liveness(self, nc: NodeClaim) -> None:
        if nc.is_registered():
            return
        now = self.clock.now()
        launched = nc.status.conditions.get(COND_LAUNCHED)
        # a claim stuck UNLAUNCHED dies on the (shorter) launch timeout,
        # measured from the Launched condition's transition — not the
        # claim's creation (liveness.go:66-88, liveness_test.go:224)
        if launched is not None and launched.status != "True":
            if now - launched.last_transition_time > LAUNCH_TIMEOUT_SECONDS:
                self._record_registration_outcome(nc, success=False)
                self.store.try_delete("NodeClaim", nc.metadata.name)
            return
        registered = nc.status.conditions.get(COND_REGISTERED)
        # registration timeout anchors at the Registered condition's
        # transition (set Unknown when the node hasn't joined); claims
        # predating that anchor fall back to creation time
        # (liveness.go:90-103, liveness_test.go:264)
        anchor = (
            registered.last_transition_time
            if registered is not None
            else nc.metadata.creation_timestamp
        )
        if now - anchor > REGISTRATION_TTL_SECONDS:
            self._record_registration_outcome(nc, success=False)
            self.store.try_delete("NodeClaim", nc.metadata.name)

    def _record_registration_outcome(self, nc: NodeClaim, success: bool) -> None:
        """Feed the per-pool health tracker and flip NodeRegistrationHealthy
        when the windowed outcome crosses the threshold (registration.go:178-200,
        liveness.go:113-145)."""
        if self.np_state is None or not nc.nodepool_name:
            return
        pool = self.store.try_get("NodePool", nc.nodepool_name)
        if pool is None:
            return
        from ...apis.nodepool import COND_NODE_REGISTRATION_HEALTHY
        from ...state import nodepoolhealth

        uid = pool.metadata.uid
        self.np_state.update(uid, success)
        status = self.np_state.status(uid)
        if success:
            if status == nodepoolhealth.STATUS_HEALTHY and not pool.status.conditions.is_true(
                COND_NODE_REGISTRATION_HEALTHY
            ):
                def apply(obj):
                    obj.status.conditions.set_true(COND_NODE_REGISTRATION_HEALTHY, now=self.clock.now())

                self.store.patch("NodePool", pool.metadata.name, apply)
        else:
            if status == nodepoolhealth.STATUS_UNHEALTHY and not pool.status.conditions.is_false(
                COND_NODE_REGISTRATION_HEALTHY
            ):
                launched = nc.status.conditions.get("Launched")
                if launched is not None and launched.status != "True":
                    reason, message = launched.reason, launched.message
                else:
                    reason, message = "RegistrationFailed", "Failed to register node"

                def apply(obj, reason=reason, message=message):
                    obj.status.conditions.set_false(COND_NODE_REGISTRATION_HEALTHY, reason, message, now=self.clock.now())

                self.store.patch("NodePool", pool.metadata.name, apply)

    # -- claim termination (lifecycle/termination.go): node drained first (the
    # node termination controller owns the drain), then instance gone, then
    # the claim finalizer is released.
    def _terminate(self, nc: NodeClaim) -> None:
        from ...cloudprovider.errors import NodeClaimNotFoundError

        # only REGISTERED claims drain through their Node objects — an
        # unregistered node has no synced kubelet state worth draining and
        # deleting it risks leaked leases, so the instance is terminated
        # directly and the node is garbage collected (controller.go:210-232)
        if nc.is_registered():
            nodes = []
            if nc.status.node_name:
                n = self.store.try_get("Node", nc.status.node_name)
                if n is not None:
                    nodes.append(n)
            if nc.status.provider_id:
                # EVERY node mapping to the claim goes (duplicate-node
                # invariant violations, termination_test.go:233); borrowed
                # scan — only names/timestamps are read, patches go by name
                for n in self.store.borrow_list("Node"):
                    if n.spec.provider_id == nc.status.provider_id and all(
                        n.metadata.name != m.metadata.name for m in nodes
                    ):
                        nodes.append(n)
            for node in nodes:
                if node.metadata.deletion_timestamp is not None:
                    continue  # already terminating; don't re-delete
                # stamp the forced-drain deadline so terminationGracePeriod
                # can override blocked PDBs / do-not-disrupt
                # (termination.go TGP)
                if nc.spec.termination_grace_period is not None:
                    deadline = self.clock.now() + nc.spec.termination_grace_period
                    # an earlier deadline already stamped (e.g. by node
                    # repair's force-drain) wins; never extend it
                    existing = nc.metadata.annotations.get(wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY)
                    if existing is not None:
                        deadline = min(deadline, float(existing))

                    def stamp(n):
                        cur = n.metadata.annotations.get(wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY)
                        if cur is None or float(cur) > deadline:
                            n.metadata.annotations[wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY] = str(deadline)

                    self.store.patch("Node", node.metadata.name, stamp)
                self.store.try_delete("Node", node.metadata.name)  # graceful: drain runs
            if nodes:
                return  # wait until ALL nodes finish draining (controller.go:228-231)
        if nc.status.provider_id:
            try:
                self.cloud_provider.delete(nc)
            except NodeClaimNotFoundError:
                pass
        self.store.remove_finalizer("NodeClaim", nc.metadata.name, wk.TERMINATION_FINALIZER)

    def _node_for(self, nc: NodeClaim):
        # borrowed scan to find the match, clone only the hit (callers mutate
        # the returned node and write it back)
        for node in self.store.borrow_list("Node"):
            if node.spec.provider_id == nc.status.provider_id:
                return self.store.get("Node", node.metadata.name)
        return None


def _node_ready(node) -> bool:
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return True  # KWOK nodes have no kubelet; absence of conditions counts ready
