"""NodeClaim pod-events controller.

Reference: pkg/controllers/nodeclaim/podevents/controller.go — when a pod is
newly bound, turns terminal, or starts terminating on a karpenter node, stamp
NodeClaim.status.lastPodEventTime (deduped to one write per dedupeTimeout).
Consolidation's consolidateAfter clock keys off this timestamp.
"""

from __future__ import annotations

from ...utils import pods as pod_utils

DEDUPE_TIMEOUT_SECONDS = 10.0


class PodEventsController:
    """Watch-driven: register() subscribes to the store's Pod watch feed."""

    def __init__(self, store, clock):
        self.store = store
        self.clock = clock
        # pod key -> (node_name, terminal, terminating) last observed
        self._observed: dict[str, tuple[str, bool, bool]] = {}

    def register(self) -> None:
        self.store.watch("Pod", self._on_pod_event)

    def _on_pod_event(self, event: str, pod) -> None:
        key = pod.key()
        prev = self._observed.get(key, ("", False, False))
        terminal = pod.status.phase in ("Succeeded", "Failed")
        terminating = pod.metadata.deletion_timestamp is not None
        if pod_utils.is_owned_by_daemonset(pod):
            return
        if event == "DELETED":
            # a finalizer-less delete emits only DELETED; the node the pod
            # leaves still needs its consolidateAfter idle clock reset
            self._observed.pop(key, None)
            if prev[0]:
                self._stamp(prev[0])
            return
        self._observed[key] = (pod.spec.node_name, terminal, terminating)
        bound = prev[0] == "" and pod.spec.node_name != ""
        unbound = prev[0] != "" and pod.spec.node_name == ""  # eviction unbind
        went_terminal = not prev[1] and terminal
        went_terminating = not prev[2] and terminating
        if unbound:
            self._stamp(prev[0])
            return
        if not pod.spec.node_name:
            return
        if not (bound or went_terminal or went_terminating):
            return
        self._stamp(pod.spec.node_name)

    def _stamp(self, node_name: str) -> None:
        node = self.store.borrow_get("Node", node_name)
        if node is None:
            return
        provider_id = node.spec.provider_id
        nc = next(
            (c for c in self.store.borrow_list("NodeClaim") if c.status.node_name == node_name or c.status.provider_id == provider_id),
            None,
        )
        if nc is None:
            return
        if nc.status.last_pod_event_time and self.clock.now() - nc.status.last_pod_event_time < DEDUPE_TIMEOUT_SECONDS:
            return

        def apply(obj):
            obj.status.last_pod_event_time = self.clock.now()

        self.store.patch("NodeClaim", nc.metadata.name, apply)
