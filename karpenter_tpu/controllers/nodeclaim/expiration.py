"""NodeClaim expiration controller.

Reference: pkg/controllers/nodeclaim/expiration/controller.go — forcefully
deletes NodeClaims older than spec.expireAfter. Expiration is absolute: it
does not wait for replacement capacity (the provisioner reprovisions for the
evicted pods afterwards).
"""

from __future__ import annotations

import math


class ExpirationController:
    def __init__(self, store, clock, metrics=None):
        self.store = store
        self.clock = clock
        self.metrics = metrics

    def reconcile(self) -> None:
        for nc in self.store.list("NodeClaim"):
            if nc.metadata.deletion_timestamp is not None:
                continue
            expire_after = nc.spec.expire_after
            if expire_after is None or expire_after == math.inf:
                continue
            if self.clock.now() < nc.metadata.creation_timestamp + expire_after:
                continue
            self.store.try_delete("NodeClaim", nc.metadata.name)
            if self.metrics is not None:
                from ... import metrics as m
                from ...apis import labels as wk

                self.metrics.counter(m.NODECLAIMS_DISRUPTED_TOTAL).inc(
                    reason="expired",
                    nodepool=nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
                    capacity_type=nc.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY, ""),
                )
