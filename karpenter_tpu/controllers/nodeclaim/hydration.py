"""NodeClaim hydration controller.

Reference: pkg/controllers/nodeclaim/hydration/controller.go — backfills
fields added in newer versions onto pre-existing NodeClaims after an upgrade.
Currently: the node-class label (<group>/<kind-lowercase> = class name).
"""

from __future__ import annotations


def node_class_label_key(group: str, kind: str) -> str:
    return f"{group}/{kind.lower()}"


class HydrationController:
    def __init__(self, store):
        self.store = store

    def reconcile(self) -> None:
        for nc in self.store.list("NodeClaim"):
            ref = nc.spec.node_class_ref
            key = node_class_label_key(ref.group, ref.kind)
            if nc.metadata.labels.get(key) == ref.name:
                continue

            def apply(obj, key=key, name=ref.name):
                obj.metadata.labels[key] = name

            self.store.patch("NodeClaim", nc.metadata.name, apply)
