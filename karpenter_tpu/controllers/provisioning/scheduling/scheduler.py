"""The scheduling simulation: Solve() bin-packs pending pods onto existing
nodes, in-flight NodeClaims, and new NodeClaims from NodePool templates.

Reference: scheduling/scheduler.go:440-1004 — the FFD loop with preference
relaxation and daemon-overhead groups. This host implementation is the exact
correctness oracle; the TPU tensor solver (karpenter_tpu/solver/tpu.py) is
validated against it and plugs in through the same Solver interface.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field

from ....apis import labels as wk
from ....scheduling.requirements import Operator, Requirement, Requirements
from ....scheduling.taints import pools_taint_prefer_no_schedule, taints_tolerate_pod
from ....utils import resources as res
from ....utils.quantity import Quantity
from ....scheduling.volumeusage import get_volumes
from .existingnode import ExistingNode
from .nodeclaim import DaemonOverheadGroup, NodeClaimTemplate, SchedulingNodeClaim
from .preferences import Preferences
from .queue import Queue
from .topology import Topology
from .volumetopology import VolumeTopology


@dataclass
class PodData:
    requests: dict
    requirements: Requirements
    strict_requirements: Requirements
    # volume topology requirement alternatives (scheduler.go:222) and the
    # pod's PVC volumes grouped by driver for limit tracking (scheduler.go:623)
    volume_requirements: list = field(default_factory=list)
    volumes: dict = field(default_factory=dict)
    # DRA: the pod's resolved ResourceClaims (scheduler.go PodData
    # ResourceClaims/HasResourceClaimRequests/ResourceClaimErr)
    resource_claims: list = field(default_factory=list)
    resource_claim_err: str | None = None


@dataclass
class Results:
    """Outcome of a Solve (scheduler.go Results)."""

    new_node_claims: list[SchedulingNodeClaim] = field(default_factory=list)
    existing_nodes: list[ExistingNode] = field(default_factory=list)
    pod_errors: dict = field(default_factory=dict)  # pod key -> error string
    timed_out: bool = False
    # effective-zone label -> pending pod count (scheduler.go:453-459,495-501);
    # None = the producing backend did not compute it
    pending_pods_by_effective_zone: dict | None = None

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors

    def non_pending_pod_scheduling_errors(self) -> str:
        return "; ".join(f"{k}: {v}" for k, v in self.pod_errors.items())

    def node_pod_count(self) -> dict[str, int]:
        out = {}
        for n in self.existing_nodes:
            if n.pods:
                out[n.name()] = len(n.pods)
        return out

    def total_new_nodes(self) -> int:
        return len(self.new_node_claims)


# fit-memo entry cap (signatures x nodes scaling; see Scheduler._fit_memo)
_FIT_MEMO_MAX = 100_000


class SchedulerRoundSeed:
    """Cross-build carry for one consolidation round's host schedulers.

    A round runs many probe simulations; each builds a fresh Scheduler over
    almost the same cluster state. Three layers are PROBE-INVARIANT and carry
    across builds:

      - pod_data_templates: signature -> shared PodData (pure function of the
        pod content + store/policy, both fixed within a round);
      - sig_by_uid: pod uid -> signature (recomputed per solve anyway — the
        carry just skips re-deriving the signature string);
      - static_rejects: (signature, node name) -> error, recorded ONLY when
        the verdict was derived at the node's INITIAL state
        (node._version == 0). A version-0 node is identical in every probe
        that includes it (ExistingNode is rebuilt from the same StateNode),
        so the rejection is sound to pre-seed — mid-probe rejects (version
        > 0) depend on that probe's placements and are never recorded.

    The 15s command Validator never receives a seed: executed commands always
    re-validate against a fully independent from-scratch simulation."""

    def __init__(self):
        self.pod_data_templates: dict = {}
        self.sig_by_uid: dict = {}
        self.static_rejects: dict = {}
        self.seeded = 0  # rejects pre-seeded into the newest build (observability)


class Scheduler:
    def __init__(
        self,
        store,
        cluster,
        node_pools: list,
        instance_types: dict[str, list],  # nodepool name -> instance types
        state_nodes: list,
        daemonset_pods: list,
        clock,
        preference_policy: str = "Respect",
        min_values_policy: str = "Strict",
        enforce_consolidate_after: bool = False,
        deleting_node_names: set[str] | None = None,
        timeout_seconds: float = 60.0,
        dra_enabled: bool = False,
        reserved_capacity_enabled: bool = True,
        reserved_offering_mode: str = "fallback",
        collect_zone_metrics: bool = True,
        registry=None,
        ffd_batch: bool | None = None,
        round_seed: "SchedulerRoundSeed | None" = None,
    ):
        self.store = store
        self.cluster = cluster
        self.clock = clock
        self.preference_policy = preference_policy
        self.collect_zone_metrics = collect_zone_metrics
        self.min_values_policy = min_values_policy
        self.deleting_node_names = deleting_node_names or set()
        self.timeout_seconds = timeout_seconds
        self.registry = registry
        # KARPENTER_FFD_BATCH=1 (default): signature-batched FFD — per-solve
        # fit memo + placement cursors + PodData template cache + incremental
        # claim ordering. =0 is the exact-reference escape hatch; placements
        # are bit-identical either way (tests/test_ffd_batch.py).
        if ffd_batch is None:
            ffd_batch = os.environ.get("KARPENTER_FFD_BATCH", "1") != "0"
        self.batch_enabled = ffd_batch
        # fit memo: (pod signature, id(node|claim|template)) ->
        #   ("reject", err)          permanent monotone rejection
        #   ("pass", version, base)  static prefix passed at that state version
        # Unlike the per-pod-bounded caches below, entries scale with
        # signatures x nodes — capped like the filter cache so a unique-
        # signature flood (e.g. per-pod StatefulSet labels) can't balloon it;
        # clearing only forgets memoized verdicts, never invalidates cursors
        # (the underlying rejections stay permanent regardless)
        self._fit_memo: dict = {}
        # per-signature scan cursor over the (fixed-order) existing-node list:
        # every node before the cursor holds a permanent rejection for the sig
        self._existing_cursor: dict = {}
        # consolidation-round carry (SchedulerRoundSeed): probe-invariant
        # layers shared across this round's scheduler builds
        self._round_seed = round_seed if self.batch_enabled else None
        # signature -> shared PodData template (volume/port/DRA-free pods)
        self._pod_data_templates: dict = {} if self._round_seed is None else self._round_seed.pod_data_templates
        # pod uid -> signature tuple (None = pod bypasses the batched path)
        self._sig_by_uid: dict = {} if self._round_seed is None else self._round_seed.sig_by_uid
        # signature -> effective zone; valid ONLY during the pre-solve metric
        # loop (no placements happen there, so topology state is frozen)
        self._zone_by_sig: dict = {}
        self.memo_stats = {"hit": 0, "miss": 0, "invalidate": 0}
        self.phase_seconds = {"existing": 0.0, "inflight": 0.0, "new_claim": 0.0}
        # the PreferNoSchedule toleration relaxation arms whenever some pool
        # taints with that effect (scheduler.go:144-153 — policy-independent)
        self.preferences = Preferences(
            tolerate_prefer_no_schedule=pools_taint_prefer_no_schedule(node_pools)
            or (preference_policy == "Ignore")
        )
        self.cached_pod_data: dict[str, PodData] = {}
        # solve-scoped filter_instance_types memo shared by every claim
        # (nodeclaim.filter_instance_types_cached): identical pod signatures
        # probing the same claim state skip the full-catalog scan
        self.filter_cache: dict = {}
        self.volume_topology = VolumeTopology(store)
        # one DRA allocator per solve, shared by every candidate (provisioner.go:333-344)
        self.allocator = None
        if dra_enabled:
            from ....scheduling.dynamicresources import Allocator

            self.allocator = Allocator(store, clock)

        # one ReservationManager per solve, shared by every claim so reserved
        # capacity is bounded ACROSS claims (scheduler.go:186, NewScheduler)
        self.reservation_manager = None
        self.reserved_offering_mode = reserved_offering_mode
        if reserved_capacity_enabled:
            from .reservationmanager import ReservationManager

            self.reservation_manager = ReservationManager(instance_types)
            if not self.reservation_manager.capacity:
                # no reserved offerings anywhere: skip the per-can_add
                # offering scan entirely (same guard the TPU decode applies)
                self.reservation_manager = None

        # NodePools ordered by weight desc (provisioner.go:268-289)
        pools = sorted(node_pools, key=lambda np: (-np.spec.weight, np.metadata.name))
        self.templates: list[NodeClaimTemplate] = []
        for np in pools:
            t = NodeClaimTemplate(np)
            its = [it for it in instance_types.get(np.metadata.name, []) if _template_compatible(t, it)]
            if not its:
                continue
            t.instance_type_options = its
            self.templates.append(t)

        # remaining resources per nodepool for limit enforcement: start from the
        # raw limits; each state node is subtracted exactly once below
        # (scheduler.go:183-185, 840)
        self.remaining_resources: dict[str, dict[str, Quantity]] = {}
        for np in pools:
            if np.spec.limits:
                self.remaining_resources[np.metadata.name] = {k: Quantity(v.milli) for k, v in np.spec.limits.items()}

        self.topology = Topology(
            store,
            cluster,
            state_nodes,
            pools,
            instance_types,
            pods=[],
            preference_policy=preference_policy,
        )

        # daemon overhead groups per template (scheduler.go:963-1004)
        self.daemon_overhead_groups: dict[int, list[DaemonOverheadGroup]] = {}
        self.daemonset_pods = daemonset_pods
        for t in self.templates:
            self.daemon_overhead_groups[id(t)] = _compute_daemon_overhead_groups(t, daemonset_pods)

        nodepool_map = {np.metadata.name: np for np in pools}
        self.existing_nodes: list[ExistingNode] = []
        for sn in sorted(state_nodes, key=lambda n: n.name()):
            taints = sn.taints()
            daemons = [
                d
                for d in daemonset_pods
                if _daemon_compatible_with_node(sn, taints, d)
            ]
            under_ca = False
            if enforce_consolidate_after and sn.node_claim is not None:
                np = nodepool_map.get(sn.nodepool_name())
                under_ca = _is_under_consolidate_after(np, sn.node_claim, clock)
            self.existing_nodes.append(
                ExistingNode(sn, self.topology, taints, res.requests_for_pods(daemons), under_ca, allocator=self.allocator, daemon_pods=daemons)
            )
            self._update_remaining_resources(sn)

        # pre-seed the fit memo from the round carry: every recorded
        # version-0 static reject of a node this build still holds is
        # identical here (same StateNode, same initial ExistingNode state)
        if self._round_seed is not None and self._round_seed.static_rejects:
            by_name = {en.state_node.name(): en for en in self.existing_nodes}
            n_seeded = 0
            for (sig, node_name), err in self._round_seed.static_rejects.items():
                en = by_name.get(node_name)
                if en is not None:
                    self._memo_put((sig, id(en)), ("reject", err))
                    n_seeded += 1
            self._round_seed.seeded = n_seeded

        self.new_node_claims: list[SchedulingNodeClaim] = []

    def _update_remaining_resources(self, sn) -> None:
        pool = sn.nodepool_name()
        if pool in self.remaining_resources:
            self.remaining_resources[pool] = res.subtract(self.remaining_resources[pool], sn.capacity())

    # -- the solve loop (scheduler.go:440-494) ---------------------------------
    def compute_effective_zone_from_pod(self, pod) -> str:
        """The pod's effective zone constraint: the intersection of its
        node-selector zone signals, volume zone requirements, and zone
        topology-spread valid domains — a concrete zone name when exactly one
        survives, "flexible" for several, "none" for an empty intersection
        (scheduler.go:860-908 computeEffectiveZoneFromPod)."""
        pod_data = self.cached_pod_data[pod.metadata.uid]
        tsc_zones, satisfiable = self.topology.get_topology_zone_constraints(pod, pod_data.requirements)
        if not satisfiable:
            return "none"
        zone_req = pod_data.strict_requirements.get(wk.ZONE_LABEL_KEY)
        vol_zone_req = _volume_zone_req(pod_data.volume_requirements)
        if zone_req.operator() == Operator.IN:
            zonal_values = zone_req.values_list()
        elif vol_zone_req is not None:
            zonal_values = vol_zone_req.values_list()
        elif tsc_zones is not None:
            zonal_values = sorted(tsc_zones)
        else:
            return "flexible"
        matched = [
            z
            for z in zonal_values
            if zone_req.has(z)
            and (vol_zone_req is None or vol_zone_req.has(z))
            and (tsc_zones is None or z in tsc_zones)
        ]
        if len(matched) == 1:
            return matched[0]
        return "flexible" if len(matched) > 1 else "none"

    def solve(self, pods: list) -> Results:
        pod_errors: dict[str, tuple] = {}  # uid -> (pod, error)
        self.topology.prepare(pods)
        from ....apis.capacitybuffer import is_virtual_pod

        # the zone memo is only valid while topology counts are frozen; a
        # reused Scheduler re-enters with counts from the previous solve.
        # Template rejections were memoized under "no topology group
        # constrains this signature" — a new pod set can add inverse groups,
        # so they reset per solve too (no-op for the usual one-solve life)
        self._zone_by_sig.clear()
        if self._fit_memo:
            tmpl_ids = {id(t) for t in self.templates}
            self._fit_memo = {k: v for k, v in self._fit_memo.items() if k[1] not in tmpl_ids}
        # per-solve observability (flushed to the registry once per solve)
        self.memo_stats = {"hit": 0, "miss": 0, "invalidate": 0}
        self.phase_seconds = {"existing": 0.0, "inflight": 0.0, "new_claim": 0.0}
        pods_by_zone: dict[str, int] | None = None
        if self.collect_zone_metrics:
            pods_by_zone = {}
        for p in pods:
            self._update_cached_pod_data(p)
            # buffer virtual pods are headroom, not demand — the reference's
            # count excludes them via the phase guard (virtual pods carry no
            # phase there, buffers.go:140-148; scheduler.go:455-459);
            # consolidation simulations skip the computation entirely
            if (
                pods_by_zone is not None
                and p.status.phase in ("", "Pending")
                and not is_virtual_pod(p)
            ):
                # no placement happens until the queue loop below, so the
                # effective zone is a pure function of the pod signature here
                sig = self._sig_by_uid.get(p.metadata.uid)
                zone = self._zone_by_sig.get(sig) if sig is not None else None
                if zone is None:
                    zone = self.compute_effective_zone_from_pod(p)
                    if sig is not None:
                        self._zone_by_sig[sig] = zone
                pods_by_zone[zone] = pods_by_zone.get(zone, 0) + 1

        if self.batch_enabled:
            # establish the fewest-pods-first invariant once (adopted in-flight
            # claims from a hybrid residual arrive unsorted); every later add
            # repositions exactly one claim, so the reference's per-_add resort
            # reduces to an O(shift) bubble
            self.new_node_claims.sort(key=lambda m: len(m.pods))

        q = Queue(pods, self.cached_pod_data)
        start = self.clock.now()
        timed_out = False
        while True:
            pod = q.pop()
            if pod is None:
                break
            if self.clock.now() - start > self.timeout_seconds:
                # surface every unattempted pod so callers never mistake a
                # partial simulation for a complete one (scheduler.go:520)
                timed_out = True
                pod_errors[pod.metadata.uid] = (pod, "scheduling simulation timed out")
                for rest in q.list():
                    pod_errors.setdefault(rest.metadata.uid, (rest, "scheduling simulation timed out"))
                break
            err = self._try_schedule(pod)
            if err is not None:
                pod_errors[pod.metadata.uid] = (pod, err)
                self.topology.update(pod)
                self._update_cached_pod_data(pod)
                q.push(pod)
            else:
                pod_errors.pop(pod.metadata.uid, None)

        for nc in self.new_node_claims:
            nc.finalize()

        if self.registry is not None:
            self._flush_solve_metrics()
        self._flush_trace()

        return Results(
            new_node_claims=list(self.new_node_claims),
            existing_nodes=list(self.existing_nodes),
            pod_errors={p.key(): e for p, e in pod_errors.values()},
            timed_out=timed_out,
            pending_pods_by_effective_zone=pods_by_zone,
        )

    def _flush_solve_metrics(self) -> None:
        from .... import metrics as m

        memo = self.registry.counter(m.SOLVER_FFD_MEMO_TOTAL)
        memo.inc(self.memo_stats["hit"], kind="hit")
        memo.inc(self.memo_stats["miss"], kind="miss")
        memo.inc(self.memo_stats["invalidate"], kind="invalidate")
        phases = self.registry.histogram(m.SOLVER_FFD_PHASE_SECONDS)
        phases.observe(self.phase_seconds["existing"], phase="existing")
        phases.observe(self.phase_seconds["inflight"], phase="inflight")
        phases.observe(self.phase_seconds["new_claim"], phase="new_claim")

    def _flush_trace(self) -> None:
        """Attach this solve's per-phase split and fit-memo attribution to
        the ambient SolveTrace, if one is active (a TPU fallback/residual or
        a flight-recorded FFD solve). The per-pod phase accumulation itself
        stays counter-based — a span per pod would be the overhead the trace
        layer promises not to add — so the totals land as back-dated spans."""
        from ....obs.trace import current_trace

        tr = current_trace()
        if tr is None or not tr.enabled:
            return
        for phase in ("existing", "inflight", "new_claim"):
            tr.add_phase(f"ffd.{phase}", self.phase_seconds[phase])
        tr.note(ffd_memo=dict(self.memo_stats))

    def _memo_put(self, key, entry) -> None:
        memo = self._fit_memo
        if len(memo) >= _FIT_MEMO_MAX:
            memo.clear()  # bound memory; verdicts re-derive on demand
        memo[key] = entry

    def _cacheable_sig(self, pod):
        """The pod's scheduling signature, or None when the pod must bypass
        the batched fast path: bound pods (node_name feeds the existing-node
        scan's consolidate-after skip), DRA pods, PVC/ephemeral-volume pods
        (claim NAMES are not part of the signature but select distinct PVC
        objects), and host-port pods (their conflict checks read mutable
        usage state the signature cannot see) — the same exclusions as
        filter_instance_types_cached."""
        spec = pod.spec
        if spec.node_name or spec.resource_claims:
            return None
        for v in spec.volumes:
            if v.get("persistentVolumeClaim") or v.get("ephemeral") is not None:
                return None
        from ....scheduling.hostports import pod_host_ports

        if pod_host_ports(pod):
            return None
        from ....solver.encode import pod_signature  # lazy: encode imports this module

        return pod_signature(pod)

    def _update_cached_pod_data(self, pod) -> None:
        if self.batch_enabled:
            sig = self._cacheable_sig(pod)
            self._sig_by_uid[pod.metadata.uid] = sig
            if sig is not None:
                data = self._pod_data_templates.get(sig)
                if data is None:
                    data = self._pod_data_templates[sig] = self._build_pod_data(pod)
                self.cached_pod_data[pod.metadata.uid] = data
                return
        self.cached_pod_data[pod.metadata.uid] = self._build_pod_data(pod)

    def _build_pod_data(self, pod) -> PodData:
        if self.preference_policy == "Ignore":
            requirements = Requirements.from_pod(pod, strict=True)
        else:
            requirements = Requirements.from_pod(pod)
        strict = requirements
        aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
        if aff is not None and aff.preferred:
            strict = Requirements.from_pod(pod, strict=True)
        claims, claim_err = [], None
        if self.allocator is not None and pod.spec.resource_claims:
            from ....scheduling.dynamicresources import resolve_pod_claims

            claims, claim_err = resolve_pod_claims(self.store, pod)
            claims = claims or []  # claim_err is carried separately and fails CanAdd
        return PodData(
            requests=res.pod_requests(pod),
            requirements=requirements,
            strict_requirements=strict,
            volume_requirements=self.volume_topology.get_requirements(pod),
            volumes=get_volumes(self.store, pod),
            resource_claims=claims,
            resource_claim_err=claim_err,
        )

    def _try_schedule(self, pod) -> str | None:
        """Relaxation loop (scheduler.go:521-552). The pod is copied lazily —
        only right before the first relaxation mutates its spec — so the
        dominant first-attempt success never pays the deepcopy, and the
        caller's original stays pristine either way."""
        import copy

        relaxed = False
        while True:
            err = self._add(pod)
            if err is None:
                return None
            if not relaxed:
                pod = copy.deepcopy(pod)
                relaxed = True
            if not self.preferences.relax(pod):
                return err
            self.topology.update(pod)
            self._update_cached_pod_data(pod)

    def _add(self, pod) -> str | None:
        t0 = time.perf_counter()
        err = self._add_to_existing_node(pod)
        t1 = time.perf_counter()
        self.phase_seconds["existing"] += t1 - t0
        if err is None:
            return None
        if not self.batch_enabled:
            # inflight claims sorted fewest-pods-first (scheduler.go:598); the
            # batched path maintains this invariant incrementally instead
            self.new_node_claims.sort(key=lambda m: len(m.pods))
        err = self._add_to_inflight_node(pod)
        t2 = time.perf_counter()
        self.phase_seconds["inflight"] += t2 - t1
        if err is None:
            return None
        if not self.templates:
            return "nodepool requirements filtered out all available instance types"
        err = self._add_to_new_node_claim(pod)
        self.phase_seconds["new_claim"] += time.perf_counter() - t2
        return err

    def _add_to_existing_node(self, pod) -> str | None:
        pod_data = self.cached_pod_data[pod.metadata.uid]
        is_pending = not pod.spec.node_name
        sig = self._sig_by_uid.get(pod.metadata.uid) if self.batch_enabled else None
        nodes = self.existing_nodes
        landed = None
        # placement cursor: every node before it permanently rejected this
        # signature, so an identical pod resumes where the last one got to
        start = self._existing_cursor.get(sig, 0) if sig is not None else 0
        if start:
            self.memo_stats["hit"] += start  # cursor-skipped permanent rejections
        for i in range(start, len(nodes)):
            node = nodes[i]
            if node.is_under_consolidate_after and not is_pending and pod.spec.node_name not in self.deleting_node_names:
                continue
            if sig is None:
                reqs, err = node.can_add(pod, pod_data)
                if err is None:
                    node.add(pod, pod_data, reqs)
                    return None
                continue
            key = (sig, id(node))
            ent = self._fit_memo.get(key)
            if ent is not None and ent[0] == "reject":
                self.memo_stats["hit"] += 1
                continue
            if ent is not None and ent[1] == node._version:
                self.memo_stats["hit"] += 1
                base = ent[2]
            else:
                if ent is not None:
                    self.memo_stats["invalidate"] += 1
                else:
                    self.memo_stats["miss"] += 1
                base, err = node.can_add_static(pod, pod_data)
                if err is not None:
                    # every static check is monotone within the solve
                    # (existingnode.can_add_static): cache forever
                    self._memo_put(key, ("reject", err))
                    if self._round_seed is not None and node._version == 0:
                        # derived at the node's INITIAL state: probe-invariant
                        # within the round — record it for the next build
                        self._round_seed.static_rejects[(sig, node.state_node.name())] = err
                    continue
                self._memo_put(key, ("pass", node._version, base))
            reqs, err = node.can_add_dynamic(pod, pod_data, base)
            if err is None:
                node.add(pod, pod_data, reqs)
                landed = i
                break
        if sig is not None:
            c = self._existing_cursor.get(sig, 0)
            while c < len(nodes):
                ent = self._fit_memo.get((sig, id(nodes[c])))
                if ent is None or ent[0] != "reject":
                    break
                c += 1
            self._existing_cursor[sig] = c
        if landed is not None:
            return None
        return "failed scheduling pod to existing nodes"

    def _add_to_inflight_node(self, pod) -> str | None:
        # the in-flight "cursor" is the memo itself: claims re-order as their
        # pod counts move (fewest-first), so a positional resume point is
        # unsound here — instead every permanently-rejected claim costs one
        # dict lookup and everything else resumes exactly where the last
        # identical pod left its verdicts
        pod_data = self.cached_pod_data[pod.metadata.uid]
        sig = self._sig_by_uid.get(pod.metadata.uid) if self.batch_enabled else None
        claims = self.new_node_claims
        for i in range(len(claims)):
            nc = claims[i]
            if sig is None:
                # in-flight claims never relax minValues (scheduler.go:669)
                reqs, its, err = nc.can_add(pod, pod_data, relax_min_values=False)
            else:
                key = (sig, id(nc))
                ent = self._fit_memo.get(key)
                if ent is not None and ent[0] == "reject":
                    self.memo_stats["hit"] += 1
                    continue
                if ent is not None and ent[1] == nc._version:
                    self.memo_stats["hit"] += 1
                    base = ent[2]
                else:
                    if ent is not None:
                        self.memo_stats["invalidate"] += 1
                    else:
                        self.memo_stats["miss"] += 1
                    base, serr = nc.can_add_static(pod, pod_data)
                    if serr is not None:
                        # taints are fixed and claim requirements only ever
                        # tighten: a static rejection is permanent
                        self._memo_put(key, ("reject", serr))
                        continue
                    self._memo_put(key, ("pass", nc._version, base))
                reqs, its, err, permanent = nc.can_add_dynamic(pod, pod_data, base, relax_min_values=False)
                if err is not None and permanent:
                    # capacity-exhausted: no option of this claim has the raw
                    # resources for its accumulated requests plus this pod —
                    # monotone regardless of topology/reservation churn
                    self._memo_put(key, ("reject", err))
            if err is None:
                nc.add(pod, pod_data, reqs, its)
                if self.batch_enabled:
                    self._bubble_claim_right(i)
                return None
        return "failed scheduling pod to inflight nodes"

    def _add_to_new_node_claim(self, pod) -> str | None:
        pod_data = self.cached_pod_data[pod.metadata.uid]
        sig = self._sig_by_uid.get(pod.metadata.uid) if self.batch_enabled else None
        errs = []
        for t in self.templates:
            its = t.instance_type_options
            remaining = self.remaining_resources.get(t.nodepool_name)
            # nodepool limits make the option set probe-dependent, so template
            # rejections are only memoized for unlimited pools (the memoized
            # error string must be exactly reproducible)
            memo_key = (sig, id(t)) if sig is not None and remaining is None else None
            if memo_key is not None:
                ent = self._fit_memo.get(memo_key)
                if ent is not None:
                    self.memo_stats["hit"] += 1
                    errs.append(ent[1])
                    continue
            if remaining is not None:
                nodes_left = remaining.get("nodes")
                if nodes_left is not None and nodes_left.milli <= 0:
                    errs.append(f"node limits exhausted for nodepool {t.nodepool_name}")
                    continue
                its = _filter_by_remaining_resources(its, remaining)
                if not its:
                    errs.append(f"all available instance types exceed limits for nodepool {t.nodepool_name}")
                    continue
            nc = SchedulingNodeClaim(
                t,
                self.topology,
                self.daemon_overhead_groups[id(t)],
                its,
                allocator=self.allocator,
                reservation_manager=self.reservation_manager,
                reserved_offering_mode=self.reserved_offering_mode,
                filter_cache=self.filter_cache,
            )
            relax = self.min_values_policy == "BestEffort"
            if memo_key is None:
                reqs, rem_its, err = nc.can_add(pod, pod_data, relax_min_values=relax)
            else:
                base, err = nc.can_add_static(pod, pod_data)
                permanent = err is not None  # static rejections are permanent
                if err is None:
                    reqs, rem_its, err, permanent = nc.can_add_dynamic(pod, pod_data, base, relax_min_values=relax)
                if err is not None and permanent and not self.topology._matching_topologies(pod, t.taints, base or nc.requirements):
                    # a fresh claim's probe is state-independent when no
                    # topology group constrains the pod: the exact error
                    # string reproduces on every later probe, so memoize it
                    # (pod_errors stay bit-identical to the unbatched path)
                    self._memo_put(memo_key, ("reject", f"{t.nodepool_name}: {err}"))
            if err is not None:
                errs.append(f"{t.nodepool_name}: {err}")
                continue
            nc.add(pod, pod_data, reqs, rem_its)
            self.new_node_claims.append(nc)
            if self.batch_enabled:
                self._bubble_claim_left()
            if remaining is not None:
                self.remaining_resources[t.nodepool_name] = _subtract_max(remaining, nc.instance_type_options)
            return None
        return "; ".join(errs) if errs else "no nodepool matched pod"

    # -- incremental fewest-pods-first maintenance -----------------------------
    # One add changes exactly one claim's pod count; relocating just that claim
    # reproduces what the reference's per-_add stable sort would compute.

    def _bubble_claim_right(self, i: int) -> None:
        """Claim i gained a pod: move it right past claims with strictly fewer
        pods (stable order among equal counts is preserved, matching
        list.sort)."""
        claims = self.new_node_claims
        c = claims[i]
        k = len(c.pods)
        j = i
        while j + 1 < len(claims) and len(claims[j + 1].pods) < k:
            claims[j] = claims[j + 1]
            j += 1
        claims[j] = c

    def _bubble_claim_left(self) -> None:
        """A claim was appended: move it left past claims with strictly more
        pods (it stays after equal counts, exactly where a stable sort of
        append-then-sort would place it)."""
        claims = self.new_node_claims
        j = len(claims) - 1
        c = claims[j]
        k = len(c.pods)
        while j > 0 and len(claims[j - 1].pods) > k:
            claims[j] = claims[j - 1]
            j -= 1
        claims[j] = c


def _volume_zone_req(volume_reqs: list) -> Requirement | None:
    """Union of zone constraints across the pod's volume requirement
    alternatives, or None when volumes don't constrain zones — any
    zone-unconstrained alternative (operator != In, since
    VolumeTopology.get_requirements normalizes alternatives to Requirements)
    unconstrains the whole pod (scheduler.go:910-936 volumeZoneReq)."""
    if not volume_reqs:
        return None
    values: set[str] = set()
    for vol in volume_reqs:
        req = vol.get(wk.ZONE_LABEL_KEY)
        if req.operator() != Operator.IN:
            return None
        values |= set(req.values_list())
    return Requirement(wk.ZONE_LABEL_KEY, Operator.IN, sorted(values))


def _template_compatible(template: NodeClaimTemplate, it) -> bool:
    """Instance type passes the template requirements and has an offering."""
    if it.requirements.intersects(template.requirements) is not None:
        return False
    return any(o.available and template.requirements.intersects(o.requirements) is None for o in it.offerings)


def _compute_daemon_overhead_groups(template: NodeClaimTemplate, daemonset_pods: list) -> list[DaemonOverheadGroup]:
    """Group instance types by which daemons would schedule to them
    (scheduler.go:963-1004): the daemon overhead depends on daemon
    nodeSelector/affinity/taints vs the concrete instance type."""
    groups: dict[tuple, DaemonOverheadGroup] = {}
    for it in template.instance_type_options:
        compatible: list = []
        for d in daemonset_pods:
            if _daemon_compatible_with_instance_type(template, it, d):
                compatible.append(d)
        key = tuple(sorted(id(d) for d in compatible))
        g = groups.get(key)
        if g is None:
            overhead = res.requests_for_pods(compatible)
            g = DaemonOverheadGroup(instance_types=[], daemon_overhead=overhead)
            # daemons reserve their host ports on every fresh node of this
            # group (suite_test.go:955 "should account for daemonset
            # hostports": a pod sharing the port can never schedule there)
            from ....scheduling.hostports import pod_host_ports

            for d in compatible:
                g.host_port_usage.add(d.key(), pod_host_ports(d))
            groups[key] = g
        g.instance_types.append(it)
    return list(groups.values())


def _daemon_requirement_alternatives(daemon_pod) -> list[Requirements]:
    """Node-selector + each required node-affinity OR-term — the reference
    relaxes daemons across all OR-terms (isDaemonPodCompatible,
    scheduler.go:1023-1040), so a daemon counts if ANY term matches."""
    base = Requirements.from_labels(daemon_pod.spec.node_selector)
    aff = daemon_pod.spec.affinity.node_affinity if daemon_pod.spec.affinity else None
    if aff is None or not aff.required:
        return [base]
    out = []
    for term in aff.required:
        r = base.copy()
        r.add(*Requirements.from_node_selector_terms(term).values())
        out.append(r)
    return out


def _daemon_compatible_with_instance_type(template: NodeClaimTemplate, it, daemon_pod) -> bool:
    """Requirements/taints only — the reference deliberately does NOT check
    resource fit (isDaemonPodCompatible, scheduler.go:1020-1043): an
    oversized daemon still counts as overhead, rendering the instance type
    unable to host anything (suite_test.go:1003)."""
    if taints_tolerate_pod(template.taints, daemon_pod) is not None:
        return False
    reqs = Requirements()
    reqs.add(*template.requirements.values())
    reqs.add(*it.requirements.values())
    return any(
        reqs.compatible(alt, allow_undefined=wk.WELL_KNOWN_LABELS) is None
        for alt in _daemon_requirement_alternatives(daemon_pod)
    )


def _daemon_compatible_with_node(sn, taints, daemon_pod) -> bool:
    if taints_tolerate_pod(taints, daemon_pod) is not None:
        return False
    node_reqs = Requirements.from_labels(sn.labels())
    return any(node_reqs.compatible(alt) is None for alt in _daemon_requirement_alternatives(daemon_pod))


def _filter_by_remaining_resources(its: list, remaining: dict[str, Quantity]) -> list:
    """Drop instance types that would exceed the nodepool limits; only the
    limited resource names are consulted (scheduler.go:1069-1085)."""
    out = []
    for it in its:
        if all(it.capacity.get(k, Quantity(0)).milli <= v.milli for k, v in remaining.items()):
            out.append(it)
    return out


def _subtract_max(remaining: dict[str, Quantity], its: list) -> dict[str, Quantity]:
    """Subtract the worst-case capacity of the chosen instance types, keyed by
    the limited resources (scheduler.go:1049-1066). We additionally decrement
    the synthetic "nodes" resource by 1 per in-flight claim — the reference
    gates node limits via the early IsZero check plus existing-node counting."""
    worst: dict[str, Quantity] = {}
    for it in its:
        for k, v in it.capacity.items():
            if k not in worst or v.milli > worst[k].milli:
                worst[k] = v
    out = {k: v - worst.get(k, Quantity(0)) for k, v in remaining.items()}
    if "nodes" in remaining:
        out["nodes"] = remaining["nodes"] - Quantity.parse(1)
    return out


def _is_under_consolidate_after(np, node_claim, clock) -> bool:
    """IsUnderConsolidateAfter (utils/disruption.go:80-100): node had pod churn
    more recently than consolidateAfter allows."""
    if np is None or node_claim is None:
        return False
    ca = np.spec.disruption.consolidate_after_seconds()
    if ca == 0 or ca == math.inf:
        return False
    from ....apis.nodeclaim import COND_INITIALIZED

    cond = node_claim.status.conditions.get(COND_INITIALIZED)
    if cond is None or cond.status != "True":
        return False
    base = node_claim.status.last_pod_event_time or cond.last_transition_time
    return clock.now() - base < ca
