"""The provisioning scheduler: FFD bin-packing simulation with topology,
preference relaxation, and instance-type filtering.

This is the host-side exact implementation (the reference semantics,
scheduler.go:440 Solve). The TPU tensor backend (karpenter_tpu/solver/) plugs
in at the Solver boundary and is validated against this one.
"""

from .queue import Queue  # noqa: F401
from .scheduler import Results, Scheduler  # noqa: F401
