"""Topology tracking: spread constraints, pod affinity/anti-affinity, and
inverse anti-affinity.

Reference: scheduling/topology.go:47-590, topologygroup.go, and
topologynodefilter.go. The semantics preserved exactly:

- spread: valid domains satisfy `count + self - globalMin <= maxSkew`; hostname
  is special-cased (a new node is always a fresh empty domain, global min 0).
- affinity: domains where a selected pod already runs; a self-selecting pod may
  bootstrap a fresh domain.
- anti-affinity: only empty domains are allowed, and inverse tracking blocks
  domains that contain pods whose anti-affinity selects the incoming pod.
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, Optional

from ....apis import labels as wk
from ....scheduling.requirements import Operator, Requirement, Requirements
from ....scheduling.taints import Taint, Toleration, taints_tolerate_pod
from ....kube.objects import match_label_selector
from ....utils import pods as pod_utils

TYPE_SPREAD = "topology-spread"
TYPE_AFFINITY = "pod-affinity"
TYPE_ANTI_AFFINITY = "pod-anti-affinity"

HONOR = "Honor"
IGNORE = "Ignore"


class TopologyDomainGroup:
    """Universe of domains for a topology key, each tagged with the taints of
    the NodePools providing it (topologygroup.go TopologyDomainGroup)."""

    def __init__(self):
        self._domains: dict[str, list[list[Taint]]] = {}

    def insert(self, domain: str, taints: list[Taint]) -> None:
        self._domains.setdefault(domain, []).append(list(taints))

    def for_each_domain(self, pod, taint_policy: str, fn: Callable[[str], None]) -> None:
        """Yield domains reachable by the pod: if taint_policy is Honor, at
        least one providing NodePool's taints must be tolerated."""
        for domain, taint_sets in self._domains.items():
            if taint_policy == HONOR:
                if not any(taints_tolerate_pod(ts, pod, include_prefer_no_schedule=True) is None for ts in taint_sets):
                    continue
            fn(domain)


class TopologyNodeFilter:
    """Decides whether a node participates in a spread topology
    (topologynodefilter.go:31-95)."""

    def __init__(self, requirements: list[Requirements], taint_policy: str, affinity_policy: str, tolerations: list):
        self.requirements = requirements
        self.taint_policy = taint_policy
        self.affinity_policy = affinity_policy
        self.tolerations = tolerations

    @classmethod
    def always(cls) -> "TopologyNodeFilter":
        return cls([], IGNORE, IGNORE, [])

    @classmethod
    def for_pod(cls, pod, taint_policy: str, affinity_policy: str) -> "TopologyNodeFilter":
        selector_reqs = Requirements.from_labels(pod.spec.node_selector)
        aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
        reqs_list: list[Requirements] = []
        if aff is None or not aff.required:
            reqs_list = [selector_reqs]
        else:
            for term in aff.required:  # OR'd terms
                r = Requirements()
                r.add(*selector_reqs.values())
                r.add(*Requirements.from_node_selector_terms(term).values())
                reqs_list.append(r)
        return cls(reqs_list, taint_policy, affinity_policy, pod.spec.tolerations or [])

    def matches(self, taints: Iterable[Taint], node_requirements: Requirements, allow_undefined=frozenset()) -> bool:
        ok_affinity = True
        if self.affinity_policy == HONOR and self.requirements:
            ok_affinity = any(
                node_requirements.compatible(r, allow_undefined=allow_undefined or wk.WELL_KNOWN_LABELS) is None
                for r in self.requirements
            )
        ok_taints = True
        if self.taint_policy == HONOR:
            tols = [t if isinstance(t, Toleration) else Toleration.from_dict(t) for t in self.tolerations]
            for t in taints:
                if t.effect == "PreferNoSchedule":
                    continue
                if not any(tol.tolerates(t) for tol in tols):
                    ok_taints = False
                    break
        return ok_affinity and ok_taints


class TopologyGroup:
    def __init__(
        self,
        type_: str,
        key: str,
        pod,
        namespaces: set[str],
        label_selector: Optional[dict],
        max_skew: int,
        min_domains: Optional[int],
        taint_policy: Optional[str],
        affinity_policy: Optional[str],
        domain_group: TopologyDomainGroup,
    ):
        self.type = type_
        self.key = key
        self.namespaces = namespaces
        self.selector = label_selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.owners: set[str] = set()
        if type_ == TYPE_SPREAD:
            tp = taint_policy if taint_policy is not None else IGNORE
            ap = affinity_policy if affinity_policy is not None else HONOR
            self.node_filter = TopologyNodeFilter.for_pod(pod, tp, ap)
        else:
            self.node_filter = TopologyNodeFilter.always()
        self.domains: dict[str, int] = {}
        self.empty_domains: set[str] = set()
        domain_group.for_each_domain(pod, self.node_filter.taint_policy, self._register_one)

    def _register_one(self, domain: str) -> None:
        if domain not in self.domains:
            self.domains[domain] = 0
            self.empty_domains.add(domain)

    # -- identity for dedup (topologygroup.go:188-204) -------------------------
    def hash_key(self) -> tuple:
        return (
            self.type,
            self.key,
            frozenset(self.namespaces),
            self.max_skew,
            self.min_domains,
            _selector_key(self.selector),
            self.node_filter.taint_policy,
            self.node_filter.affinity_policy,
            # full node-filter identity: requirement values/operators/bounds and
            # tolerations, not just keys — distinct filters must not dedupe
            tuple(
                tuple(sorted((r.key, r.complement, frozenset(r.values), r.gte, r.lte) for r in reqs.values()))
                for reqs in self.node_filter.requirements
            ),
            tuple(sorted(repr(t) for t in self.node_filter.tolerations)),
        )

    # -- ownership -------------------------------------------------------------
    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    # -- counting --------------------------------------------------------------
    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1
            self.empty_domains.discard(d)

    def register(self, *domains: str) -> None:
        for d in domains:
            if d not in self.domains:
                self.domains[d] = 0
                self.empty_domains.add(d)

    def unregister(self, *domains: str) -> None:
        for d in domains:
            self.domains.pop(d, None)
            self.empty_domains.discard(d)

    def selects(self, pod) -> bool:
        return pod.metadata.namespace in self.namespaces and (
            self.selector is not None and match_label_selector(self.selector, pod.metadata.labels)
        )

    def counts(self, pod, taints, requirements: Requirements) -> bool:
        return self.selects(pod) and self.node_filter.matches(taints, requirements)

    # -- the heart: next viable domain (topologygroup.go:128-440) --------------
    def get(self, pod, pod_domains: Requirement, node_domains: Requirement) -> tuple[Requirement, set[str]]:
        if self.type == TYPE_SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TYPE_AFFINITY:
            req = self._next_domain_affinity(pod, pod_domains, node_domains)
            return req, set(req.values)
        req = self._next_domain_anti_affinity(pod_domains, node_domains)
        return req, set(req.values)

    def _next_domain_spread(self, pod, pod_domains: Requirement, node_domains: Requirement) -> tuple[Requirement, set[str]]:
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        valid: set[str] = set()

        # hostname special case: a new NodeClaim is always a fresh domain
        if self.key == wk.HOSTNAME_LABEL_KEY and len(node_domains.values) == 1:
            hostname = next(iter(node_domains.values))
            count = self.domains.get(hostname, 0) + (1 if self_selecting else 0)
            if count <= self.max_skew:
                valid.add(hostname)
                return Requirement(self.key, Operator.IN, [hostname]), valid
            return Requirement(self.key, Operator.DOES_NOT_EXIST), valid

        best_domain, best_count = None, math.inf
        candidates = (
            [d for d in node_domains.values if d in self.domains]
            if node_domains.operator() == Operator.IN
            else [d for d in self.domains if node_domains.has(d)]
        )
        for domain in candidates:
            count = self.domains[domain] + (1 if self_selecting else 0)
            if count - min_count <= self.max_skew:
                valid.add(domain)
                if count < best_count:
                    best_domain, best_count = domain, count
        if best_domain is None:
            return Requirement(self.key, Operator.DOES_NOT_EXIST), valid
        return Requirement(self.key, Operator.IN, [best_domain]), valid

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        if self.key == wk.HOSTNAME_LABEL_KEY:
            return 0  # we can always create a new hostname domain
        min_count = math.inf
        supported = 0
        for domain, count in self.domains.items():
            if pod_domains.has(domain):
                supported += 1
                min_count = min(min_count, count)
        if self.min_domains is not None and supported < self.min_domains:
            min_count = 0
        return 0 if min_count is math.inf else min_count

    def _next_domain_affinity(self, pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        options = Requirement(self.key, Operator.DOES_NOT_EXIST)

        if self.key == wk.HOSTNAME_LABEL_KEY and len(node_domains.values) == 1:
            hostname = next(iter(node_domains.values))
            if not pod_domains.has(hostname):
                return options
            if self.domains.get(hostname, 0) > 0:
                options.insert(hostname)
                return options
            if self.selects(pod) and (len(self.domains) == len(self.empty_domains) or not self._any_compatible_pod_domain(pod_domains)):
                options.insert(hostname)
            return options

        candidates = (
            [d for d in node_domains.values if d in self.domains]
            if node_domains.operator() == Operator.IN
            else [d for d in self.domains if node_domains.has(d)]
        )
        for domain in candidates:
            if pod_domains.has(domain) and self.domains.get(domain, 0) > 0:
                options.insert(domain)
        if len(options.values) != 0:
            return options

        # bootstrap: self-selecting pod and no compatible scheduled pods yet
        if self.selects(pod) and (len(self.domains) == len(self.empty_domains) or not self._any_compatible_pod_domain(pod_domains)):
            for domain in self.domains:
                if pod_domains.has(domain) and node_domains.has(domain):
                    options.insert(domain)
                    break
            if len(options.values) == 0:
                for domain in self.domains:
                    if pod_domains.has(domain):
                        options.insert(domain)
                        break
        return options

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(pod_domains.has(d) and c > 0 for d, c in self.domains.items())

    def _next_domain_anti_affinity(self, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        options = Requirement(self.key, Operator.DOES_NOT_EXIST)
        if self.key == wk.HOSTNAME_LABEL_KEY and len(node_domains.values) == 1:
            hostname = next(iter(node_domains.values))
            if self.domains.get(hostname, 0) == 0:
                options.insert(hostname)
            return options
        if node_domains.operator() == Operator.IN and len(node_domains.values) < len(self.empty_domains):
            for domain in node_domains.values:
                if domain in self.empty_domains and pod_domains.has(domain):
                    options.insert(domain)
        else:
            for domain in self.empty_domains:
                if node_domains.has(domain) and pod_domains.has(domain):
                    options.insert(domain)
        return options


def effective_spread_selector(pod, tsc) -> Optional[dict]:
    """The spread constraint's selector with the pod's values for every
    matchLabelKeys entry merged in as In-expressions (topology.go:467-475);
    keys absent from the pod's labels are ignored."""
    sel = tsc.label_selector
    if sel is None:
        return None  # nil selector matches nothing; matchLabelKeys can't revive it
    keys = [k for k in (getattr(tsc, "match_label_keys", None) or []) if k in pod.metadata.labels]
    if not keys:
        return sel
    merged = {
        "matchLabels": dict((sel or {}).get("matchLabels") or {}),
        "matchExpressions": list((sel or {}).get("matchExpressions") or []),
    }
    for k in keys:
        merged["matchExpressions"].append({"key": k, "operator": "In", "values": [pod.metadata.labels[k]]})
    return merged


def _selector_key(selector: Optional[dict]):
    if selector is None:
        return None
    ml = tuple(sorted((selector.get("matchLabels") or {}).items()))
    me = tuple(
        sorted(
            (e["key"], e["operator"], tuple(sorted(e.get("values", []))))
            for e in (selector.get("matchExpressions") or [])
        )
    )
    return (ml, me)


# domain-group construction iterates every (NodePool x InstanceType x
# requirement key) — ~1e3 Requirements builds — yet its inputs change only
# when a NodePool template or the instance-type catalog does. Cached across
# Scheduler builds (each consolidation simulation builds one) keyed on
# template content + catalog list identity; groups are read-only after build
# (insert happens only inside _build_domain_groups).
_DOMAIN_GROUPS_CACHE: dict = {}

# whether a node participates in a spread topology depends only on the group's
# filter identity and the node's content — memoized across the many Topology
# instances the consolidation loop builds per round (one per simulation)
_NODE_MATCH_CACHE: dict = {}


def _node_filter_matches_cached(tg, tg_hash: tuple, node, scope) -> bool:
    # `scope` is the owning Cluster's process-unique epoch: (name, rv) pairs
    # repeat across Environments in one process, so verdicts must not leak
    # between stores
    key = (scope, tg_hash, node.metadata.name, node.metadata.resource_version)
    hit = _NODE_MATCH_CACHE.get(key)
    if hit is None:
        if len(_NODE_MATCH_CACHE) > 200_000:
            _NODE_MATCH_CACHE.clear()
        hit = _NODE_MATCH_CACHE[key] = tg.node_filter.matches(
            node.spec.taints, Requirements.from_labels_view(node.metadata.labels)
        )
    return hit


def _nodepool_template_fingerprint(np) -> tuple:
    t = np.spec.template
    return (
        np.metadata.name,
        repr(t.requirements),
        repr(t.labels),
        tuple(t.taints),
    )


def _domain_groups_cached(node_pools, instance_types: dict[str, list]) -> dict:
    key = tuple(sorted(_nodepool_template_fingerprint(np) for np in node_pools))
    entry = _DOMAIN_GROUPS_CACHE.get(key)
    if entry is not None:
        cached_its, groups = entry
        if len(cached_its) == len(instance_types) and all(
            cached_its.get(name) is its for name, its in instance_types.items()
        ):
            return groups
    groups = Topology._build_domain_groups(node_pools, instance_types)
    if len(_DOMAIN_GROUPS_CACHE) > 8:
        _DOMAIN_GROUPS_CACHE.clear()
    _DOMAIN_GROUPS_CACHE[key] = (dict(instance_types), groups)
    return groups


class Topology:
    """The per-solve topology state (topology.go:47-103)."""

    def __init__(
        self,
        store,
        cluster,
        state_nodes: list,
        node_pools: list,
        instance_types: dict[str, list],
        pods: list,
        preference_policy: str = "Respect",
    ):
        self.store = store
        self.cluster = cluster
        self.state_nodes = state_nodes
        self.preference_policy = preference_policy
        self.topology_groups: dict[tuple, TopologyGroup] = {}
        self.inverse_topology_groups: dict[tuple, TopologyGroup] = {}
        self.domain_groups = _domain_groups_cached(node_pools, instance_types)
        self.excluded_pods: set[str] = set()
        self._prepared = False
        # record() memo: (namespace, labels) -> (n_groups stamp, groups whose
        # selector selects such pods). Every add used to scan ALL topology
        # groups per recorded pod; deployment replicas share (ns, labels), so
        # the selector scan runs once per distinct shape. The group-count
        # stamp invalidates entries when prepare()/update() registers new
        # groups mid-solve (groups are never removed within a solve).
        self._record_memo: dict[tuple, tuple[int, list]] = {}
        if pods:
            self.prepare(pods)

    def prepare(self, pods: list) -> None:
        """Exclude the solve pods from counting BEFORE recording inverse
        anti-affinity domains (topology.go:91-103 order), then build each
        pod's topology groups. Must run exactly once per solve."""
        self.excluded_pods.update(p.metadata.uid for p in pods)
        if not self._prepared:
            self._update_inverse_affinities()
            self._prepared = True
        for p in pods:
            self.update(p)

    def get_topology_zone_constraints(self, pod, pod_requirements: Requirements) -> tuple[set | None, bool]:
        """Valid zones intersected across every zone-keyed topology group
        owning the pod, plus whether they are satisfiable; None means no zone
        topology constrains the pod (topology.go:250-281
        GetTopologyZoneConstraints)."""
        result: set | None = None
        for tg in self.topology_groups.values():
            if not tg.is_owned_by(pod.metadata.uid) or tg.key != wk.ZONE_LABEL_KEY:
                continue
            pod_domains = Requirement(tg.key, Operator.EXISTS)
            if pod_requirements.has(tg.key):
                pod_domains = pod_requirements.get(tg.key)
            node_domains = Requirement(tg.key, Operator.EXISTS)
            _, valid = tg.get(pod, pod_domains, node_domains)
            if not valid:
                return None, False
            result = set(valid) if result is None else result & valid
        return result, True

    # -- construction ----------------------------------------------------------
    @staticmethod
    def _build_domain_groups(node_pools, instance_types: dict[str, list]) -> dict[str, TopologyDomainGroup]:
        """Universe of domains per key from NodePool x InstanceType requirements
        (topology.go:105-143). NodePool requirements narrow instance domains."""
        groups: dict[str, TopologyDomainGroup] = {}
        by_name = {np.metadata.name: np for np in node_pools}
        for np_name, its in instance_types.items():
            np = by_name.get(np_name)
            if np is None:
                continue
            np_taints = np.spec.template.taints
            base = Requirements.from_node_selector_terms(np.spec.template.requirements)
            base.add(*Requirements.from_labels(np.spec.template.labels).values())
            for it in its:
                reqs = base.copy()
                reqs.add(*it.requirements.values())
                for key, requirement in reqs.items():
                    if requirement.operator() == Operator.IN:
                        g = groups.setdefault(key, TopologyDomainGroup())
                        for domain in requirement.values:
                            g.insert(domain, np_taints)
            for key, requirement in base.items():
                if requirement.operator() == Operator.IN:
                    g = groups.setdefault(key, TopologyDomainGroup())
                    for domain in requirement.values:
                        g.insert(domain, np_taints)
        return groups

    # -- update on pod add/relax (topology.go:361-425) -------------------------
    def update(self, pod) -> None:
        for tg in self.topology_groups.values():
            tg.remove_owner(pod.metadata.uid)

        aff = pod.spec.affinity
        has_required_anti = aff is not None and bool(aff.pod_anti_affinity_required)
        has_any_anti = aff is not None and (bool(aff.pod_anti_affinity_required) or bool(aff.pod_anti_affinity_preferred))
        if (self.preference_policy == "Ignore" and has_required_anti) or (self.preference_policy == "Respect" and has_any_anti):
            self._update_inverse_anti_affinity(pod, None)

        for tg in self._new_for_topologies(pod) + self._new_for_affinities(pod):
            h = tg.hash_key()
            existing = self.topology_groups.get(h)
            if existing is None:
                self._count_domains(tg)
                self.topology_groups[h] = tg
            else:
                tg = existing
            tg.add_owner(pod.metadata.uid)

    def _new_for_topologies(self, pod) -> list[TopologyGroup]:
        out = []
        for tsc in pod.spec.topology_spread_constraints:
            if self.preference_policy == "Ignore" and tsc.when_unsatisfiable != "DoNotSchedule":
                continue
            out.append(
                TopologyGroup(
                    TYPE_SPREAD,
                    tsc.topology_key,
                    pod,
                    {pod.metadata.namespace},
                    effective_spread_selector(pod, tsc),
                    tsc.max_skew,
                    tsc.min_domains,
                    tsc.node_taints_policy,
                    tsc.node_affinity_policy,
                    self.domain_groups.get(tsc.topology_key, TopologyDomainGroup()),
                )
            )
        return out

    def _new_for_affinities(self, pod) -> list[TopologyGroup]:
        out = []
        aff = pod.spec.affinity
        if aff is None:
            return out
        terms: list[tuple[str, object]] = []
        for t in aff.pod_affinity_required:
            terms.append((TYPE_AFFINITY, t))
        for t in aff.pod_anti_affinity_required:
            terms.append((TYPE_ANTI_AFFINITY, t))
        if self.preference_policy == "Respect":
            for wt in aff.pod_affinity_preferred:
                terms.append((TYPE_AFFINITY, wt.term))
            for wt in aff.pod_anti_affinity_preferred:
                terms.append((TYPE_ANTI_AFFINITY, wt.term))
        for type_, term in terms:
            out.append(
                TopologyGroup(
                    type_,
                    term.topology_key,
                    pod,
                    self._namespaces_for_term(pod, term),
                    term.label_selector,
                    2**31 - 1,
                    None,
                    None,
                    None,
                    self.domain_groups.get(term.topology_key, TopologyDomainGroup()),
                )
            )
        return out

    def _namespaces_for_term(self, pod, term) -> set[str]:
        from ....utils.pods import term_namespaces

        # empty selector matches all namespaces; approximated with the
        # namespaces of current pods plus the pod's own (shared helper keeps
        # the Binder's term scoping identical)
        return term_namespaces(
            pod, term, lambda: (p.metadata.namespace for p in self.store.borrow_list("Pod"))
        )

    def _update_inverse_affinities(self) -> None:
        for pod in self.cluster.pods_with_anti_affinity():
            if pod.metadata.uid in self.excluded_pods:
                continue
            node = self.store.borrow_get("Node", pod.spec.node_name) if pod.spec.node_name else None
            self._update_inverse_anti_affinity(pod, node.metadata.labels if node else None)

    def _update_inverse_anti_affinity(self, pod, node_labels: Optional[dict]) -> None:
        """Track pods with anti-affinity so incoming pods they select can't land
        in their domains (topology.go:476-508)."""
        aff = pod.spec.affinity
        for term in aff.pod_anti_affinity_required:
            tg = TopologyGroup(
                TYPE_ANTI_AFFINITY,
                term.topology_key,
                pod,
                self._namespaces_for_term(pod, term),
                term.label_selector,
                2**31 - 1,
                None,
                None,
                None,
                self.domain_groups.get(term.topology_key, TopologyDomainGroup()),
            )
            h = tg.hash_key()
            existing = self.inverse_topology_groups.get(h)
            if existing is None:
                self.inverse_topology_groups[h] = tg
            else:
                tg = existing
            if node_labels and tg.key in node_labels:
                tg.record(node_labels[tg.key])
            tg.add_owner(pod.metadata.uid)

    def _count_domains(self, tg: TopologyGroup) -> None:
        """Initialize counts from existing scheduled pods (topology.go:361-459)."""
        tg_hash = tg.hash_key()
        scope = getattr(self.cluster, "epoch", None) or id(self.store)
        for n in self.state_nodes:
            if n.node is None:
                continue
            if not _node_filter_matches_cached(tg, tg_hash, n.node, scope):
                continue
            domain = n.labels().get(tg.key)
            if domain is not None:
                tg.register(domain)

        if tg.selector is None:
            return  # nil selector matches no pods (labels.Nothing()), but node
            # domains above are still registered
        node_cache: dict[str, object] = {}
        for ns in tg.namespaces:
            # borrowed reads: pure counting over the informer-cache view
            for pod in self.store.borrow_list("Pod", namespace=ns, label_selector=tg.selector):
                if not pod.spec.node_name or pod.metadata.uid in self.excluded_pods:
                    continue
                if ignored_for_topology(pod):
                    continue
                node = node_cache.get(pod.spec.node_name)
                if node is None:
                    node = self.store.borrow_get("Node", pod.spec.node_name)
                    if node is None:
                        continue
                    node_cache[pod.spec.node_name] = node
                domain = node.metadata.labels.get(tg.key)
                if domain is None and tg.key == wk.HOSTNAME_LABEL_KEY:
                    domain = node.metadata.name
                if domain is None:
                    continue
                if not _node_filter_matches_cached(tg, tg_hash, node, scope):
                    continue
                tg.record(domain)

    # -- solve-time interface (topology.go:222-270) ----------------------------
    def add_requirements(
        self, pod, taints, pod_requirements: Requirements, node_requirements: Requirements, allow_undefined=frozenset()
    ) -> Requirements | str:
        """Tighten node requirements with per-topology viable domains; returns
        the tightened Requirements or an error string."""
        out = Requirements()
        out.add(*node_requirements.values())
        for tg in self._matching_topologies(pod, taints, node_requirements):
            pod_domains = pod_requirements.get(tg.key)
            node_domains = node_requirements.get(tg.key)
            domains, _ = tg.get(pod, pod_domains, node_domains)
            if len(domains) == 0:
                return f"unsatisfiable topology constraint for {tg.type}, key={tg.key}"
            out.add(domains)
        return out

    def record(self, pod, taints, requirements: Requirements) -> None:
        # per-(namespace, labels) memo of the groups that SELECT this pod
        # shape (counts() = selects() AND node_filter.matches(); the selector
        # half is signature-stable, the node-filter half depends on the
        # placement and re-evaluates per call)
        md = pod.metadata
        key = (md.namespace, tuple(sorted(md.labels.items())) if md.labels else ())
        entry = self._record_memo.get(key)
        if entry is None or entry[0] != len(self.topology_groups):
            entry = (
                len(self.topology_groups),
                [tg for tg in self.topology_groups.values() if tg.selects(pod)],
            )
            self._record_memo[key] = entry
        for tg in entry[1]:
            if tg.node_filter.matches(taints, requirements):
                domains = requirements.get(tg.key)
                if tg.type == TYPE_ANTI_AFFINITY:
                    tg.record(*domains.values)
                elif domains.operator() == Operator.IN and len(domains.values) == 1:
                    tg.record(next(iter(domains.values)))
        for tg in self.inverse_topology_groups.values():
            if tg.is_owned_by(pod.metadata.uid):
                tg.record(*requirements.get(tg.key).values)

    def register(self, key: str, domain: str) -> None:
        for tg in list(self.topology_groups.values()) + list(self.inverse_topology_groups.values()):
            if tg.key == key:
                tg.register(domain)

    def _matching_topologies(self, pod, taints, requirements: Requirements) -> list[TopologyGroup]:
        out = [tg for tg in self.topology_groups.values() if tg.is_owned_by(pod.metadata.uid)]
        out += [tg for tg in self.inverse_topology_groups.values() if tg.counts(pod, taints, requirements)]
        return out


def ignored_for_topology(pod) -> bool:
    return pod_utils.is_terminal(pod) or pod_utils.is_terminating(pod)
