"""Volume topology: PVC-derived node requirements for scheduling.

Reference: provisioning/scheduling/volumetopology.go — a pod's PVCs constrain
where it can run (a bound PV's node affinity, or a StorageClass's
AllowedTopologies for unbound WaitForFirstConsumer claims). Each OR'd term
becomes one *alternative* Requirements; for multi-volume pods the cross
product of per-volume alternatives is taken, preferring branches whose
requirements intersect (volumetopology.go:92-125).

The alternatives attach to node/claim requirements only — never to the pod's
own affinity — so topology-spread counting still uses the pod's original
constraints (volumetopology.go:62-64).
"""

from __future__ import annotations

from ....apis import labels as wk
from ....scheduling.requirements import Requirements
from ....scheduling.volumeusage import (
    BIND_COMPLETED_ANNOTATION,
    effective_storage_class_name,
    get_persistent_volume_claim,
    resolve_driver,
)

# Volume plugins / topology keys Karpenter cannot satisfy; pods referencing
# them are skipped (volumetopology.go:39-46).
UNSUPPORTED_PROVISIONERS: set[str] = set()
UNSUPPORTED_TOPOLOGY_KEYS: set[str] = set()


class VolumeTopology:
    def __init__(self, store):
        self.store = store

    def get_requirements(self, pod) -> list[Requirements]:
        """Volume topology requirement alternatives for the pod; empty list =
        unconstrained (volumetopology.go:65-90)."""
        alternatives: list = [None]
        for volume in pod.spec.volumes:
            vol_alts = self._volume_requirements(pod, volume)
            if not vol_alts:
                continue
            alternatives = _merge_alternatives(alternatives, vol_alts)
        if len(alternatives) == 1 and alternatives[0] is None:
            return []
        return [a if a is not None else Requirements() for a in alternatives]

    def _volume_requirements(self, pod, volume: dict) -> list[Requirements]:
        pvc, _ = get_persistent_volume_claim(self.store, pod, volume)
        if pvc is None:
            return []
        if pvc.volume_name:
            return self._persistent_volume_requirements(pvc.volume_name)
        sc_name = effective_storage_class_name(self.store, pvc)
        if sc_name:
            return self._storage_class_requirements(sc_name)
        return []

    def _storage_class_requirements(self, storage_class_name: str) -> list[Requirements]:
        """Each AllowedTopologies term is OR'd -> one alternative each
        (volumetopology.go:172-189)."""
        sc = self.store.try_get("StorageClass", storage_class_name)
        if sc is None:
            return []
        alternatives = []
        for term in sc.allowed_topologies:
            exprs = [{"key": e["key"], "operator": "In", "values": e.get("values", [])} for e in term]
            if exprs:
                alternatives.append(Requirements.from_node_selector_terms(exprs))
        return alternatives

    def _persistent_volume_requirements(self, volume_name: str) -> list[Requirements]:
        """Each PV nodeSelectorTerm is OR'd -> one alternative each; hostname
        affinity on Local/HostPath volumes is ignored since a replacement node
        can never carry the old hostname (volumetopology.go:191-222)."""
        pv = self.store.try_get("PersistentVolume", volume_name)
        if pv is None or not pv.node_affinity_required:
            return []
        alternatives = []
        for term in pv.node_affinity_required:
            exprs = term
            if pv.local or pv.host_path:
                exprs = [e for e in term if e.get("key") != wk.HOSTNAME_LABEL_KEY]
                if term and not exprs:
                    # hostname-only terms become unconstrained alternatives
                    alternatives.append(Requirements())
                    continue
            if exprs:
                alternatives.append(Requirements.from_node_selector_terms(exprs))
        return alternatives

    def validate_persistent_volume_claims(self, pod) -> str | None:
        """Pre-scheduling PVC validation mirroring what kube-scheduler rejects
        (volumetopology.go:227-289). Returns an error string to skip the pod."""
        for volume in pod.spec.volumes:
            pvc, _ = get_persistent_volume_claim(self.store, pod, volume)
            if pvc is None:
                # a named claim that doesn't exist (vs. a non-PVC volume type)
                # blocks scheduling
                name = (volume.get("persistentVolumeClaim") or {}).get("claimName")
                if name:
                    return f"persistentvolumeclaim {name} not found"
                continue
            if pvc.metadata.deletion_timestamp is not None:
                return f"persistentvolumeclaim {pvc.key()} is being deleted"
            if pvc.phase == "Lost":
                return f"persistentvolumeclaim {pvc.key()} bound to non-existent persistentvolume"
            if pvc.volume_name:
                err = self._validate_volume(pvc.volume_name)
                if err is not None:
                    return err
                # bound-with-volumeName claims must carry the bind-completed
                # annotation to count as bound (volumetopology.go:250-255)
                if BIND_COMPLETED_ANNOTATION not in pvc.metadata.annotations:
                    return f"pvc {pvc.key()} is considered unbound, missing {BIND_COMPLETED_ANNOTATION}"
            else:
                sc_name = effective_storage_class_name(self.store, pvc)
                if not sc_name:
                    return f"unbound pvc {pvc.key()} must define a storage class"
                sc = self.store.try_get("StorageClass", sc_name)
                if sc is None:
                    return f"storage class {sc_name} not found"
                if sc.volume_binding_mode == "Immediate":
                    return f"pvc {pvc.key()} with immediate volume binding mode must be bound"
                for term in sc.allowed_topologies:
                    for expr in term:
                        if expr.get("key") in UNSUPPORTED_TOPOLOGY_KEYS:
                            return f"storage class {sc.metadata.name} uses unsupported topology key {expr.get('key')}"
            driver = resolve_driver(self.store, pvc)
            if driver in UNSUPPORTED_PROVISIONERS:
                return f"provisioner {driver} is not supported"
        return None

    def _validate_volume(self, volume_name: str) -> str | None:
        pv = self.store.try_get("PersistentVolume", volume_name)
        if pv is None:
            return f"persistentvolume {volume_name} not found"
        if pv.metadata.deletion_timestamp is not None:
            return f"persistentvolume {volume_name} is being deleted"
        return None


def _merge_alternatives(alternatives: list, vol_alts: list) -> list:
    """Cross-product preferring compatible branches; fall back to the full
    product when every branch conflicts (volumetopology.go:92-125)."""
    compatible = [
        _merge_pair(existing, vol)
        for existing in alternatives
        for vol in vol_alts
        if _pair_compatible(existing, vol)
    ]
    if compatible:
        return compatible
    return [_merge_pair(existing, vol) for existing in alternatives for vol in vol_alts]


def _pair_compatible(existing, vol) -> bool:
    if existing is None or vol is None:
        return True
    return existing.intersects(vol) is None


def _merge_pair(existing, vol) -> Requirements:
    merged = Requirements()
    if existing is not None:
        merged.add(*existing.values())
    if vol is not None:
        merged.add(*vol.values())
    return merged
