"""Preference relaxation (reference: scheduling/preferences.go:30-140).

When a pod fails to schedule, soft constraints are peeled off one per attempt,
in order: required node-affinity OR-terms (beyond the first), preferred pod
affinity, preferred pod anti-affinity, preferred node affinity, ScheduleAnyway
topology spreads, and optionally PreferNoSchedule tolerations.
"""

from __future__ import annotations

from ....scheduling.taints import PREFER_NO_SCHEDULE, Toleration


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod) -> bool:
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity,
            self._remove_preferred_pod_anti_affinity,
            self._remove_preferred_node_affinity,
            self._remove_schedule_anyway_spread,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule)
        for fn in relaxations:
            if fn(pod):
                return True
        return False

    @staticmethod
    def _remove_required_node_affinity_term(pod) -> bool:
        aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
        if aff is None or len(aff.required) <= 1:
            return False  # OR-terms: can drop all but the last
        aff.required = aff.required[1:]
        return True

    @staticmethod
    def _remove_preferred_node_affinity(pod) -> bool:
        aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
        if aff is None or not aff.preferred:
            return False
        aff.preferred = sorted(aff.preferred, key=lambda t: -t.weight)[1:]
        return True

    @staticmethod
    def _remove_preferred_pod_affinity(pod) -> bool:
        aff = pod.spec.affinity
        if aff is None or not aff.pod_affinity_preferred:
            return False
        aff.pod_affinity_preferred = sorted(aff.pod_affinity_preferred, key=lambda t: -t.weight)[1:]
        return True

    @staticmethod
    def _remove_preferred_pod_anti_affinity(pod) -> bool:
        aff = pod.spec.affinity
        if aff is None or not aff.pod_anti_affinity_preferred:
            return False
        aff.pod_anti_affinity_preferred = sorted(aff.pod_anti_affinity_preferred, key=lambda t: -t.weight)[1:]
        return True

    @staticmethod
    def _remove_schedule_anyway_spread(pod) -> bool:
        for i, tsc in enumerate(pod.spec.topology_spread_constraints):
            if tsc.when_unsatisfiable == "ScheduleAnyway":
                pod.spec.topology_spread_constraints.pop(i)
                return True
        return False

    @staticmethod
    def _tolerate_prefer_no_schedule(pod) -> bool:
        tol = Toleration(operator="Exists", effect=PREFER_NO_SCHEDULE)
        existing = [t if isinstance(t, Toleration) else Toleration.from_dict(t) for t in pod.spec.tolerations or []]
        if any(t.operator == "Exists" and t.effect == PREFER_NO_SCHEDULE and not t.key for t in existing):
            return False
        pod.spec.tolerations = list(pod.spec.tolerations or []) + [tol]
        return True
