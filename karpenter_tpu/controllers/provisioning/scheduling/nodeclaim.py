"""In-flight scheduling NodeClaim: template, CanAdd, instance-type filtering.

Reference: scheduling/nodeclaim.go (CanAdd :124-208, filterInstanceTypes
:541-640, FinalizeScheduling :383-409) and nodeclaimtemplate.go (requirement
assembly, MaxInstanceTypes truncation, capacity-type narrowing).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from ....apis import labels as wk
from ....apis.nodeclaim import NodeClaim as APINodeClaim
from ....apis.nodeclaim import NodeClaimSpec, NodeClassReference
from ....cloudprovider.types import InstanceType, order_by_price
from ....kube.objects import ObjectMeta
from ....scheduling.hostports import HostPortUsage, pod_host_ports
from ....scheduling.requirements import Operator, Requirement, Requirements
from ....scheduling.taints import taints_tolerate_pod
from ....utils import resources as res
from ....utils.durations import parse_duration
from ....utils.quantity import Quantity

MAX_INSTANCE_TYPES = 600

_hostname_seq = itertools.count(1)

# native requirements-intersection tables, one per NodeClaimTemplate per solve
# (weak-keyed so solves don't leak tables; falls back to the Python algebra
# when the C++ kernel isn't available — karpenter_tpu/native)
import weakref

_native_tables: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


# Below this many instance types the Python set algebra's short-circuiting
# beats the per-call ctypes query lowering (measured ~0.8x at 36 rows, ~1.0x
# at 500 simple rows, 15x isolated on requirement-heavy tables)
NATIVE_MIN_TABLE_ROWS = 200


def _native_table_for(template):
    from ....native import ReqTable, UnsupportedRequirements, available

    its = template.instance_type_options
    if len(its) < NATIVE_MIN_TABLE_ROWS or not available():
        return None
    cached = _native_tables.get(template)
    if cached is None:
        try:
            cached = (ReqTable([it.requirements for it in its]), {id(it): i for i, it in enumerate(its)})
        except UnsupportedRequirements:
            cached = (None, None)  # e.g. >int64 integer values; stay on Python
        _native_tables[template] = cached
    return cached if cached[0] is not None else None


@dataclass
class DaemonOverheadGroup:
    """Instance types sharing a daemon-compatibility class and hence the same
    daemon overhead (scheduler.go:963-1004)."""

    instance_types: list[InstanceType]
    daemon_overhead: dict[str, Quantity]
    host_port_usage: HostPortUsage = field(default_factory=HostPortUsage)

    def copy(self) -> "DaemonOverheadGroup":
        return DaemonOverheadGroup(self.instance_types, self.daemon_overhead, self.host_port_usage.copy())


class NodeClaimTemplate:
    """Scheduling view of a NodePool's NodeClaim template
    (nodeclaimtemplate.go:55-95)."""

    def __init__(self, node_pool):
        self.node_pool = node_pool
        self.nodepool_name = node_pool.metadata.name
        self.weight = node_pool.spec.weight
        self.is_static = node_pool.is_static()
        self.labels = dict(node_pool.spec.template.labels)
        self.labels[wk.NODEPOOL_LABEL_KEY] = node_pool.metadata.name
        self.annotations = dict(node_pool.spec.template.annotations)
        self.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = node_pool.hash()
        # both hash AND hash-version propagate to claims (nodeclaimtemplate.go);
        # static drift only compares hashes under matching versions
        from ...nodepool.hash import NODEPOOL_HASH_VERSION

        self.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = NODEPOOL_HASH_VERSION
        self.taints = list(node_pool.spec.template.taints)
        self.startup_taints = list(node_pool.spec.template.startup_taints)
        self.instance_type_options: list[InstanceType] = []
        self.requirements = Requirements()
        self.requirements.add(*Requirements.from_node_selector_terms(node_pool.spec.template.requirements).values())
        self.requirements.add(*Requirements.from_labels(self.labels).values())
        # simulation-only keys so DaemonSets with affinity on them count
        self.requirements.add(Requirement(wk.NODE_REGISTERED_LABEL_KEY, "In", ["true"]))
        self.requirements.add(Requirement(wk.NODE_INITIALIZED_LABEL_KEY, "In", ["true"]))


class SchedulingNodeClaim:
    """A NodeClaim being built up during a single Solve
    (scheduling/nodeclaim.go:52-120)."""

    def __init__(
        self,
        template: NodeClaimTemplate,
        topology,
        daemon_overhead_groups: list[DaemonOverheadGroup],
        instance_types: list[InstanceType],
        allocator=None,
        reservation_manager=None,
        reserved_offering_mode: str = "fallback",  # fallback | strict (scheduler.go:59-77)
        filter_cache: Optional[dict] = None,  # solve-scoped filter_instance_types memo
    ):
        self.template = template
        self.topology = topology
        self.filter_cache = filter_cache
        self.daemon_overhead_groups = [g.copy() for g in daemon_overhead_groups]
        self.pods: list = []
        self.instance_type_options = instance_types
        self.allocator = allocator  # DRA; None when the gate is off
        self.dra_trackers: dict = {}  # instance type name -> AllocationTracker
        self._pending_dra = None  # {it name: AllocationResult} awaiting add()
        self._pending_dra_meta = None  # {claim key: ClaimAllocationMetadata}
        self._dra_claim_keys: set = set()  # claims committed on this node
        # reserved-offering accounting (nodeclaim.go:43-62): the claim tracks
        # the reserved offerings it currently holds so stale ones release on
        # later narrowing and compatible ones can re-expand across iterations
        self.reservation_manager = reservation_manager
        self.reserved_offering_mode = reserved_offering_mode
        self.reserved_offerings: list = []
        self._pending_reserved: list = []
        self.requirements = Requirements()
        self.requirements.add(*template.requirements.values())
        self.hostname = f"hostname-placeholder-{next(_hostname_seq):05d}"
        self.requirements.add(Requirement(wk.HOSTNAME_LABEL_KEY, "In", [self.hostname]))
        topology.register(wk.HOSTNAME_LABEL_KEY, self.hostname)
        self.spec_requests: dict[str, Quantity] = {}  # accumulated pod requests
        # monotone state version: bumped on every add(); the scheduler's fit
        # memo stamps static-pass entries with it so a stale pass is recomputed
        # after this claim's options narrow or its requirements tighten
        self._version = 0

    @property
    def nodepool_name(self) -> str:
        return self.template.nodepool_name

    def rehydrate(self, topology, allocator=None, reservation_manager=None, reserved_offering_mode: str = "fallback", filter_cache: Optional[dict] = None) -> None:
        """Re-wire the solve-scoped plumbing `__init__` normally provides, for
        claims built OUTSIDE a Scheduler: the tensor decode constructs claims
        with `__new__` (the device result fully determines them), and the
        hybrid residual solve then adopts them as live in-flight claims. The
        field list lives here, next to `__init__`, so new per-solve state
        cannot be missed on the adoption path (solver/ffd.py _adopt_claim)."""
        self.topology = topology
        self.filter_cache = filter_cache
        # decode shares one group list per template across claims (and across
        # solves via its cache); Add() mutates group port usage, so a live
        # claim needs its own copies — exactly like __init__
        self.daemon_overhead_groups = [g.copy() for g in self.daemon_overhead_groups]
        self.allocator = allocator
        self.dra_trackers = {}
        self._pending_dra = None
        self._pending_dra_meta = None
        self._dra_claim_keys = set()
        self.reservation_manager = reservation_manager
        self.reserved_offering_mode = reserved_offering_mode
        self.reserved_offerings = getattr(self, "reserved_offerings", [])
        self._pending_reserved = []
        self._version = 0

    def can_add(self, pod, pod_data, relax_min_values: bool = False):
        """Returns (updated_requirements, remaining_instance_types) or an error
        string (nodeclaim.go:124-158)."""
        base, err = self.can_add_static(pod, pod_data)
        if err is not None:
            return None, None, err
        reqs, its, err, _permanent = self.can_add_dynamic(pod, pod_data, base, relax_min_values)
        return reqs, its, err

    def can_add_static(self, pod, pod_data):
        """The MONOTONE prefix of can_add: template taints (fixed for the
        whole solve) and requirements compatibility (this claim's requirements
        only ever tighten — add() intersects). A rejection here can never turn
        into an acceptance later, so the scheduler's fit memo caches it
        permanently per pod signature. Returns (base_requirements, None) or
        (None, err)."""
        err = taints_tolerate_pod(self.template.taints, pod, include_prefer_no_schedule=True)
        if err is not None:
            return None, err

        base = Requirements()
        base.add(*self.requirements.values())
        cerr = base.compatible(pod_data.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        if cerr is not None:
            return None, f"incompatible requirements, {cerr}"
        base.add(*pod_data.requirements.values())
        return base, None

    def can_add_dynamic(self, pod, pod_data, base: Requirements, relax_min_values: bool = False):
        """The suffix of can_add: volume alternatives, topology, instance-type
        filtering, DRA, reservations. Returns (reqs, its, err, permanent) —
        `permanent` is True when the rejection is monotone in this claim's
        state REGARDLESS of topology/reservation churn: every instance type
        still in the option set lacks the raw resources for the accumulated
        requests plus this pod (options only narrow, requests only grow), so
        the scheduler's fit memo may cache the rejection for the signature.

        Try each volume topology alternative; the selected constraints affect
        downstream topology and instance-type checks (nodeclaim.go:138-157)."""
        last_err = None
        all_permanent = True  # a rejection is permanent only if EVERY alternative's is
        self._pending_dra = None
        self._pending_dra_meta = None
        self._pending_reserved = []
        for vol_reqs in pod_data.volume_requirements or [None]:
            reqs, its, err, permanent = self._try_volume_alternative(pod, pod_data, base, vol_reqs, relax_min_values)
            if err is not None:
                last_err = err
                all_permanent = all_permanent and permanent
                continue
            return reqs, its, None, False
        return None, None, last_err, all_permanent

    def _try_volume_alternative(self, pod, pod_data, base: Requirements, vol_reqs, relax_min_values: bool):
        """One alternative: volume reqs -> topology -> instance-type filter
        (nodeclaim.go:164-240). Volume reqs narrow the claim only, never the
        pod's affinity, preserving TSC counting semantics. Returns
        (reqs, its, err, permanent) — see can_add_dynamic."""
        claim_reqs = Requirements()
        claim_reqs.add(*base.values())
        if vol_reqs is not None:
            cerr = claim_reqs.compatible(vol_reqs, allow_undefined=wk.WELL_KNOWN_LABELS)
            if cerr is not None:
                return None, None, f"incompatible volume requirements, {cerr}", False
            claim_reqs.add(*vol_reqs.values())

        topo = self.topology.add_requirements(
            pod, self.template.taints, pod_data.strict_requirements, claim_reqs, allow_undefined=wk.WELL_KNOWN_LABELS
        )
        if isinstance(topo, str):
            return None, None, topo, False
        cerr = claim_reqs.compatible(topo, allow_undefined=wk.WELL_KNOWN_LABELS)
        if cerr is not None:
            return None, None, cerr, False
        claim_reqs.add(*topo.values())

        requests = res.merge(self.spec_requests, pod_data.requests)
        remaining, unsatisfiable, ferr, capacity_exhausted = filter_instance_types_cached(
            getattr(self, "filter_cache", None),
            self.instance_type_options, claim_reqs, pod, pod_data.requests, self.daemon_overhead_groups, requests, relax_min_values,
            native=_native_table_for(self.template),
        )
        if relax_min_values:
            for key, mv in unsatisfiable.items():
                # copy-on-write: claim_reqs aliases Requirement objects owned by
                # the template; mutating in place would relax minValues for every
                # subsequent claim in the solve
                relaxed = claim_reqs.get(key).copy()
                relaxed.min_values = mv
                claim_reqs.replace(relaxed)
        if ferr is not None:
            return None, None, ferr, capacity_exhausted

        # DRA: keep only instance types whose template devices satisfy the
        # pod's claims; the reference allocates before the filter and prunes
        # unsupported types after (nodeclaim.go:177-194,225-229). Per-IT
        # device choices then SUPERPOSE their contributed requirements: a
        # claim's topology is the intersection across surviving types, and
        # types that would collapse it to empty are pruned
        # (allocator.go:90-134)
        if (pod_data.resource_claims or pod_data.resource_claim_err) and self.allocator is not None:
            if pod_data.resource_claim_err is not None:
                return None, None, pod_data.resource_claim_err, False
            per_it = {}
            for it in remaining:
                tracker = self.dra_trackers.get(it.name)
                if tracker is None:
                    from ....scheduling.dynamicresources.allocator import AllocationTracker

                    # shares the allocator's pool-budget registry so template
                    # counter sets (partitionable devices) bound this claim
                    tracker = AllocationTracker(budgets=self.allocator.counter_budgets)
                result, derr = self.allocator.allocate(
                    self.hostname, self.allocator.template_devices(it), pod_data.resource_claims, tracker
                )
                if derr is None:
                    per_it[it.name] = (tracker, result)
            kept, metas = self.allocator.superpose_template_allocation(self.hostname, per_it)
            surviving = [it for it in remaining if it.name in kept]
            if not surviving:
                return None, None, "no instance type can allocate the pod's dynamic resources", False
            remaining = surviving
            self._pending_dra = kept
            self._pending_dra_meta = metas

        # reserved-offering reservations (nodeclaim.go:303-350): collect every
        # compatible+available reserved offering the claim could launch into;
        # under strict mode, fail rather than silently lose reserved capacity
        ofs, rerr = self._offerings_to_reserve(remaining, claim_reqs)
        if rerr is not None:
            # reservation state is NOT monotone (releases can re-open options)
            return None, None, rerr, False
        self._pending_reserved = ofs
        return claim_reqs, remaining, None, False

    def _offerings_to_reserve(self, instance_types: list[InstanceType], claim_reqs: Requirements):
        """Returns (reservable offerings, err). Reservation is pessimistic:
        any reserved offering the claim is compatible with is claimed, so two
        claims in one solve can never oversubscribe a reservation."""
        if self.reservation_manager is None:
            return [], None
        has_compatible = False
        reservable = []
        for it in instance_types:
            for o in it.offerings:
                if not o.available or o.capacity_type() != wk.CAPACITY_TYPE_RESERVED:
                    continue
                if claim_reqs.compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) is not None:
                    continue
                has_compatible = True
                if self.reservation_manager.can_reserve(self.hostname, o):
                    reservable.append(o)
        if self.reserved_offering_mode == "strict":
            if has_compatible and not reservable:
                return None, "reserved offering error: compatible reserved offerings exist but could not be reserved"
            if self.reserved_offerings and not reservable:
                return None, "reserved offering error: updated constraints would remove all reserved offering options"
        return reservable, None

    def add(self, pod, pod_data, updated_requirements: Requirements, updated_instance_types: list[InstanceType]) -> None:
        # getattr: decode builds claims with __new__ (rehydrate() re-seeds the
        # version, but direct adds on bare claims must not require it)
        self._version = getattr(self, "_version", 0) + 1
        self.pods.append(pod)
        self.requirements = updated_requirements
        removed = set()
        if self.allocator is not None and (self._dra_claim_keys or self._pending_dra_meta):
            removed = {it.name for it in self.instance_type_options} - {it.name for it in updated_instance_types}
        self.instance_type_options = updated_instance_types
        self.spec_requests = res.merge(self.spec_requests, pod_data.requests)
        if self.reservation_manager is not None:
            # reserve the surviving set, release what narrowing dropped
            # (nodeclaim.go:260-262 + releaseReservedOfferings :280-295)
            self.reservation_manager.reserve(self.hostname, *self._pending_reserved)
            updated_ids = {o.reservation_id() for o in self._pending_reserved}
            stale = [o for o in self.reserved_offerings if o.reservation_id() not in updated_ids]
            self.reservation_manager.release(self.hostname, *stale)
            self.reserved_offerings = self._pending_reserved
            self._pending_reserved = []
        if self._pending_dra is not None and self.allocator is not None:
            # commit per-instance-type device picks so later pods on this
            # in-flight node see the consumed template budget
            for it_name, (tracker, result) in self._pending_dra.items():
                self.dra_trackers[it_name] = tracker
                self.allocator.commit(self.hostname, result, tracker)
            if self._pending_dra_meta:
                self.allocator.commit_template_metadata(self._pending_dra_meta)
                self._dra_claim_keys.update(self._pending_dra_meta)
            self._pending_dra = None
            self._pending_dra_meta = None
        # single release site: instance types dropped by this pod's narrowing
        # (the pre-add option set is a superset of every claim's superposition
        # filter set, so `removed` covers prior AND just-committed claims)
        # relax committed claims' pessimistic contributions (allocator.go
        # "totalRequirements are updated each time instance types are released")
        if self.allocator is not None and self._dra_claim_keys and removed:
            for ck in self._dra_claim_keys:
                self.allocator.release_instance_types(ck, removed)
        # track host ports per daemon group so future pods see conflicts
        ports = pod_host_ports(pod)
        for g in self.daemon_overhead_groups:
            g.host_port_usage.add(pod.key(), ports)
        self.topology.record(pod, self.template.taints, self.requirements)

    def finalize(self) -> None:
        """Drop the hostname placeholder so the claim can land anywhere; pin
        reserved claims to their reservation ids (nodeclaim.go:383-409)."""
        reqs = Requirements()
        for key, r in self.requirements.items():
            if key != wk.HOSTNAME_LABEL_KEY:
                reqs.replace(r)
        if self.reserved_offerings:
            # tightening to reserved gives automatic drift handling when the
            # capacity-type label is later updated by the cloud provider, and
            # the id set prevents overlaunching into a single reservation
            reqs.replace(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_RESERVED]))
            rids = sorted({o.reservation_id() for o in self.reserved_offerings})
            reqs.replace(Requirement(wk.RESERVATION_ID_LABEL_KEY, "In", rids))
        self.requirements = reqs

    def to_api_node_claim(self, clock=None) -> APINodeClaim:
        """Produce the API NodeClaim to create (nodeclaimtemplate.go ToNodeClaim):
        price-ordered truncated instance types and narrowed capacity types."""
        its = order_by_price(self.instance_type_options, self.requirements)[:MAX_INSTANCE_TYPES]
        reqs = Requirements()
        for key, r in self.requirements.items():
            if key not in (wk.NODE_REGISTERED_LABEL_KEY, wk.NODE_INITIALIZED_LABEL_KEY):
                reqs.replace(r.copy())
        mv = self.requirements.get(wk.INSTANCE_TYPE_LABEL_KEY).min_values
        reqs.replace(Requirement(wk.INSTANCE_TYPE_LABEL_KEY, "In", [it.name for it in its], min_values=mv))
        cts = sorted(
            {
                o.capacity_type()
                for it in its
                for o in it.offerings
                if o.available and reqs.intersects(o.requirements) is None
            }
        )
        if cts:
            reqs.add(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", cts))

        tmpl = self.template
        # include daemon overhead in the claim's resource requests (reference
        # FinalizeScheduling -> addDaemonRequests): take the overhead of the
        # group backing the cheapest surviving instance type
        spec_requests = dict(self.spec_requests)
        surviving = {id(x) for x in its}
        for g in self.daemon_overhead_groups:
            if any(id(x) in surviving for x in g.instance_types):
                spec_requests = res.merge(spec_requests, g.daemon_overhead)
                break
        req_dicts = [d for r in reqs.values() for d in _req_to_dicts(r)]
        # keep the instance-type values price-ordered (cheapest first) so
        # downstream pickers and truncation see the intended preference
        for d in req_dicts:
            if d["key"] == wk.INSTANCE_TYPE_LABEL_KEY and d["operator"] == "In":
                d["values"] = [it.name for it in its]
        nc = APINodeClaim(
            metadata=ObjectMeta(
                name=f"{tmpl.nodepool_name}-{_rand_suffix()}",
                labels={**tmpl.labels, **_concrete_labels(reqs)},
                annotations=dict(tmpl.annotations),
                finalizers=[wk.TERMINATION_FINALIZER],
            ),
            spec=NodeClaimSpec(
                taints=list(tmpl.taints),
                startup_taints=list(tmpl.startup_taints),
                requirements=req_dicts,
                resources=spec_requests,
                node_class_ref=NodeClassReference(**tmpl.node_pool.spec.template.node_class_ref)
                if isinstance(tmpl.node_pool.spec.template.node_class_ref, dict)
                else tmpl.node_pool.spec.template.node_class_ref,
                termination_grace_period=parse_duration(tmpl.node_pool.spec.template.termination_grace_period),
                expire_after=parse_duration(tmpl.node_pool.spec.template.expire_after),
            ),
        )
        return nc


def _concrete_labels(reqs: Requirements) -> dict[str, str]:
    out = {}
    for key, r in reqs.items():
        if key in (wk.NODE_REGISTERED_LABEL_KEY, wk.NODE_INITIALIZED_LABEL_KEY, wk.HOSTNAME_LABEL_KEY):
            continue
        if r.operator() == Operator.IN and len(r.values) == 1:
            out[key] = r.any()
    return out


def _req_to_dicts(r: Requirement) -> list[dict]:
    """Serialize back to NodeSelectorRequirement dicts; a requirement carrying
    both bounds emits two entries (requirement.go:116-126)."""
    out: list[dict] = []
    if r.gte is not None:
        out.append({"key": r.key, "operator": "Gte", "values": [str(r.gte)]})
    if r.lte is not None:
        out.append({"key": r.key, "operator": "Lte", "values": [str(r.lte)]})
    if not out:
        out.append({"key": r.key, "operator": r.operator().value, "values": r.values_list()})
    if r.min_values is not None:
        for d in out:
            d["minValues"] = r.min_values
    return out


def _rand_suffix() -> str:
    # 10 hex chars: a 5-char suffix has ~9% birthday-collision odds by 400
    # generated names, which intermittently failed large solves with
    # AlreadyExists on claim create (kube generateName uses 5 chars but the
    # apiserver retries; the store does not)
    import random

    return f"{random.randrange(16**10):010x}"


def _reqs_content_key(reqs: Requirements) -> tuple:
    """Content identity of a Requirements set — equal keys for equal
    filtering behavior. The per-claim HOSTNAME placeholder is excluded: no
    instance type or offering constrains hostname, so it cannot change the
    filter result, and including it would make every claim's key unique
    (zero hits). Entries are keyed-unique, so sorting by label key alone
    gives a canonical order (frozensets have no total order)."""
    return tuple(
        sorted(
            (
                (r.key, r.complement, frozenset(r.values), r.gte, r.lte, r.min_values)
                for r in reqs.values()
                if r.key != wk.HOSTNAME_LABEL_KEY
            ),
            key=lambda t: t[0],
        )
    )


_FILTER_CACHE_MAX = 50_000


def filter_instance_types_cached(
    cache: Optional[dict],
    instance_types: list[InstanceType],
    requirements: Requirements,
    pod,
    pod_requests: dict[str, Quantity],
    daemon_overhead_groups: list[DaemonOverheadGroup],
    total_requests: dict[str, Quantity],
    relax_min_values: bool = False,
    native=None,
) -> tuple[Optional[list[InstanceType]], dict[str, int], Optional[str], bool]:
    """Solve-scoped memo around `filter_instance_types` (ROADMAP: the
    residual host FFD is ~0.6 ms/pod dominated by this call). The filter is
    a pure function of (type set, requirement CONTENT, accumulated requests,
    daemon groups, relax flag) — identical pod signatures probing the same
    claim state must not re-scan the full 500-type list. Host-port-carrying
    pods bypass the memo: their group conflict check reads mutable
    `host_port_usage` state the key cannot see (portless pods — the dominant
    shape — never conflict)."""
    if cache is None or pod_host_ports(pod):
        return filter_instance_types(
            instance_types, requirements, pod, pod_requests, daemon_overhead_groups,
            total_requests, relax_min_values, native=native,
        )
    its_key = (id(instance_types), len(instance_types))
    reqs_key = _reqs_content_key(requirements)
    groups_key = tuple((id(g.instance_types), id(g.daemon_overhead)) for g in daemon_overhead_groups)
    key = (
        # list identity + length, verified against the stored reference on
        # hit (a solve-scoped cache may see a recycled id after GC): claims
        # REPLACE their option list on every narrowing, so identity tracks
        # content exactly
        its_key,
        reqs_key,
        tuple(sorted((k, q.milli) for k, q in total_requests.items())),
        # group copies share their instance_types/daemon_overhead objects
        # with the template's originals, so claims of one template hit
        groups_key,
        relax_min_values,
    )
    hit = cache.get(key)
    if hit is None or hit[0] is not instance_types:
        if len(cache) >= _FILTER_CACHE_MAX:
            cache.clear()  # bound memory; repopulates within the solve
        # second-level cache: the requirement-dependent verdicts (type
        # compat + per-allocatable-group offering compat) are independent of
        # BOTH the accumulated requests and the narrowing option list, so a
        # landing (new totals, replaced option list) re-runs only the
        # res.fits scan over verdicts cached for the template-wide universe
        skey = ("static", reqs_key, groups_key)
        static = cache.get(skey)
        if static is None:
            static = cache[skey] = _static_group_verdicts(requirements, daemon_overhead_groups, native)
        hit = cache[key] = (
            instance_types,
            *filter_instance_types(
                instance_types, requirements, pod, pod_requests, daemon_overhead_groups,
                total_requests, relax_min_values, native=native, static=static,
            ),
        )
    _its_ref, remaining, unsat, err, capacity_exhausted = hit
    # callers assign/narrow the list downstream — never hand out the cached one
    return (list(remaining) if remaining is not None else None, dict(unsat), err, capacity_exhausted)


def _static_group_verdicts(
    requirements: Requirements,
    daemon_overhead_groups: list[DaemonOverheadGroup],
    native=None,
) -> list[list]:
    """Per daemon-overhead group, the requirement-dependent (hence totals-
    independent) verdicts for every instance type in the TEMPLATE-wide group
    lists: (it, compat, ((allocatable, has_compatible_offering), ...)).
    `filter_instance_types` combines these with the claim's current
    eligibility set and a fresh res.fits scan — the only parts that move when
    a landing grows the accumulated requests and narrows the options. Only
    used on the memoized (portless) path, where no daemon group can be
    skipped by a port conflict."""
    native_mask = native_rows = None
    if native is not None:
        from ....native import UnsupportedRequirements

        table, native_rows = native
        try:
            native_mask = table.filter(requirements)
        except UnsupportedRequirements:
            native_mask = None
    out: list[list] = []
    for group in daemon_overhead_groups:
        rows = []
        for it in group.instance_types:
            if native_mask is not None and id(it) in native_rows:
                compat = native_mask[native_rows[id(it)]] == 1
            else:
                compat = it.requirements.intersects(requirements) is None
            ginfo = tuple(
                (
                    alloc,
                    any(
                        requirements.compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) is None
                        for o in offerings
                    ),
                )
                for alloc, offerings in it.allocatable_offerings_list()
            )
            rows.append((it, compat, ginfo))
        out.append(rows)
    return out


def filter_instance_types(
    instance_types: list[InstanceType],
    requirements: Requirements,
    pod,
    pod_requests: dict[str, Quantity],
    daemon_overhead_groups: list[DaemonOverheadGroup],
    total_requests: dict[str, Quantity],
    relax_min_values: bool = False,
    native=None,
    static=None,
) -> tuple[Optional[list[InstanceType]], dict[str, int], Optional[str], bool]:
    """compat x fits x offering filter per daemon-overhead group
    (nodeclaim.go:541-640). Returns (remaining, unsatisfiable_min_values, err,
    capacity_exhausted). `capacity_exhausted` is True iff the filter rejected
    AND no eligible instance type has an allocatable group with the raw
    resources for `total_requests` — a verdict independent of requirement/
    offering compatibility, hence monotone in claim state (requests only
    grow, the option set only narrows): the scheduler's fit memo may cache
    such a rejection permanently. `native` is an optional (ReqTable, rowmap)
    that answers the per-type intersects check in one C call for the whole
    table."""
    remaining: list[InstanceType] = []
    ports = pod_host_ports(pod)
    any_compat = any_fits = any_offering = any_resource_fit = False

    native_mask = native_rows = None
    if native is not None and static is None:
        from ....native import UnsupportedRequirements

        table, native_rows = native
        try:
            native_mask = table.filter(requirements)
        except UnsupportedRequirements:
            native_mask = None  # query carries >int64 integers; Python path

    any_group_skipped = False
    if static is not None:
        # fast path over precomputed requirement verdicts (only the memoized
        # portless shape reaches here, so no group is ever port-skipped):
        # just apply the current eligibility set and re-run the
        # totals-dependent res.fits scan. The any_* failure flags feed only
        # the rejection message, and a rejection per (signature, claim) state
        # happens once before the fit memo pins it — compute them lazily in a
        # second pass instead of on every landing.
        eligible = {id(it) for it in instance_types}
        fits_fn = res.fits
        for rows, group in zip(static, daemon_overhead_groups):
            total = res.merge(total_requests, group.daemon_overhead) if group.daemon_overhead else total_requests
            for it, compat, ginfo in rows:
                if not compat or id(it) not in eligible:
                    continue
                for alloc, has_compat_off in ginfo:
                    if has_compat_off and fits_fn(total, alloc):
                        remaining.append(it)
                        break
        if not remaining:
            for rows, group in zip(static, daemon_overhead_groups):
                total = res.merge(total_requests, group.daemon_overhead) if group.daemon_overhead else total_requests
                for it, compat, ginfo in rows:
                    if id(it) not in eligible:
                        continue
                    fits = resource_fit = has_offering = False
                    for alloc, has_compat_off in ginfo:
                        has_offering |= has_compat_off
                        if fits_fn(total, alloc):
                            resource_fit = True
                            if has_compat_off:
                                fits = True
                                break
                    any_compat |= compat
                    any_fits |= fits
                    any_offering |= has_offering
                    any_resource_fit |= resource_fit
    else:
        eligible = {id(it) for it in instance_types}
        for group in daemon_overhead_groups:
            if group.host_port_usage.conflicts(pod.key(), ports) is not None:
                any_group_skipped = True  # unevaluated types: capacity verdict incomplete
                continue
            total = res.merge(total_requests, group.daemon_overhead) if group.daemon_overhead else total_requests
            for it in group.instance_types:
                if id(it) not in eligible:
                    continue
                if native_mask is not None and id(it) in native_rows:
                    compat = native_mask[native_rows[id(it)]] == 1
                else:
                    compat = it.requirements.intersects(requirements) is None
                fits, has_offering, resource_fit = _fits_and_offering(it, total, requirements)
                any_compat |= compat
                any_fits |= fits
                any_offering |= has_offering
                any_resource_fit |= resource_fit
                if compat and fits and has_offering:
                    remaining.append(it)

    unsatisfiable: dict[str, int] = {}
    if requirements.has_min_values():
        from ....cloudprovider.types import satisfies_min_values

        _, unsat = satisfies_min_values(remaining, requirements)
        if unsat:
            if not relax_min_values:
                return None, {}, (
                    f"minValues requirement is not met for {sorted(unsat)} "
                    f"(observed {unsat})"
                ), False
            unsatisfiable = unsat

    if not remaining:
        parts = []
        if not any_compat:
            parts.append("no instance type satisfied requirements")
        if not any_fits:
            parts.append(f"no instance type has enough resources for {res.fmt(total_requests)}")
        if not any_offering:
            parts.append("no instance type has a compatible offering")
        if not parts:
            parts.append("no single instance type met requirements/fits/offering simultaneously")
        capacity_exhausted = not any_resource_fit and not any_group_skipped
        return None, unsatisfiable, "; ".join(parts), capacity_exhausted
    return remaining, unsatisfiable, None, False


def _fits_and_offering(it: InstanceType, requests: dict[str, Quantity], requirements: Requirements) -> tuple[bool, bool, bool]:
    """(fits, has_offering, resource_fit) per allocatable-offerings group:
    offerings with capacity/overhead overrides form groups with their OWN
    allocatable, so an instance type fits iff some group both fits the
    requests and holds a compatible offering (nodeclaim.go:624-640 fits +
    types.go:202-257 AllocatableOfferingsList). Deliberately
    reference-exact: fits=False even when resources fit but no group holds a
    compatible offering — the reference's error for that case likewise merges
    both criteria ("no instance type had enough resources or had a required
    offering", nodeclaim.go:505-507). The third element reports the RAW
    resource verdict (some group fits the requests, compatibility aside): a
    requirements-independent — hence monotone — capacity signal the fit memo
    keys permanence on."""
    has_offering = False
    any_resource_fit = False
    for alloc, offerings in it.allocatable_offerings_list():
        resource_fit = res.fits(requests, alloc)
        any_resource_fit |= resource_fit
        for o in offerings:
            if requirements.compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) is None:
                has_offering = True
                if resource_fit:
                    return True, True, True
                break
    return False, has_offering, any_resource_fit
