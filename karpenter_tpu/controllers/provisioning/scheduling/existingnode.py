"""ExistingNode: scheduling against an already-running (or launching) node.

Reference: scheduling/existingnode.go — remaining resources start at
allocatable minus current pods minus expected daemon overhead; CanAdd checks
taints, host ports, resources, requirements, then topology.
"""

from __future__ import annotations

from ....apis import labels as wk
from ....scheduling.requirements import Requirement, Requirements
from ....scheduling.taints import taints_tolerate_pod
from ....scheduling.hostports import pod_host_ports
from ....utils import resources as res
from ....utils.quantity import Quantity


class ExistingNode:
    def __init__(self, state_node, topology, taints, daemon_resources: dict[str, Quantity], is_under_consolidate_after: bool = False, allocator=None, daemon_pods: list | None = None):
        self.state_node = state_node
        self.topology = topology
        self.taints = taints
        self.pods: list = []
        self.is_under_consolidate_after = is_under_consolidate_after
        self.allocator = allocator  # DRA; None when the gate is off
        self._pending_dra = None
        # monotone state version: bumped on every add(); the scheduler's fit
        # memo stamps static-pass entries with it so a stale pass is recomputed
        self._version = 0

        # remaining = allocatable - committed pods - headroom for daemons that
        # haven't scheduled yet (existingnode.go:45-60)
        remaining = res.subtract(state_node.allocatable(), state_node.total_pod_requests())
        daemon_headroom = res.subtract(daemon_resources, state_node.total_daemon_requests())
        daemon_headroom = {k: v for k, v in daemon_headroom.items() if v.milli > 0}
        self.remaining_resources = res.subtract(remaining, daemon_headroom)

        self.host_port_usage = state_node.host_port_usage.copy()
        # phantom daemon port headroom: this substrate has no DaemonSet
        # controller materializing daemon pods, so compatible daemons that
        # haven't landed yet reserve their ports here the same way their
        # resources reserve headroom above; a port already held by a real
        # daemon pod stays held (the conflicting add is skipped)
        for d in daemon_pods or []:
            ports = pod_host_ports(d)
            if ports and self.host_port_usage.conflicts(d.key(), ports) is None:
                self.host_port_usage.add(f"daemon-headroom/{d.key()}", ports)
        self.volume_usage = state_node.volume_usage.copy()
        self.requirements = Requirements.from_labels_view(state_node.labels()).copy_shallow()
        self.requirements.add(Requirement(wk.HOSTNAME_LABEL_KEY, "In", [state_node.hostname()]))
        topology.register(wk.HOSTNAME_LABEL_KEY, state_node.hostname())

    def name(self) -> str:
        return self.state_node.name()

    def can_add(self, pod, pod_data):
        """Returns (updated_requirements, None) or error string
        (existingnode.go:81-139)."""
        base, err = self.can_add_static(pod, pod_data)
        if err is not None:
            return None, err
        return self.can_add_dynamic(pod, pod_data, base)

    def can_add_static(self, pod, pod_data):
        """The MONOTONE prefix of can_add: taints, volume limits, host ports,
        resource fit, and requirements compatibility. Within one solve this
        node's taints and labels are fixed and its usage only grows (resources
        shrink, requirements tighten, port/volume usage accumulates), so a
        rejection here can never turn into an acceptance later — the
        scheduler's fit memo caches it permanently per pod signature. Returns
        (base_requirements, None) or (None, err)."""
        err = taints_tolerate_pod(self.taints, pod, include_prefer_no_schedule=True)
        if err is not None:
            return None, err
        verr = self.volume_usage.exceeds_limits(pod_data.volumes)
        if verr is not None:
            return None, f"checking volume usage, {verr}"
        ports = pod_host_ports(pod)
        cerr = self.host_port_usage.conflicts(pod.key(), ports)
        if cerr is not None:
            return None, cerr
        if not res.fits(pod_data.requests, self.remaining_resources):
            return None, "exceeds node resources"
        cerr = self.requirements.compatible(pod_data.requirements)
        if cerr is not None:
            return None, cerr
        base = Requirements()
        base.add(*self.requirements.values())
        base.add(*pod_data.requirements.values())
        return base, None

    def can_add_dynamic(self, pod, pod_data, base: Requirements):
        """The NON-monotone suffix: topology (skew counts move both ways) and
        DRA allocation. Never memoized — must re-run on every probe.

        Try each volume topology alternative; the selected constraints shape
        the topology checks (existingnode.go:108-137)."""
        last_err = None
        self._pending_dra = None
        for vol_reqs in pod_data.volume_requirements or [None]:
            reqs, err = self._try_volume_alternative(pod, pod_data, base, vol_reqs)
            if err is not None:
                last_err = err
                continue
            # simulate DRA allocation against this node's published devices;
            # committed on Add. The result is independent of the volume
            # alternative (node requirements are immutable here), so a failure
            # short-circuits instead of re-running the DFS per alternative
            # (existingnode.go:122-135)
            if (pod_data.resource_claims or pod_data.resource_claim_err) and self.allocator is not None:
                if pod_data.resource_claim_err is not None:
                    return None, pod_data.resource_claim_err
                result, derr = self.allocator.allocate_for_node(self.name(), pod_data.resource_claims)
                if derr is not None:
                    return None, f"allocating dynamic resources, {derr}"
                self._pending_dra = result
            return reqs, None
        return None, last_err

    def _try_volume_alternative(self, pod, pod_data, base: Requirements, vol_reqs):
        """Volume requirements bind to the node only — never to pod affinity —
        so spread counting keeps the pod's own constraints
        (existingnode.go:143-168)."""
        node_reqs = Requirements()
        node_reqs.add(*base.values())
        if vol_reqs is not None:
            cerr = node_reqs.compatible(vol_reqs)
            if cerr is not None:
                return None, f"incompatible volume requirements, {cerr}"
            node_reqs.add(*vol_reqs.values())
        topo = self.topology.add_requirements(pod, self.taints, pod_data.strict_requirements, node_reqs)
        if isinstance(topo, str):
            return None, topo
        cerr = node_reqs.compatible(topo)
        if cerr is not None:
            return None, cerr
        node_reqs.add(*topo.values())
        return node_reqs, None

    def add(self, pod, pod_data, updated_requirements: Requirements) -> None:
        self._version += 1
        self.pods.append(pod)
        self.requirements = updated_requirements
        self.remaining_resources = res.subtract(self.remaining_resources, pod_data.requests)
        self.host_port_usage.add(pod.key(), pod_host_ports(pod))
        self.volume_usage.add(pod.key(), pod_data.volumes)
        if self._pending_dra is not None and self.allocator is not None:
            self.allocator.commit_for_node(self.name(), self._pending_dra)
            self._pending_dra = None
        self.topology.record(pod, self.taints, self.requirements)
