"""FFD pod queue (reference: scheduling/queue.go:31-108).

Pods are sorted CPU-descending then memory-descending (first-fit-decreasing);
Pop stops when the queue cycles without progress.

Cycle detection keys `_last_len` by pod uid. The scheduler's relaxation loop
(`Scheduler._try_schedule`) deep-copies the pod before mutating its spec and
REQUEUES THE CALLER'S ORIGINAL — `copy.deepcopy` preserves `metadata.uid`, so
either object maps to the same `_last_len` slot and a pod that exhausts every
relaxation (twice-relaxed or more) still terminates the queue: its re-push
records the queue length, and the next pop at an unchanged length returns
None instead of spinning (regression: tests/test_ffd_batch.py).
"""

from __future__ import annotations


from collections import deque


class Queue:
    def __init__(self, pods: list, pod_data: dict):
        self.pods = deque(sorted(pods, key=lambda p: _sort_key(p, pod_data)))
        self._last_len: dict[str, int] = {}

    def pop(self):
        if not self.pods:
            return None
        p = self.pods[0]
        if self._last_len.get(p.metadata.uid) == len(self.pods):
            return None  # cycled through with no progress
        self.pods.popleft()
        return p

    def push(self, pod) -> None:
        self.pods.append(pod)
        self._last_len[pod.metadata.uid] = len(self.pods)

    def list(self) -> list:
        return list(self.pods)


def _sort_key(pod, pod_data):
    req = pod_data[pod.metadata.uid].requests
    cpu = req.get("cpu")
    mem = req.get("memory")
    return (
        -(cpu.milli if cpu else 0),
        -(mem.milli if mem else 0),
        pod.metadata.creation_timestamp,
        pod.metadata.uid,
    )
