"""Reserved-offering capacity accounting for one scheduling solve.

Reference: scheduling/reservationmanager.go:29-120 — reserved offerings
(`karpenter.sh/capacity-type: reserved`) carry a finite ReservationCapacity;
during a single solve every in-flight NodeClaim pessimistically reserves all
compatible reserved offerings so that two claims can never oversubscribe one
reservation, and releases reservations that later requirement-narrowing (or
relaxation re-runs) filtered out.

Used by the host FFD scheduler per claim (nodeclaim.go:303-350
offeringsToReserve) and by the TPU decode as the host-side cap over device
placements (SURVEY.md §7 "Reserved offerings ... keep host-side").
"""

from __future__ import annotations

from ....apis import labels as wk


class ReservationManager:
    def __init__(self, instance_types: dict[str, list]):
        capacity: dict[str, int] = {}
        for its in instance_types.values():
            for it in its:
                for o in it.offerings:
                    if o.capacity_type() != wk.CAPACITY_TYPE_RESERVED:
                        continue
                    rid = o.reservation_id()
                    # multiple nodepools can reference one reservation with the
                    # capacity updated between GetInstanceTypes calls: track
                    # the smallest (reservationmanager.go:40-45)
                    cur = capacity.get(rid)
                    if cur is None or cur > o.reservation_capacity:
                        capacity[rid] = o.reservation_capacity
        self.capacity = capacity
        self.reservations: dict[str, set[str]] = {}  # hostname -> reservation ids

    def can_reserve(self, hostname: str, offering) -> bool:
        """Idempotent: True if this hostname already holds the reservation or
        capacity remains."""
        rid = offering.reservation_id()
        held = self.reservations.get(hostname)
        if held and rid in held:
            return True
        return self.capacity.get(rid, 0) > 0

    def reserve(self, hostname: str, *offerings) -> None:
        """Idempotent per (hostname, reservation id)."""
        for o in offerings:
            rid = o.reservation_id()
            held = self.reservations.setdefault(hostname, set())
            if rid in held:
                continue
            remaining = self.capacity.get(rid, 0)
            if remaining <= 0:
                raise RuntimeError(f"attempted to over-reserve offering with reservation id {rid!r}")
            self.capacity[rid] = remaining - 1
            held.add(rid)

    def release(self, hostname: str, *offerings) -> None:
        """No-op for offerings the hostname never reserved."""
        held = self.reservations.get(hostname)
        if not held:
            return
        for o in offerings:
            rid = o.reservation_id()
            if rid in held:
                held.discard(rid)
                self.capacity[rid] = self.capacity.get(rid, 0) + 1

    def has_reservation(self, hostname: str, offering) -> bool:
        held = self.reservations.get(hostname)
        return bool(held) and offering.reservation_id() in held

    def remaining_capacity(self, offering) -> int:
        return self.capacity.get(offering.reservation_id(), 0)
