"""Pod batcher: idle/max windows (reference: provisioning/batcher.go:33-110).

Triggers accumulate; a batch fires after BatchIdleDuration of quiet or
BatchMaxDuration since the first trigger (defaults 1s/10s, options.go:129-130).
"""

from __future__ import annotations


class Batcher:
    def __init__(self, clock, idle_seconds: float = 1.0, max_seconds: float = 10.0):
        self.clock = clock
        self.idle = idle_seconds
        self.max = max_seconds
        self._first: float | None = None
        self._last: float | None = None

    def trigger(self, uid: str = "") -> None:
        now = self.clock.now()
        if self._first is None:
            self._first = now
        self._last = now

    def ready(self) -> bool:
        if self._first is None:
            return False
        now = self.clock.now()
        return (now - self._last) >= self.idle or (now - self._first) >= self.max

    def reset(self) -> None:
        self._first = None
        self._last = None
