"""Pod batcher: idle/max windows (reference: provisioning/batcher.go:33-110)
plus in-flight-aware wake-up coalescing for the steady-state serving loop.

Triggers accumulate; a batch fires after BatchIdleDuration of quiet or
BatchMaxDuration since the first trigger (defaults 1s/10s, options.go:129-130).

Coalescing (the churn serving loop's throughput lever): triggers that arrive
WHILE a solve is in flight fold into one pending generation instead of each
scheduling work — when the solve completes, `ready()` fires immediately
(the in-flight solve itself WAS the batching window, so the accumulated
generation drains as ONE batched follow-up solve with no idle-window stall).
N triggers during a solve therefore cost exactly one follow-up solve, never
N. The provisioner brackets its solve with `begin_solve()`/`end_solve()`;
a batcher that never sees those calls behaves exactly like the reference's
idle/max-window batcher.

Thread-safe: triggers arrive from store watch callbacks on whatever thread
mutated the store (the serving harness's event driver runs concurrently
with the solve loop), so the trigger/bracket state is lock-guarded — a
trigger racing `end_solve`'s read-and-zero must either land in the returned
coalesced count or in the next generation, never vanish.
"""

from __future__ import annotations

import time

from ...obs.racecheck import make_lock


class Batcher:
    # racecheck guarded-field registry: the trigger/bracket state is written
    # from watch-delivery threads and read by the serving loop — every touch
    # goes through `_lock` (analysis: guarded-field-access enforces it)
    GUARDED_FIELDS = {
        "_first": "_lock",
        "_last": "_lock",
        "_count": "_lock",
        "_in_flight": "_lock",
        "_during": "_lock",
        "_drain": "_lock",
        "_opened_monotonic": "_lock",
        "_last_gen": "_lock",
    }

    def __init__(self, clock, idle_seconds: float = 1.0, max_seconds: float = 10.0):
        self.clock = clock
        self.idle = idle_seconds
        self.max = max_seconds
        self._lock = make_lock("batcher")
        self._first: float | None = None
        self._last: float | None = None
        # current generation's trigger count (the solve-queue depth surface)
        self._count = 0
        # in-flight coalescing state
        self._in_flight = False
        self._during = 0  # triggers folded into the in-flight solve's window
        self._drain = False  # a coalesced generation is waiting: fire now
        # podtrace: MONOTONIC open stamp of the pending generation and the
        # last taken generation's window summary (opened -> taken residency
        # + trigger count) — the coalescing-window surface the event tracer
        # links into each solve's event-batch note. The fake-clock fields
        # above drive window POLICY; these measure wall residency.
        self._opened_monotonic = 0.0
        self._last_gen: dict | None = None
        # push-wake seam (serving/fleet.py): a zero-arg callable invoked on
        # every trigger, AFTER the lock is released — the fleet front-end
        # installs one per tenant to mark the tenant runnable and wake the
        # fleet loop, so a watch-delivered arrival reaches the scheduler
        # push-style instead of waiting for the next poll of ready(). The
        # hook must be cheap and lock-ordered BELOW the batcher lock (the
        # fleet's wake path takes only its own leaf lock + an Event.set).
        self.wake_hook = None

    def trigger(self, uid: str = "") -> None:
        now = self.clock.now()
        with self._lock:
            if self._first is None:
                self._first = now
                self._opened_monotonic = time.monotonic()
            self._last = now
            self._count += 1
            if self._in_flight:
                self._during += 1
        hook = self.wake_hook
        if hook is not None:
            hook()

    # -- in-flight coalescing (serving loop) -----------------------------------
    def take_generation(self) -> int:
        """Atomically close the current generation AND open the in-flight
        window (reset + begin_solve in one lock hold): returns the closed
        generation's trigger count. A concurrent trigger either lands in the
        returned count or in the in-flight window — never in a gap between
        the two, which would erase it from the coalescing accounting and
        cost its follow-up solve a full idle-window stall."""
        with self._lock:
            n = self._count
            taken = time.monotonic()
            self._last_gen = {
                "count": n,
                "window_s": max(0.0, taken - self._opened_monotonic) if n else 0.0,
                "taken_monotonic": taken,
            }
            self._first = None
            self._last = None
            self._count = 0
            self._drain = False
            self._in_flight = True
            self._during = 0
            return n

    def last_generation(self) -> dict | None:
        """The most recently taken generation's wall-clock window summary
        ({count, window_s, taken_monotonic}) — the coalescing-residency
        surface podtrace joins into the solve's event-batch note."""
        with self._lock:
            return dict(self._last_gen) if self._last_gen is not None else None

    def begin_solve(self) -> None:
        """The provisioner is entering a solve: triggers from here to
        `end_solve()` coalesce into one pending generation."""
        with self._lock:
            self._in_flight = True
            self._during = 0

    def end_solve(self) -> int:
        """The solve finished. Returns the number of triggers coalesced into
        the pending generation and, when nonzero, arms the drain so the next
        `ready()` fires immediately — one batched follow-up solve."""
        with self._lock:
            self._in_flight = False
            n, self._during = self._during, 0
            if n:
                self._drain = True
            return n

    def pending(self) -> int:
        """Triggers accumulated in the current (unfired) generation."""
        with self._lock:
            return self._count

    def eta(self) -> float | None:
        """Seconds until `ready()` would fire for the pending generation
        (0.0 = ready now), or None when no generation is open. The fleet
        front-end's push loop sleeps exactly this long instead of polling:
        the idle/max window stays a COALESCING bound while the poll interval
        stops being a latency floor."""
        now = self.clock.now()
        with self._lock:
            if self._first is None:
                return None
            if self._drain:
                return 0.0
            return max(0.0, min(self._last + self.idle, self._first + self.max) - now)

    def ready(self) -> bool:
        now = self.clock.now()
        with self._lock:
            if self._first is None:
                return False
            if self._drain:
                # coalesced generation: the just-finished solve was the window
                return True
            return (now - self._last) >= self.idle or (now - self._first) >= self.max

    def reset(self) -> None:
        with self._lock:
            self._first = None
            self._last = None
            self._count = 0
            self._drain = False
