"""Provisioner: batch pending pods -> solve -> create NodeClaims.

Reference: provisioning/provisioner.go:127-513 — the singleton reconciler at
the top of call stack §3.1. The Solve step goes through the Solver plugin
point (FFD default, TPU opt-in — BASELINE.json north star).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...apis import labels as wk
from ...apis.nodepool import COND_NODEPOOL_READY
from ...solver import FFDSolver, SolverSnapshot
from ...utils import pods as pod_utils
from ...utils import resources as res
from .batcher import Batcher
from .scheduling.scheduler import Results


@dataclass
class ProvisionerOptions:
    preference_policy: str = "Respect"
    min_values_policy: str = "Strict"
    batch_idle_seconds: float = 1.0
    batch_max_seconds: float = 10.0
    capacity_buffer_enabled: bool = False  # CapacityBuffer feature gate
    dynamic_resources_enabled: bool = False  # DynamicResources feature gate
    reserved_capacity_enabled: bool = True  # ReservedCapacity feature gate


class Provisioner:
    def __init__(self, store, cluster, cloud_provider, clock, solver=None, recorder=None, options: ProvisionerOptions | None = None, metrics=None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.solver = solver or FFDSolver()
        self.recorder = recorder
        self.options = options or ProvisionerOptions()
        self.metrics = metrics
        self.batcher = Batcher(clock, self.options.batch_idle_seconds, self.options.batch_max_seconds)
        # bounded fleet tenant label (serving.fleet.tenant_label output, set
        # by FleetFrontend at session registration): rides the churn metric
        # families so one shared fleet registry attributes them per tenant.
        # "" outside a fleet — the registry renders that as the empty label.
        self.tenant = ""
        # serving-loop double-buffer (serving/prestage.py): when installed,
        # get_pending_pods consumes pre-staged pod clones (already validated
        # and signature-stamped, by the worker that overlapped the previous
        # solve's device pack) instead of cloning inline; None = the
        # reference clone-per-pass behavior
        self.prestager = None
        # podtrace (obs/podtrace.py): the event-lifecycle tracer, installed
        # by the Environment — provision() stamps dispatch/solved on every
        # traced pod in the batch and links the batch summary into the
        # SolveTrace. None = untraced provisioner (direct-wired tests).
        self.podtracer = None
        # watch-loss convergence (faultline): the store's Pod loss epoch
        # seen at the last reconcile. A bump means the delivered stream
        # lost events the Cluster mirror never saw — re-converge it from
        # store content before the next solve reads cluster state.
        self._watch_loss_seen = store.watch_loss_epoch("Pod") if hasattr(store, "watch_loss_epoch") else 0

    # -- triggering (provisioning/controller.go) -------------------------------
    def trigger(self, uid: str = "") -> None:
        self.batcher.trigger(uid)

    def reconcile(self, force: bool = False) -> Results | None:
        """One pass: fire when the batch window closes and state is synced.

        The solve is bracketed with the batcher's in-flight window so trigger
        bursts landing DURING it coalesce into exactly one batched follow-up
        solve (see Batcher); the karpenter_solver_churn_* families record the
        coalescing behavior per solve."""
        if not force and not self.batcher.ready():
            return None
        if not self.cluster.synced():
            return None
        # store content is authoritative: if the watch stream lost Pod
        # events since the last pass (faultline watch-drop, or any real
        # lossy transport), the event-fed Cluster mirror is stale —
        # re-converge it BEFORE the solve reads node usage/bindings
        loss = self.store.watch_loss_epoch("Pod") if hasattr(self.store, "watch_loss_epoch") else 0
        if loss != self._watch_loss_seen:
            self._watch_loss_seen = loss
            self.cluster.resync_pods()
            if self.metrics is not None:
                from ... import metrics as m

                self.metrics.counter(m.SOLVER_WATCH_RESYNC_TOTAL).inc()
        # one atomic handoff: close the generation and open the in-flight
        # window together, so a concurrent trigger can never fall between
        events = self.batcher.take_generation()
        try:
            results = self.provision()
        finally:
            coalesced = self.batcher.end_solve()
            if self.metrics is not None:
                from ... import metrics as m

                if coalesced:
                    self.metrics.counter(m.SOLVER_CHURN_COALESCED_TOTAL).inc(coalesced, tenant=self.tenant)  # solverlint: ok(metric-label-cardinality): tenant is a serving.fleet.tenant_label() output stored at fleet registration — the bounded fleet enum ("" outside a fleet)
                self.metrics.histogram(m.SOLVER_CHURN_EVENTS_PER_SOLVE).observe(float(events), tenant=self.tenant)  # solverlint: ok(metric-label-cardinality): tenant is a serving.fleet.tenant_label() output stored at fleet registration — the bounded fleet enum ("" outside a fleet)
                # depth AFTER the solve: the coalesced generation still queued
                self.metrics.gauge(m.SOLVER_CHURN_QUEUE_DEPTH).set(self.batcher.pending(), tenant=self.tenant)  # solverlint: ok(metric-label-cardinality): tenant is a serving.fleet.tenant_label() output stored at fleet registration — the bounded fleet enum ("" outside a fleet)
        return results

    # -- the provisioning pass (provisioner.go:350-458) ------------------------
    def provision(self) -> Results:
        pods = self.get_pending_pods()
        # podtrace dispatch stamp: the generation was just taken and its
        # batch assembled — every traced event's coalescing-window residency
        # ends HERE, and the batch summary rides the SolveTrace (explain()
        # joins the two views through the solve seq)
        tracer = self.podtracer
        if tracer is not None and tracer.enabled:
            batch = tracer.on_dispatch(pods, window=self.batcher.last_generation())
            if batch is not None and hasattr(self.solver, "stage_event_batch"):
                self.solver.stage_event_batch(batch)
        results = self.schedule(pods)
        if tracer is not None and tracer.enabled:
            tracer.on_solved(results, solve_seq=getattr(getattr(self.solver, "_trace", None), "seq", 0))
            if hasattr(self.solver, "discard_event_batch"):
                # schedule() may have declined to solve (no pods / no ready
                # nodepools): a staged batch the solve never consumed must
                # not attach to a later, unrelated solve's trace
                self.solver.discard_event_batch()
        for claim in results.new_node_claims:
            if claim.pods:
                self.create_node_claim(claim)
        # nominate existing nodes that received pods so disruption leaves them be
        for existing in results.existing_nodes:
            if existing.pods:
                self.cluster.nominate_node(existing.name())
        if self.options.capacity_buffer_enabled:
            self._record_buffer_pod_counts(results)
        return results

    def _record_buffer_pod_counts(self, results: Results) -> None:
        """Which nodes host virtual buffer pods this round — emptiness must
        not reclaim them (provisioner.go:156, cluster.go:299-307)."""
        from ...apis.capacitybuffer import is_virtual_pod

        counts: dict[str, int] = {}
        for existing in results.existing_nodes:
            n = sum(1 for p in existing.pods if is_virtual_pod(p))
            if n:
                counts[existing.state_node.provider_id()] = n
        self.cluster.update_buffer_pod_counts(counts)

    def get_pending_pods(self) -> list:
        """Provisionable pods (provisioner.go:192-221); pods referencing
        invalid PVCs are skipped the way kube-scheduler rejects them
        (provisioner.go:556-566)."""
        from .scheduling.volumetopology import VolumeTopology

        from ...kube.clone import fast_deepcopy

        vt = VolumeTopology(self.store)
        prestager = self.prestager
        out = []
        # filter over the borrowed cache view (most pods are bound — cloning
        # the full list per call dominated at reference scale), then clone
        # only the survivors: the store may mutate them between solves. With
        # a prestager installed (serving loop), the clone+validate work for
        # unchanged pods was already done — typically overlapped with the
        # PREVIOUS solve's device pack — and the SAME clone object is reused
        # while (uid, resourceVersion) holds, which is what lets the encoder
        # classify consecutive serving snapshots as pod deltas
        for pod in self.store.borrow_list("Pod"):
            if not pod_utils.is_provisionable(pod):
                continue
            if prestager is not None:
                clone = prestager.take(pod)
                if clone is not None:
                    # staged pods carry no claim-backed volumes, so the PVC
                    # validation below is a provable no-op for them
                    out.append(clone)
                    continue
            verr = vt.validate_persistent_volume_claims(pod)
            if verr is not None:
                if self.recorder is not None:
                    self.recorder.publish(pod, "FailedScheduling", f"ignoring pod, {verr}", type_="Warning")
                continue
            out.append(fast_deepcopy(pod))
        # CapacityBuffer virtual pods join AFTER validation so they skip PVC
        # checks and never round-trip through the store (buffers.go:37-87)
        if self.options.capacity_buffer_enabled:
            out = self._append_virtual_pods(out)
        return out

    def _append_virtual_pods(self, pods: list) -> list:
        from ...apis.capacitybuffer import COND_READY_FOR_PROVISIONING
        from ..capacitybuffer.controller import build_virtual_pods, resolve_buffer_pod_spec

        for cb in self.store.list("CapacityBuffer"):
            if not cb.status.conditions.is_true(COND_READY_FOR_PROVISIONING):
                continue
            if not cb.status.replicas or cb.status.replicas <= 0:
                continue
            spec, template_labels = resolve_buffer_pod_spec(self.store, cb)
            if spec is None:
                continue
            pods = pods + build_virtual_pods(cb, spec, template_labels)
        return pods

    def schedule(self, pods: list) -> Results:
        if not pods:
            if self.metrics is not None:
                from ... import metrics as m

                self.metrics.gauge(m.SCHEDULER_QUEUE_DEPTH).set(0)
                self.metrics.gauge(m.SCHEDULER_UNSCHEDULABLE_PODS).set(0)
                self.metrics.gauge(m.SCHEDULER_PENDING_PODS_BY_EFFECTIVE_ZONE).reset()
            return Results()
        snapshot = self.make_snapshot(pods)
        # computing effective zones is pointless when nobody publishes them
        snapshot.collect_zone_metrics = self.metrics is not None
        if not snapshot.node_pools:
            if self.metrics is not None:
                from ... import metrics as m

                # no solve runs, so every solve-scoped gauge would otherwise
                # keep reporting the previous batch forever
                self.metrics.gauge(m.SCHEDULER_QUEUE_DEPTH).set(len(pods))
                self.metrics.gauge(m.SCHEDULER_UNSCHEDULABLE_PODS).set(len(pods))
                self.metrics.gauge(m.SCHEDULER_PENDING_PODS_BY_EFFECTIVE_ZONE).reset()
            return Results(pod_errors={p.key(): "no ready nodepools" for p in pods})
        if self.metrics is None:
            return self.solver.solve(snapshot)
        import time as _time

        from ... import metrics as m

        self.metrics.gauge(m.SCHEDULER_QUEUE_DEPTH).set(len(pods))
        t0 = _time.perf_counter()
        results = self.solver.solve(snapshot)
        self.metrics.histogram(m.SCHEDULER_SCHEDULING_DURATION).observe(_time.perf_counter() - t0)
        # unschedulable virtual buffer pods are headroom shortfall, not real
        # demand failures (buffers.go filterVirtualPodErrors)
        from ...apis.capacitybuffer import is_virtual_pod

        virtual_keys = {p.key() for p in pods if is_virtual_pod(p)}
        real_errors = {k: v for k, v in results.pod_errors.items() if k not in virtual_keys}
        self.metrics.gauge(m.SCHEDULER_UNSCHEDULABLE_PODS).set(len(real_errors))
        # effective-zone demand gauge (scheduler.go:450,495-501): stale zone
        # labels are dropped each solve, then the batch's counts published; a
        # backend that does not compute the counts (TPU decode) clears the
        # gauge too, so it never reports a previous batch
        g = self.metrics.gauge(m.SCHEDULER_PENDING_PODS_BY_EFFECTIVE_ZONE)
        g.reset()
        for zone, count in (results.pending_pods_by_effective_zone or {}).items():
            g.set(count, zone=zone)
        return results

    def make_snapshot(self, pods: list, state_nodes=None, exclude_deleting: bool = True) -> SolverSnapshot:
        """Snapshot assembly (provisioner.go:261-348 NewScheduler)."""
        # skip static pools, deleting pools, and pools an aux controller has
        # explicitly marked not-Ready (provisioner.go:272-281; absence of the
        # condition counts ready so direct-wired tests need no readiness pass)
        node_pools = [
            np
            for np in self.store.list("NodePool")
            if not np.is_static()
            and np.metadata.deletion_timestamp is None
            and not np.status.conditions.is_false(COND_NODEPOOL_READY)
        ]
        instance_types = {}
        for np in node_pools:
            its = self.cloud_provider.get_instance_types(np)
            if its:
                instance_types[np.metadata.name] = its
        node_pools = [np for np in node_pools if np.metadata.name in instance_types]
        if state_nodes is None:
            state_nodes = [
                n
                for n in self.cluster.nodes()
                if not (exclude_deleting and (n.marked_for_deletion or n.deleted()))
            ]
        daemonset_pods = [ds.to_pod() for ds in self.store.list("DaemonSet")]
        return SolverSnapshot(
            store=self.store,
            cluster=self.cluster,
            node_pools=node_pools,
            instance_types=instance_types,
            state_nodes=state_nodes,
            daemonset_pods=daemonset_pods,
            pods=pods,
            clock=self.clock,
            preference_policy=self.options.preference_policy,
            min_values_policy=self.options.min_values_policy,
            dra_enabled=self.options.dynamic_resources_enabled,
            reserved_capacity_enabled=self.options.reserved_capacity_enabled,
            registry=self.metrics,
        )

    def create_node_claim(self, scheduling_claim, reason: str = "provisioning") -> str | None:
        """Limits check + API create (provisioner.go:460-513). Returns the
        created claim name or None when limits forbid it."""
        nc = scheduling_claim.to_api_node_claim(self.clock)
        pool_name = scheduling_claim.nodepool_name if hasattr(scheduling_claim, "nodepool_name") else scheduling_claim.template.nodepool_name
        node_pool = self.store.try_get("NodePool", pool_name)
        if node_pool is None:
            return None
        if node_pool.spec.limits:
            # reject when current usage already exceeds limits (provisioner.go
            # Create: ExceededBy(current)); forward-looking enforcement happens
            # in the scheduler via remainingResources filtering
            current = self.cluster.nodepool_resources(pool_name)
            err = node_pool.limits_exceeded_by(current)
            if err is not None:
                return None
        created = self.store.create(nc)
        # immediately mirror into cluster state so the next solve sees it, and
        # nominate it so emptiness doesn't reclaim capacity (e.g. a node built
        # purely for buffer headroom) before the next pass records its pods
        self.cluster.update_node_claim(created)
        self.cluster.nominate_claim(created.metadata.name)
        if self.metrics is not None:
            from ... import metrics as m

            relaxed = wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY in nc.metadata.annotations
            self.metrics.counter(m.NODECLAIMS_CREATED_TOTAL).inc(
                reason=reason, nodepool=pool_name, min_values_relaxed=str(relaxed).lower()  # solverlint: ok(metric-label-cardinality): reason is a parameter whose call sites pass fixed literals ("provisioning", "static_provisioned") or a disruption command reason — all enum-bounded
            )
        return created.metadata.name
