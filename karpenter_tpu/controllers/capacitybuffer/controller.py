"""CapacityBuffer controller: resolve pod shape + target replicas into status.

Reference: capacitybuffer/controller.go:69-245 + helpers.go — resolves
podTemplateRef or scalableRef to a pod spec, derives the replica count
(max(replicas, percentage-of-workload), bounded by resource limits; limits
alone size the buffer when neither is set), and publishes ReadyForProvisioning
so the provisioner can inject virtual pods.
"""

from __future__ import annotations

import copy
import math

from ...apis.capacitybuffer import (
    BUFFER_NAME_LABEL,
    BUFFER_NAMESPACE_LABEL,
    COND_READY_FOR_PROVISIONING,
    FAKE_POD_ANNOTATION_KEY,
    FAKE_POD_ANNOTATION_VALUE,
    VIRTUAL_POD_PRIORITY,
)
from ...kube.objects import ObjectMeta, Pod, PodCondition, PodStatus
from ...utils import resources as res

SCALABLE_KINDS = ("Deployment", "ReplicaSet", "StatefulSet")


RECONCILE_SECONDS = 30.0  # controller.go:102 RequeueAfter


class CapacityBufferController:
    def __init__(self, store, clock, provisioner=None):
        self.store = store
        self.clock = clock
        self.provisioner = provisioner  # triggered after successful resolve
        self._last_run = -1e18

    def reconcile(self) -> None:
        # 30s cadence matching the reference's requeue: each pass re-resolves
        # status and re-triggers a provisioning pass, which is also what
        # refreshes buffer-pod counts after buffers shrink or disappear. New
        # buffers (no condition yet) are resolved immediately, like the
        # watch-driven reconcile on create.
        now = self.clock.now()
        buffers = self.store.list("CapacityBuffer")
        fresh = any(cb.status.conditions.get(COND_READY_FOR_PROVISIONING) is None for cb in buffers)
        if now - self._last_run < RECONCILE_SECONDS and not fresh:
            return
        self._last_run = now
        for cb in buffers:
            self._reconcile_buffer(cb)

    def _reconcile_buffer(self, cb) -> None:
        resolved = self._resolve_and_update_status(cb)
        cb.status.provisioning_strategy = cb.spec.provisioning_strategy
        self.store.update_status(cb)
        if resolved and self.provisioner is not None:
            self.provisioner.trigger(cb.metadata.uid)

    def _resolve_and_update_status(self, cb) -> bool:
        """controller.go:142-178 resolveAndUpdateStatus."""
        now = self.clock.now()
        errs = cb.runtime_validate()
        if errs:
            cb.status.conditions.set_false(COND_READY_FOR_PROVISIONING, "ResolutionFailed", "; ".join(errs), now=now)
            return False
        candidates: list[int] = []
        if cb.spec.pod_template_ref is not None:
            pt = self.store.try_get("PodTemplate", cb.spec.pod_template_ref, cb.metadata.namespace)
            if pt is None:
                cb.status.conditions.set_false(
                    COND_READY_FOR_PROVISIONING, "PodTemplateNotFound",
                    f"podtemplate {cb.spec.pod_template_ref} not found", now=now,
                )
                return False
            pod_spec = pt.template_spec
            cb.status.pod_template_ref = pt.metadata.name
            cb.status.pod_template_generation = pt.metadata.generation
        elif cb.spec.scalable_ref is not None:
            ref = cb.spec.scalable_ref
            if ref.kind not in SCALABLE_KINDS:
                cb.status.conditions.set_false(
                    COND_READY_FOR_PROVISIONING, "ResolutionFailed",
                    f"unsupported scalableRef kind {ref.kind}", now=now,
                )
                return False
            workload = self.store.try_get(ref.kind, ref.name, cb.metadata.namespace)
            if workload is None:
                cb.status.conditions.set_false(
                    COND_READY_FOR_PROVISIONING, "ScalableRefNotFound",
                    f"{ref.kind.lower()} {ref.name} not found", now=now,
                )
                return False
            pod_spec = workload.template_spec
            cb.status.pod_template_ref = None
            cb.status.pod_template_generation = None
            if cb.spec.percentage is not None and workload.replicas > 0:
                candidates.append(_percentage_replicas(workload.replicas, cb.spec.percentage))
        else:
            cb.status.conditions.set_false(
                COND_READY_FOR_PROVISIONING, "ResolutionFailed",
                "neither podTemplateRef nor scalableRef is set", now=now,
            )
            return False

        cb.status.replicas = _compute_replicas(cb, pod_spec, candidates)
        cb.status.conditions.set_true(COND_READY_FOR_PROVISIONING, "Resolved", now=now)
        return True


def _compute_replicas(cb, pod_spec, candidates: list[int]) -> int:
    """replicas/percentage combine by MAX; limits bound by MIN, or size the
    buffer alone when neither is set (controller.go:181-208)."""
    if cb.spec.replicas is not None:
        candidates.append(cb.spec.replicas)
    desired = max(candidates) if candidates else 0
    if cb.spec.limits and pod_spec is not None:
        limit_replicas = _limit_replicas(cb.spec.limits, pod_spec)
        if limit_replicas is not None:
            return min(desired, limit_replicas) if candidates else limit_replicas
    return desired


def _limit_replicas(limits: dict, pod_spec) -> int | None:
    """floor(limit/request) minimized over overlapping resources
    (helpers.go:29-57); None when limits constrain nothing."""
    shim = Pod(spec=pod_spec)
    requests = res.pod_requests(shim)
    best = None
    for name, limit in limits.items():
        req = requests.get(name)
        if req is None or req.milli == 0:
            continue
        n = int(limit.milli // req.milli)
        best = n if best is None else min(best, n)
    return best


def _percentage_replicas(scalable_replicas: int, percentage: int) -> int:
    """ceil(replicas x pct / 100); positive inputs always yield >= 1
    (helpers.go:59-67)."""
    return math.ceil(scalable_replicas * percentage / 100.0)


def resolve_buffer_pod_spec(store, cb):
    """(pod spec, template labels) behind a buffer, read from spec (not
    status) so flipping between ref kinds never serves a stale shape
    (buffers.go:92-109). Returns (None, None) when the ref is dangling."""
    if cb.spec.pod_template_ref is not None:
        pt = store.try_get("PodTemplate", cb.spec.pod_template_ref, cb.metadata.namespace)
        if pt is None:
            return None, None
        return pt.template_spec, dict(pt.template_metadata.labels)
    if cb.spec.scalable_ref is not None:
        w = store.try_get(cb.spec.scalable_ref.kind, cb.spec.scalable_ref.name, cb.metadata.namespace)
        if w is None:
            return None, None
        return w.template_spec, dict(w.template_metadata.labels)
    return None, None


def build_virtual_pods(cb, pod_spec, template_labels: dict | None = None) -> list:
    """N placeholder pods with deterministic names/uids; PVC-backed volumes are
    stripped (no real PVC will ever exist for them) and priority is pinned
    below every real pod (buffers.go:114-189). Template labels ride along so
    spread constraints / anti-affinity selecting the workload's own labels
    shape the headroom the way real replicas would."""
    count = cb.status.replicas or 0
    if count <= 0:
        return []
    spec = copy.deepcopy(pod_spec)
    spec.node_name = ""
    spec.priority = VIRTUAL_POD_PRIORITY
    spec.volumes = [v for v in spec.volumes if not (v.get("persistentVolumeClaim") or v.get("ephemeral") is not None)]
    labels = {
        **(template_labels or {}),
        BUFFER_NAME_LABEL: cb.metadata.name,
        BUFFER_NAMESPACE_LABEL: cb.metadata.namespace,
    }
    out = []
    for i in range(1, count + 1):
        out.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"capacity-buffer-{cb.metadata.name}-{i}",
                    namespace=cb.metadata.namespace,
                    uid=f"{cb.metadata.uid}-{i}",
                    annotations={FAKE_POD_ANNOTATION_KEY: FAKE_POD_ANNOTATION_VALUE},
                    labels=dict(labels),
                ),
                spec=copy.deepcopy(spec),
                status=PodStatus(
                    phase="Pending",
                    conditions=[PodCondition(type="PodScheduled", status="False", reason="Unschedulable")],
                ),
            )
        )
    return out
