"""CapacityBuffer controller (reference: pkg/controllers/capacitybuffer)."""

from .controller import CapacityBufferController, build_virtual_pods, resolve_buffer_pod_spec  # noqa: F401
