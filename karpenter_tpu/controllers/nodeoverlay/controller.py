"""NodeOverlay controller: validate overlays, build the overlay store, publish
it atomically.

Reference: pkg/controllers/nodeoverlay/controller.go:73-141 — one reconcile
evaluates every overlay against every NodePool's instance types in descending
weight order, detects equal-weight conflicts, writes ValidationSucceeded
status conditions, then swaps the published InstanceTypeStore and marks the
cluster unconsolidated so scheduling sees the new prices.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...apis.nodeoverlay import COND_VALIDATION_SUCCEEDED, order_by_weight
from ...scheduling.requirements import Requirement, Requirements
from .store import InstanceTypeStore, InternalInstanceTypeStore


class NodeOverlayController:
    def __init__(self, store, cloud_provider, instance_type_store: InstanceTypeStore, cluster, clock, options=None):
        self.store = store
        self.cloud_provider = cloud_provider
        self.instance_type_store = instance_type_store
        self.cluster = cluster
        self.clock = clock
        self.options = options
        self._dirty = True
        self._pool_spec_fingerprints: dict[str, str] = {}
        # overlay/nodepool churn re-triggers evaluation (controller.go:143-161
        # watches); everything else rides the periodic requeue
        store.watch("NodeOverlay", self._mark_dirty)
        store.watch("NodePool", self._on_node_pool)

    def _mark_dirty(self, event: str, obj) -> None:
        self._dirty = True

    def _on_node_pool(self, event: str, np_) -> None:
        # overlay matching reads only the pool spec (template labels etc.) —
        # status-only churn (e.g. the counter controller on every scale event)
        # must not re-trigger the O(pools × types × overlays) evaluation
        name = np_.metadata.name
        if event == "DELETED":
            self._pool_spec_fingerprints.pop(name, None)
            self._dirty = True
            return
        fp = repr(np_.spec)
        if self._pool_spec_fingerprints.get(name) != fp:
            self._pool_spec_fingerprints[name] = fp
            self._dirty = True

    def reconcile(self, force: bool = False) -> None:
        # the reference only registers this controller when the gate is on
        # (controllers.go:171-172)
        if self.options is not None and not self.options.feature_gates.node_overlay:
            return
        if not force and not self._dirty:
            return
        self._dirty = False

        overlays = order_by_weight(self.store.list("NodeOverlay"))
        node_pools = self.store.list("NodePool")
        pool_instance_types = {}
        for np_ in node_pools:
            its = self.cloud_provider.get_instance_types(np_)
            if its:
                pool_instance_types[np_.metadata.name] = its
        evaluated = [np_ for np_ in node_pools if np_.metadata.name in pool_instance_types]

        temp = InternalInstanceTypeStore()
        validation_failures: dict[str, str] = {}
        conflicts: set[str] = set()
        for overlay in overlays:
            errs = overlay.runtime_validate()
            if errs:
                validation_failures[overlay.metadata.name] = "; ".join(errs)
                continue
            if not self._validate_and_update(temp, evaluated, pool_instance_types, overlay):
                conflicts.add(overlay.metadata.name)
        temp.evaluated_node_pools.update(np_.metadata.name for np_ in evaluated)

        now = self.clock.now()
        for overlay in overlays:
            name = overlay.metadata.name
            if name in validation_failures:
                desired = ("False", "RuntimeValidation", validation_failures[name])
            elif name in conflicts:
                desired = ("False", "Conflict", "conflict with another overlay")
            else:
                desired = ("True", COND_VALIDATION_SUCCEEDED, "")
            cur = overlay.status.conditions.get(COND_VALIDATION_SUCCEEDED)
            # patch only on transition — an unconditional patch would fire our
            # own NodeOverlay watch and re-dirty this controller every tick
            if cur is not None and (cur.status, cur.reason, cur.message) == desired:
                continue

            def set_status(o, desired=desired):
                o.status.conditions.set(COND_VALIDATION_SUCCEEDED, desired[0], desired[1], desired[2], now=now)

            self.store.patch("NodeOverlay", name, set_status)

        # publish; wake consolidation only when the effective overlays changed
        changed = self.instance_type_store.publish_if_changed(temp)
        if changed:
            self.cluster.mark_unconsolidated()

    # -- evaluation (controller.go:163-224) ------------------------------------
    def _validate_and_update(self, temp, node_pools, pool_instance_types, overlay) -> bool:
        """Two-phase: validate against every pool first so an invalid overlay
        is never partially applied, then store (controller.go:173-180)."""
        for np_ in node_pools:
            if not self._validate_pool(temp, np_, pool_instance_types[np_.metadata.name], overlay):
                return False
        for np_ in node_pools:
            self._store_pool(temp, np_, pool_instance_types[np_.metadata.name], overlay)
        return True

    def _overlay_requirements(self, overlay) -> Requirements:
        return Requirements.from_node_selector_terms(overlay.spec.requirements)

    def _overlaid_offerings(self, np_, it, overlay_reqs: Requirements) -> list:
        """Offerings the overlay selects on this instance type, or [] when the
        overlay does not select the type at all (controller.go:226-245)."""
        it_reqs = Requirements(Requirement(wk.NODEPOOL_LABEL_KEY, "In", [np_.metadata.name]))
        it_reqs.add(*Requirements.from_labels(np_.spec.template.labels).values())
        it_reqs.add(*it.requirements.values())
        if not it_reqs.is_compatible(overlay_reqs):
            return []
        return [o for o in it.offerings if overlay_reqs.intersects(o.requirements) is None]

    def _validate_pool(self, temp, np_, its, overlay) -> bool:
        overlay_reqs = self._overlay_requirements(overlay)
        has_price = overlay.spec.price is not None or overlay.spec.price_adjustment is not None
        for it in its:
            offerings = self._overlaid_offerings(np_, it, overlay_reqs)
            if not offerings:
                continue
            if has_price and any(
                temp.is_offering_update_conflicting(np_.metadata.name, it.name, o, overlay) for o in offerings
            ):
                return False
            if overlay.spec.capacity and temp.is_capacity_update_conflicting(np_.metadata.name, it.name, overlay):
                return False
        return True

    def _store_pool(self, temp, np_, its, overlay) -> None:
        overlay_reqs = self._overlay_requirements(overlay)
        for it in its:
            offerings = self._overlaid_offerings(np_, it, overlay_reqs)
            if not offerings:
                continue
            temp.update_instance_type_offering(np_.metadata.name, it.name, overlay, offerings)
            temp.update_instance_type_capacity(np_.metadata.name, it.name, overlay)
