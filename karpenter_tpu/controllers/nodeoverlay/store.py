"""Copy-on-write instance-type overlay store.

Reference: pkg/controllers/nodeoverlay/store.go — an atomically-swapped
snapshot mapping nodePool -> instanceType -> {per-offering price update,
capacity update}. Readers (the overlay CloudProvider decorator) apply it with
selective copying: requirements/overhead are shared, offerings and capacity
are copied only when actually overridden, so a 144-type catalog costs a
handful of allocations per overlaid type.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class UnevaluatedNodePoolError(Exception):
    """GetInstanceTypes asked for a pool the overlay controller has not yet
    evaluated (cloudprovider NewUnevaluatedNodePoolError) — callers treat this
    as 'no instance types yet', retried on the next reconcile."""

    def __init__(self, pool: str):
        super().__init__(f"nodepool {pool!r} not yet evaluated by the nodeoverlay controller")
        self.pool = pool


def _offering_key(offering):
    """Canonical, collision-free identity for an offering's requirements
    (repr would truncate long In lists)."""
    return tuple(
        sorted(
            (r.key, r.complement, tuple(sorted(r.values)), r.gte, r.lte)
            for r in offering.requirements.values()
        )
    )


@dataclass
class _PriceUpdate:
    # the winning overlay's price ("1.5") or adjustment ("+10%"), store.go:30-33
    update: str | None = None
    absolute: bool = False  # True = spec.price, False = spec.priceAdjustment
    lowest_weight: int = 0


@dataclass
class _CapacityUpdate:
    update: dict = field(default_factory=dict)
    lowest_weight_resources: dict = field(default_factory=dict)
    lowest_weight: int = 0


@dataclass
class _InstanceTypeUpdate:
    price: dict[tuple, _PriceUpdate] = field(default_factory=dict)  # offering key -> update
    capacity: _CapacityUpdate | None = None


class InternalInstanceTypeStore:
    """One immutable-once-published snapshot (store.go:100-110)."""

    def __init__(self):
        self.updates: dict[str, dict[str, _InstanceTypeUpdate]] = {}  # pool -> type -> update
        self.evaluated_node_pools: set[str] = set()

    # -- write path (controller only; descending-weight order assumed) ---------
    def update_instance_type_offering(self, pool: str, type_name: str, overlay, offerings) -> None:
        """store.go:240-265 — first (heaviest) overlay to claim an offering
        wins; later equal-weight claims only record the weight for conflict
        detection."""
        if overlay.spec.price is None and overlay.spec.price_adjustment is None:
            return
        absolute = overlay.spec.price is not None
        price = overlay.spec.price if absolute else overlay.spec.price_adjustment
        itu = self.updates.setdefault(pool, {}).setdefault(type_name, _InstanceTypeUpdate())
        for o in offerings:
            key = _offering_key(o)
            existing = itu.price.get(key)
            if existing is not None:
                existing.lowest_weight = overlay.spec.weight
                continue
            itu.price[key] = _PriceUpdate(update=price, absolute=absolute, lowest_weight=overlay.spec.weight)

    def is_offering_update_conflicting(self, pool: str, type_name: str, offering, overlay) -> bool:
        """store.go:267-286 — same weight touching an already-claimed offering."""
        itu = self.updates.get(pool, {}).get(type_name)
        if itu is None:
            return False
        existing = itu.price.get(_offering_key(offering))
        if existing is None:
            return False
        return existing.lowest_weight == overlay.spec.weight

    def update_instance_type_capacity(self, pool: str, type_name: str, overlay) -> None:
        """store.go:178-210 — per-resource first-writer-wins merge."""
        if not overlay.spec.capacity:
            return
        itu = self.updates.setdefault(pool, {}).setdefault(type_name, _InstanceTypeUpdate())
        if itu.capacity is None:
            itu.capacity = _CapacityUpdate(
                update=dict(overlay.spec.capacity),
                lowest_weight_resources=dict(overlay.spec.capacity),
                lowest_weight=overlay.spec.weight,
            )
            return
        for res_name, q in overlay.spec.capacity.items():
            if res_name not in itu.capacity.update:
                itu.capacity.update[res_name] = q
        # Track ALL resources claimed at the current (lowest-seen) weight tier,
        # merging when another overlay of the same weight lands, so a later
        # equal-weight overlay conflicts with ANY earlier same-weight claimant,
        # not just the immediately preceding one. (The reference replaces the
        # set here — store.go:207 — which misses non-adjacent conflicts.)
        if itu.capacity.lowest_weight == overlay.spec.weight:
            itu.capacity.lowest_weight_resources.update(overlay.spec.capacity)
        else:
            itu.capacity.lowest_weight_resources = dict(overlay.spec.capacity)
            itu.capacity.lowest_weight = overlay.spec.weight

    def is_capacity_update_conflicting(self, pool: str, type_name: str, overlay) -> bool:
        """store.go:212-236 — equal-weight overlays touching the same resource."""
        itu = self.updates.get(pool, {}).get(type_name)
        if itu is None or itu.capacity is None:
            return False
        if itu.capacity.lowest_weight != overlay.spec.weight:
            return False
        return any(r in itu.capacity.lowest_weight_resources for r in overlay.spec.capacity)

    # -- read path -------------------------------------------------------------
    def apply(self, pool: str, it):
        """Copy-on-write application (store.go:117-149)."""
        itu = self.updates.get(pool, {}).get(it.name)
        if itu is None:
            return it
        from ...cloudprovider.types import InstanceType, Offering

        out = InstanceType(
            name=it.name,
            requirements=it.requirements,  # shared — never modified
            overhead=it.overhead,  # shared — never modified
            capacity=it.capacity,
        )
        if itu.capacity is not None and itu.capacity.update:
            out.capacity = dict(it.capacity)
            out.apply_capacity_overlay(itu.capacity.update)
        if itu.price:
            offerings = []
            for o in it.offerings:
                pu = itu.price.get(_offering_key(o))
                if pu is None:
                    offerings.append(o)  # shared — not modified
                    continue
                copied = Offering(
                    requirements=o.requirements,  # shared — immutable
                    price=o.price,
                    available=o.available,
                    reservation_capacity=o.reservation_capacity,
                    # preserve allocatable-group identity (types.go
                    # AllocatableOfferings): dropping these would silently
                    # move the copy into the base group
                    capacity_override=o.capacity_override,
                    overhead_override=o.overhead_override,
                )
                copied.apply_price_overlay(pu.update, pu.absolute)
                offerings.append(copied)
            out.offerings = offerings
        else:
            out.offerings = it.offerings  # shared
        return out


class InstanceTypeStore:
    """The published pointer readers go through (store.go:45-89). CPython
    attribute assignment is atomic, giving the same swap semantics as the
    reference's atomic.Pointer."""

    def __init__(self):
        self._store = InternalInstanceTypeStore()

    def update_store(self, new_store: InternalInstanceTypeStore) -> None:
        self._store = new_store

    def publish_if_changed(self, new_store: InternalInstanceTypeStore) -> bool:
        """Swap and report whether the effective content differs from the
        previous snapshot (so callers can skip consolidation wakeups)."""
        old = self._store
        self._store = new_store
        return (
            old.updates != new_store.updates or old.evaluated_node_pools != new_store.evaluated_node_pools
        )

    def apply_all(self, pool: str, its: list) -> list:
        store = self._store
        if pool not in store.evaluated_node_pools:
            raise UnevaluatedNodePoolError(pool)
        if pool not in store.updates:
            return its
        return [store.apply(pool, it) for it in its]

    def apply(self, pool: str, it):
        store = self._store
        if pool not in store.evaluated_node_pools:
            raise UnevaluatedNodePoolError(pool)
        return store.apply(pool, it)

    def reset(self) -> None:
        self._store = InternalInstanceTypeStore()
