from .controller import NodeOverlayController
from .store import InstanceTypeStore, InternalInstanceTypeStore, UnevaluatedNodePoolError

__all__ = [
    "NodeOverlayController",
    "InstanceTypeStore",
    "InternalInstanceTypeStore",
    "UnevaluatedNodePoolError",
]
