"""NodePool readiness controller.

Reference: pkg/controllers/nodepool/readiness/controller.go:60-105 — mirrors
the referenced NodeClass's Ready condition onto the NodePool as
NodeClassReady: NotFound/Terminating/Unknown/False all block readiness.
"""

from __future__ import annotations

from ...apis.conditions import FALSE, TRUE
from ...apis.nodepool import COND_NODEPOOL_READY, COND_NODEPOOL_VALIDATION_SUCCEEDED

COND_NODECLASS_READY = "NodeClassReady"


class NodePoolReadinessController:
    def __init__(self, store, clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> None:
        for np in self.store.list("NodePool"):
            ref = np.spec.template.node_class_ref
            kind = ref["kind"] if isinstance(ref, dict) else ref.kind
            name = ref["name"] if isinstance(ref, dict) else ref.name
            node_class = self.store.try_get(kind, name)
            changed = self._set_conditions(np, node_class)
            if changed:
                self.store.update_status(np)

    def _set_conditions(self, np, node_class) -> bool:
        now = self.clock.now()
        conds = np.status.conditions
        if node_class is None:
            changed = conds.set_false(COND_NODECLASS_READY, "NodeClassNotFound", "NodeClass not found on cluster", now=now)
        elif node_class.metadata.deletion_timestamp is not None:
            changed = conds.set_false(COND_NODECLASS_READY, "NodeClassTerminating", "NodeClass is Terminating", now=now)
        else:
            ready = node_class.status.conditions.get("Ready")
            if ready is None:
                # node classes with no readiness machinery (KWOK) count ready
                changed = conds.set_true(COND_NODECLASS_READY, now=now)
            elif ready.status == TRUE:
                changed = conds.set_true(COND_NODECLASS_READY, now=now)
            elif ready.status == FALSE:
                changed = conds.set_false(COND_NODECLASS_READY, ready.reason, ready.message, now=now)
            else:
                changed = conds.set_false(COND_NODECLASS_READY, "NodeClassReadinessUnknown", "Node Class Readiness Unknown", now=now)
        # roll up the overall Ready condition from the per-aspect conditions
        aspects = [COND_NODECLASS_READY, COND_NODEPOOL_VALIDATION_SUCCEEDED]
        failed = [a for a in aspects if conds.is_false(a)]
        if failed:
            changed |= conds.set_false(COND_NODEPOOL_READY, failed[0], now=now)
        else:
            changed |= conds.set_true(COND_NODEPOOL_READY, now=now)
        return changed
