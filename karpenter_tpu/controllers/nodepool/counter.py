"""NodePool resource counter.

Reference: pkg/controllers/nodepool/counter/controller.go:74-97 — copies the
cluster-state per-pool resource totals into NodePool.status.resources and the
node count into status.node_count, gated on cluster sync so a fresh restart
can't patch a lower count over the truth.
"""

from __future__ import annotations

from ...utils.quantity import Quantity

BASE_RESOURCES = ("cpu", "memory", "pods", "ephemeral-storage", "nodes")


class NodePoolCounterController:
    def __init__(self, store, cluster):
        self.store = store
        self.cluster = cluster

    def reconcile(self) -> None:
        if not self.cluster.synced():
            return
        for np in self.store.list("NodePool"):
            resources = {name: Quantity(0) for name in BASE_RESOURCES}
            resources.update(self.cluster.nodepool_resources(np.metadata.name))
            count = self.cluster.nodepool_node_count(np.metadata.name)
            # the reference reports the count as the "nodes" resource too, which
            # is how per-pool node-count limits are expressed (counter.go:87-90)
            resources["nodes"] = Quantity.from_value(count)
            if np.status.resources != resources or np.status.node_count != count:
                def apply(obj, resources=resources, count=count):
                    obj.status.resources = resources
                    obj.status.node_count = count

                self.store.patch("NodePool", np.metadata.name, apply)
