from .hash import NodePoolHashController
from .counter import NodePoolCounterController
from .readiness import NodePoolReadinessController
from .registrationhealth import NodePoolRegistrationHealthController
from .validation import NodePoolValidationController

__all__ = [
    "NodePoolHashController",
    "NodePoolCounterController",
    "NodePoolReadinessController",
    "NodePoolRegistrationHealthController",
    "NodePoolValidationController",
]
