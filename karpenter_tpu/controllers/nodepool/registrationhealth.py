"""NodePool registration-health controller.

Reference: pkg/controllers/nodepool/registrationhealth/controller.go:66-111 —
hydrates the in-memory health tracker from a persisted condition after a
restart, and resets NodeRegistrationHealthy to Unknown whenever the NodePool
spec (generation) or its NodeClass generation changes. The lifecycle
registration/liveness reconcilers feed successes/failures into the tracker.
"""

from __future__ import annotations

from ...apis.conditions import UNKNOWN
from ...apis.nodepool import COND_NODE_REGISTRATION_HEALTHY
from ...state import nodepoolhealth


class NodePoolRegistrationHealthController:
    def __init__(self, store, np_state: nodepoolhealth.NodePoolHealthState, clock):
        self.store = store
        self.np_state = np_state
        self.clock = clock
        # pool uid -> (pool generation, node class generation) last observed
        self._observed: dict[str, tuple[int, int]] = {}

    def reconcile(self) -> None:
        pools = self.store.list("NodePool")
        live = {np.metadata.uid for np in pools}
        self.np_state.prune(live)
        self._observed = {uid: v for uid, v in self._observed.items() if uid in live}
        for np in pools:
            ref = np.spec.template.node_class_ref
            kind = ref["kind"] if isinstance(ref, dict) else ref.kind
            name = ref["name"] if isinstance(ref, dict) else ref.name
            node_class = self.store.try_get(kind, name)
            if node_class is None:
                continue
            uid = np.metadata.uid
            cond = np.status.conditions.get(COND_NODE_REGISTRATION_HEALTHY)

            # restart hydration: persisted condition pre-populates the tracker
            if self.np_state.status(uid) == nodepoolhealth.STATUS_UNKNOWN and cond is not None:
                if np.status.conditions.is_true(COND_NODE_REGISTRATION_HEALTHY):
                    self.np_state.set_status(uid, nodepoolhealth.STATUS_HEALTHY)
                elif np.status.conditions.is_false(COND_NODE_REGISTRATION_HEALTHY):
                    self.np_state.set_status(uid, nodepoolhealth.STATUS_UNHEALTHY)

            observed = (np.metadata.generation, node_class.metadata.generation)
            if cond is None or self._observed.get(uid) not in (None, observed):
                def apply(obj):
                    obj.status.conditions.set(COND_NODE_REGISTRATION_HEALTHY, UNKNOWN, now=self.clock.now())

                self.store.patch("NodePool", np.metadata.name, apply)
                self.np_state.set_status(uid, nodepoolhealth.STATUS_UNKNOWN)
            self._observed[uid] = observed
