"""NodePool runtime-validation controller.

Reference: pkg/controllers/nodepool/validation/controller.go:59-82 — runs
RuntimeValidate on each NodePool and sets the ValidationSucceeded condition.
The provisioner skips pools that fail validation.
"""

from __future__ import annotations

from ...apis.nodepool import COND_NODEPOOL_VALIDATION_SUCCEEDED
from ...apis.validation import runtime_validate


class NodePoolValidationController:
    def __init__(self, store, clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> None:
        for np in self.store.list("NodePool"):
            errs = runtime_validate(np)
            conds = np.status.conditions
            if errs:
                changed = conds.set_false(
                    COND_NODEPOOL_VALIDATION_SUCCEEDED, "NodePoolValidationFailed", "; ".join(errs), now=self.clock.now()
                )
            else:
                changed = conds.set_true(COND_NODEPOOL_VALIDATION_SUCCEEDED, now=self.clock.now())
            if changed:
                self.store.update_status(np)
