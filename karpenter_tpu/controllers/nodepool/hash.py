"""NodePool drift-hash controller.

Reference: pkg/controllers/nodepool/hash/controller.go:66-129 — stamps the
static-drift hash + hash-version annotations on each NodePool, and when the
hash *version* changes (a breaking change to the hash computation), re-stamps
every non-drifted NodeClaim of the pool so stale hashes don't read as drift.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...apis.nodeclaim import COND_DRIFTED

# Bump when the fields included in NodePool.hash() change incompatibly
# (reference: nodepool.go:334 NodePoolHashVersion).
NODEPOOL_HASH_VERSION = "v1"


class NodePoolHashController:
    def __init__(self, store):
        self.store = store

    def reconcile(self) -> None:
        for np in self.store.list("NodePool"):
            if np.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY) != NODEPOOL_HASH_VERSION:
                self._update_node_claim_hashes(np)
            want = {
                wk.NODEPOOL_HASH_ANNOTATION_KEY: np.hash(),
                wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY: NODEPOOL_HASH_VERSION,
            }
            if any(np.metadata.annotations.get(k) != v for k, v in want.items()):
                def apply(obj, want=want):
                    obj.metadata.annotations.update(want)

                self.store.patch("NodePool", np.metadata.name, apply)

    def _update_node_claim_hashes(self, np) -> None:
        """hash/controller.go:96-129: on hash-version change, adopt the pool's
        new hash onto claims — except claims already Drifted, which stay
        drifted (we can no longer tell whether they've un-drifted)."""
        for nc in self.store.list("NodeClaim"):
            if nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) != np.metadata.name:
                continue
            if nc.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY) == NODEPOOL_HASH_VERSION:
                continue

            def apply(obj, np=np):
                obj.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = NODEPOOL_HASH_VERSION
                if obj.status.conditions.get(COND_DRIFTED) is None:
                    obj.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = np.hash()

            self.store.patch("NodeClaim", nc.metadata.name, apply)
