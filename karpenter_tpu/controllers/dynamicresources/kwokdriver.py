"""Fake DRA driver: publish ResourceSlices for nodes from a DRAConfig.

Reference: dra-kwok-driver/ — a standalone binary watching a DRAConfig CRD and
creating ResourceSlices for matching (KWOK) nodes so DRA flows can run without
real device plugins. Here it's an in-process controller: each registered node
matching a config's node selector gets one slice per config; slices for gone
nodes are garbage-collected.
"""

from __future__ import annotations

import copy
import hashlib
from dataclasses import dataclass, field

from ...apis import labels as wk
from ...kube.objects import ObjectMeta, ResourceSlice, match_label_selector


@dataclass
class DRAConfig:
    """Which devices to fake onto which nodes
    (dra-kwok-driver/pkg/apis DRAConfig)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    driver: str = "fake.dra.karpenter.sh"
    node_selector: dict | None = None  # metav1 label selector; None = all nodes
    devices: list = field(default_factory=list)  # [kube.objects.Device]
    kind: str = "DRAConfig"


class DRAKwokDriver:
    def __init__(self, store):
        self.store = store

    def reconcile(self) -> None:
        configs = self.store.list("DRAConfig")
        nodes = [
            n
            for n in self.store.list("Node")
            if n.metadata.labels.get(wk.NODE_REGISTERED_LABEL_KEY) == "true"
            and n.metadata.deletion_timestamp is None
        ]
        # key on the (node, config) PAIR, not the joined name: names built as
        # f"{node}-{config}" collide across distinct pairs when the parts
        # contain dashes ("a-b"+"c" vs "a"+"b-c"); the pair rides in labels
        # and a short digest keeps the object name unique
        want: dict[tuple[str, str], tuple] = {}
        for cfg in configs:
            for node in nodes:
                if cfg.node_selector is not None and not match_label_selector(cfg.node_selector, node.metadata.labels):
                    continue
                want[(node.metadata.name, cfg.metadata.name)] = (cfg, node)
        have: dict[tuple[str, str], ResourceSlice] = {}
        for sl in self.store.list("ResourceSlice"):
            cfg_name = sl.metadata.labels.get("dra.karpenter.sh/config")
            if cfg_name:
                have[(sl.metadata.labels.get("dra.karpenter.sh/node", sl.node_name), cfg_name)] = sl
        for (node_name, cfg_name), (cfg, node) in want.items():
            existing = have.get((node_name, cfg_name))
            if existing is None:
                digest = hashlib.sha1(f"{node_name}\x00{cfg_name}".encode()).hexdigest()[:8]
                self.store.create(
                    ResourceSlice(
                        metadata=ObjectMeta(
                            name=f"{node_name}-{cfg_name}-{digest}",
                            labels={"dra.karpenter.sh/config": cfg_name, "dra.karpenter.sh/node": node_name},
                        ),
                        driver=cfg.driver,
                        pool_name=node.metadata.name,
                        node_name=node.metadata.name,
                        devices=copy.deepcopy(cfg.devices),
                    )
                )
            elif existing.devices != cfg.devices or existing.driver != cfg.driver:
                # config edits must reach already-published slices

                def apply(sl, cfg=cfg):
                    sl.driver = cfg.driver
                    sl.devices = copy.deepcopy(cfg.devices)
                    sl.pool_generation += 1

                self.store.patch("ResourceSlice", existing.metadata.name, apply)
        for key, sl in have.items():
            if key not in want:
                self.store.try_delete("ResourceSlice", sl.metadata.name)
