"""DRA controllers (reference: pkg/controllers/dynamicresources +
dra-kwok-driver)."""

from .deviceallocation import DeviceAllocationController  # noqa: F401
from .kwokdriver import DRAConfig, DRAKwokDriver  # noqa: F401
