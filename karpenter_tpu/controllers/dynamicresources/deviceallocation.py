"""Device-allocation controller: persist DRA allocations to ResourceClaim
status.

Reference: pkg/controllers/dynamicresources/deviceallocation/controller.go —
the scheduler's in-memory device decisions become durable by writing
status.allocation (devices + node) and status.reservedFor onto the
ResourceClaims of bound pods; claims whose reserving pods are gone get
released so their devices free up.
"""

from __future__ import annotations

from ...scheduling.dynamicresources import Allocator, resolve_pod_claims
from ...utils import pods as pod_utils


class DeviceAllocationController:
    def __init__(self, store, cluster, clock):
        self.store = store
        self.cluster = cluster
        self.clock = clock

    def reconcile(self) -> None:
        self._allocate_for_bound_pods()
        self._release_orphaned_claims()

    def _allocate_for_bound_pods(self) -> None:
        allocator = None
        for pod in self.store.list("Pod"):
            if not pod.spec.resource_claims or not pod.spec.node_name or not pod_utils.is_active(pod):
                continue
            claims, err = resolve_pod_claims(self.store, pod)
            if err is not None:
                continue
            for rc in claims:
                stored = self.store.try_get("ResourceClaim", rc.metadata.name, rc.metadata.namespace)
                if stored is not None and stored.status.allocation:
                    self._ensure_reserved(stored, pod)
                    continue
                if allocator is None:
                    allocator = Allocator(self.store, self.clock)
                result, aerr = allocator.allocate_for_node(pod.spec.node_name, [rc])
                if aerr is not None:
                    continue
                allocator.commit_for_node(pod.spec.node_name, result)
                self._write_allocation(rc, pod, result)

    def _write_allocation(self, rc, pod, result) -> None:
        devices = [
            {
                "request": name,
                "driver": ref.driver,
                "pool": ref.pool,
                "device": ref.device.name,
                **({"consumedCapacity": cap} if cap else {}),
                **({"multiAllocatable": True} if ref.device.allow_multiple_allocations else {}),
            }
            for name, ref, cap in result.picks.get(rc.key(), [])
        ]
        stored = self.store.try_get("ResourceClaim", rc.metadata.name, rc.metadata.namespace)
        if stored is None:
            # template-derived claim materializes on first allocation
            rc.status.allocation = {"nodeName": pod.spec.node_name, "devices": devices}
            rc.status.reserved_for = [pod.metadata.uid]
            self.store.create(rc)
            return

        def apply(obj):
            obj.status.allocation = {"nodeName": pod.spec.node_name, "devices": devices}
            if pod.metadata.uid not in obj.status.reserved_for:
                obj.status.reserved_for.append(pod.metadata.uid)

        self.store.patch("ResourceClaim", rc.metadata.name, apply, namespace=rc.metadata.namespace)

    def _ensure_reserved(self, rc, pod) -> None:
        if pod.metadata.uid in rc.status.reserved_for:
            return

        def apply(obj):
            if pod.metadata.uid not in obj.status.reserved_for:
                obj.status.reserved_for.append(pod.metadata.uid)

        self.store.patch("ResourceClaim", rc.metadata.name, apply, namespace=rc.metadata.namespace)

    def _release_orphaned_claims(self) -> None:
        active_uids = {p.metadata.uid for p in self.store.list("Pod") if pod_utils.is_active(p)}
        for rc in self.store.list("ResourceClaim"):
            if not rc.status.allocation and not rc.status.reserved_for:
                continue
            still = [uid for uid in rc.status.reserved_for if uid in active_uids]
            if still == rc.status.reserved_for:
                continue

            def apply(obj, still=still):
                obj.status.reserved_for = list(still)
                if not still:
                    obj.status.allocation = None  # devices free up

            self.store.patch("ResourceClaim", rc.metadata.name, apply, namespace=rc.metadata.namespace)
