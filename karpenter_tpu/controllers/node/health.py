"""Node health/repair controller (NodeRepair feature gate).

Reference: pkg/controllers/node/health/controller.go:64-155 — nodes whose
conditions match a CloudProvider RepairPolicy for longer than the policy's
toleration window are force-repaired by deleting their NodeClaim, with the
termination-grace-period annotation stamped so the drain cannot wedge.
Repair is vetoed while >20% of the pool's (or cluster's, for standalone
claims) nodes are unhealthy — mass-outage protection.
"""

from __future__ import annotations

import math

from ...apis import labels as wk
from ...utils import pods as pod_utils

ALLOWED_UNHEALTHY_PERCENT = 20


class HealthController:
    def __init__(self, store, cluster, cloud_provider, clock, recorder=None, metrics=None, enabled=True):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        self.metrics = metrics
        self.enabled = enabled

    def reconcile(self) -> None:
        if not self.enabled:
            return
        policies = self.cloud_provider.repair_policies()
        if not policies:
            return
        nodes = self.store.list("Node")
        claims_by_provider = {c.status.provider_id: c for c in self.store.list("NodeClaim") if c.status.provider_id}
        # one pass over conditions; the veto math below reuses this set
        unhealthy = {n.metadata.name: self._find_unhealthy(n, policies) for n in nodes}
        unhealthy = {name: v for name, v in unhealthy.items() if v[0] is not None}
        for node in nodes:
            nc = claims_by_provider.get(node.spec.provider_id)
            if nc is None or nc.metadata.deletion_timestamp is not None:
                continue
            cond, toleration = unhealthy.get(node.metadata.name, (None, 0.0))
            if cond is None:
                continue
            if self.clock.now() < cond.last_transition_time + toleration:
                continue  # not yet past the toleration window
            pool_name = nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY)
            if not self._healthy_enough(nodes, unhealthy, pool_name):
                if self.recorder is not None:
                    scope = f"nodepool {pool_name}" if pool_name else "cluster"
                    self.recorder.publish(
                        node,
                        "NodeRepairBlocked",
                        f"more than {ALLOWED_UNHEALTHY_PERCENT}% of nodes in the {scope} are unhealthy",
                        type_="Warning",
                    )
                continue
            self._repair(node, nc, cond)

    @staticmethod
    def _find_unhealthy(node, policies):
        """First node condition matching a repair policy (controller.go
        findUnhealthyConditions)."""
        for policy in policies:
            for cond in node.status.conditions:
                if cond.type == policy.condition_type and cond.status == policy.condition_status:
                    return cond, policy.toleration_duration
        return None, 0.0

    @staticmethod
    def _healthy_enough(nodes, unhealthy: dict, pool_name: str | None) -> bool:
        """<=20% (ceil) of the pool's nodes may be unhealthy for repair to
        proceed (controller.go:236-263)."""
        scope = [
            n
            for n in nodes
            if pool_name is None or n.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == pool_name
        ]
        count = sum(1 for n in scope if n.metadata.name in unhealthy)
        threshold = math.ceil(ALLOWED_UNHEALTHY_PERCENT * len(scope) / 100)
        return count <= threshold

    def _repair(self, node, nc, cond) -> None:
        # force-drain via the termination-grace-period annotation: an already-
        # expired deadline lets the terminator bypass blocked PDBs/do-not-disrupt
        deadline = self.clock.now()

        def stamp(obj):
            obj.metadata.annotations[wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY] = str(deadline)

        self.store.patch("NodeClaim", nc.metadata.name, stamp)
        self.store.patch("Node", node.metadata.name, stamp)
        self.store.try_delete("NodeClaim", nc.metadata.name)
        if self.recorder is not None:
            self.recorder.publish(
                node, "NodeRepair", f"repairing node: condition {cond.type}={cond.status} past toleration"
            )
        if self.metrics is not None:
            from ... import metrics as m

            labels = dict(
                reason="unhealthy",
                nodepool=node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
                capacity_type=node.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY, ""),
            )
            self.metrics.counter(m.NODECLAIMS_DISRUPTED_TOTAL).inc(**labels)
            reschedulable = [
                p
                for p in self.store.list("Pod")
                if p.spec.node_name == node.metadata.name and pod_utils.is_reschedulable(p)
            ]
            self.metrics.counter(m.PODS_DISRUPTION_INITIATED_TOTAL).inc(len(reschedulable), **labels)
