"""Node termination: finalizer-driven drain (reference:
node/termination/{controller,terminator}.go — call stack SURVEY.md §3.4).

Flow: node deleted (disruption queue or user) -> taint disrupted ->
evict pods in priority groups (PDB-aware) -> cloud instance deleted ->
finalizer released.

Eviction in this hermetic substrate models controller-recreated workloads:
an evicted pod is reset to Pending/unbound (as a ReplicaSet would recreate
it), which feeds straight back into the provisioner's pending-pod batch.
DaemonSet- and node-owned pods are deleted with their node.
"""

from __future__ import annotations

from ...apis import labels as wk
from ...cloudprovider.errors import NodeClaimNotFoundError
from ...scheduling.taints import NO_SCHEDULE, Taint, taints_tolerate_pod
from ...utils import pods as pod_utils
from ...utils.pdb import PDBLimits

DISRUPTED_TAINT = Taint(key=wk.DISRUPTED_TAINT_KEY, effect=NO_SCHEDULE)
# well-known k8s label: service controllers drop labeled nodes from external
# load-balancer target groups (corev1.LabelNodeExcludeBalancers)
EXCLUDE_BALANCERS_LABEL_KEY = "node.kubernetes.io/exclude-from-external-load-balancers"


class TerminationController:
    def __init__(self, store, cluster, cloud_provider, clock, recorder=None, metrics=None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        self.metrics = metrics

    def reconcile(self) -> None:
        for node in self.store.list("Node"):
            if node.metadata.deletion_timestamp is None:
                continue
            if wk.TERMINATION_FINALIZER not in node.metadata.finalizers:
                continue
            # per-item error isolation (controller-runtime semantics): a
            # transient cloud delete failure keeps the finalizer and retries
            # next round; it never kills the rest of the drain fleet
            try:
                self._terminate(node)
            except Exception as e:  # noqa: BLE001
                if self.recorder is not None:
                    self.recorder.publish(node, "TerminationError", str(e), type_="Warning")

    def _terminate(self, node) -> None:
        name = node.metadata.name
        # 1. taint so nothing new schedules, and pull the node out of
        # load-balancer target groups BEFORE draining starts — connections
        # must stop arriving before the instance disappears
        # (terminator.go:55-75; aws/karpenter#2518)
        needs_taint = not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        needs_lb_label = node.metadata.labels.get(EXCLUDE_BALANCERS_LABEL_KEY) != "karpenter"
        if needs_taint or needs_lb_label:
            def taint(n):
                if not any(t.key == wk.DISRUPTED_TAINT_KEY for t in n.spec.taints):
                    n.spec.taints.append(DISRUPTED_TAINT)
                n.metadata.labels[EXCLUDE_BALANCERS_LABEL_KEY] = "karpenter"

            self.store.patch("Node", name, taint)

        # 2. drain: evict by descending priority groups (terminator.go:96-138).
        # Pods that TOLERATE the disruption taint opted into riding the node
        # down — they are not evicted and are deleted with the instance
        # (podutils IsWaitingEviction; suite_test.go:225-288)
        bound = [p for p in self.store.list("Pod") if p.spec.node_name == name and pod_utils.is_active(p)]
        evictable = [p for p in bound if self._drainable(p)]
        tgp_expired = self._grace_period_expired(node)
        if evictable:
            pdb = PDBLimits(self.store)
            # evict the LOWEST priority group first; critical pods drain last
            # (terminator.go groupPodsByPriority / graceful-shutdown order)
            groups = sorted({(p.spec.priority or 0) for p in evictable})
            first = [p for p in evictable if (p.spec.priority or 0) == groups[0]]
            progressed = False
            for p in first:
                if not tgp_expired:
                    if pod_utils.is_eviction_blocked(p, self.clock.now()):
                        continue  # do-not-disrupt pods wait for TGP
                    ok, _ = pdb.can_evict(p)
                    if not ok:
                        continue
                    pdb.note_eviction(p)
                self._evict(p)
                progressed = True
            if not progressed and not tgp_expired:
                return  # blocked; retry next reconcile
            if len(evictable) > len(first) or not progressed:
                return  # more groups remain; drain continues next reconcile

        # recheck: everything evictable gone?
        still = [p for p in self.store.list("Pod") if p.spec.node_name == name and pod_utils.is_active(p) and self._drainable(p)]
        if still and not tgp_expired:
            return

        # 3. wait for VolumeAttachments of drain-able pods to detach before
        # the instance goes away, so PV-backed workloads can re-attach
        # elsewhere (controller.go:235-280 awaitVolumeDetachment); an elapsed
        # termination grace period skips the wait
        if not tgp_expired:
            pending = self._pending_volume_attachments(node)
            if pending:
                if self.recorder is not None:
                    self.recorder.publish(
                        node,
                        "AwaitingVolumeDetachment",
                        f"awaiting deletion of {len(pending)} volume attachment(s)",
                    )
                return

        # 4. delete daemon pods with the node
        for p in self.store.list("Pod"):
            if p.spec.node_name == name:
                self.store.try_delete("Pod", p.metadata.name, namespace=p.metadata.namespace)

        # 5. cloud delete + release finalizer (controller.go + [cloud boundary])
        claim = self._claim_for(node)
        if claim is not None:
            try:
                self.cloud_provider.delete(claim)
            except NodeClaimNotFoundError:
                pass
        self.store.remove_finalizer("Node", name, wk.TERMINATION_FINALIZER)
        if self.metrics is not None:
            from ... import metrics as m

            pool = node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            zone = node.metadata.labels.get(wk.ZONE_LABEL_KEY, "")
            self.metrics.counter(m.NODES_TERMINATED_TOTAL).inc(nodepool=pool, zone=zone)
            if claim is not None:
                self.metrics.counter(m.NODECLAIMS_TERMINATED_TOTAL).inc(
                    nodepool=pool,
                    capacity_type=node.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY, ""),
                    zone=zone,
                )
        if self.recorder is not None:
            self.recorder.publish(node, "NodeTerminated", f"node {name} drained and terminated")

    def _pending_volume_attachments(self, node) -> list:
        """VolumeAttachments that must detach before instance deletion.
        Attachments whose PV backs a NON-drainable pod (do-not-disrupt,
        daemon/node-owned — pods that ride the node down) don't block
        (controller.go:309-355 filterVolumeAttachments)."""
        name = node.metadata.name
        vas = [va for va in self.store.list("VolumeAttachment") if va.node_name == name]
        if not vas:
            return []
        undrainable_pvs: set[str] = set()
        for p in self.store.list("Pod"):
            if p.spec.node_name != name or not pod_utils.is_active(p):
                continue
            if pod_utils.is_eviction_blocked(p, self.clock.now()) or not self._drainable(p):
                for v in p.spec.volumes:
                    ref = v.get("persistentVolumeClaim")
                    if not ref:
                        continue
                    pvc = self.store.try_get("PersistentVolumeClaim", ref.get("claimName", ""), p.metadata.namespace)
                    if pvc is not None and pvc.volume_name:
                        undrainable_pvs.add(pvc.volume_name)
        return [va for va in vas if va.persistent_volume_name not in undrainable_pvs]

    @staticmethod
    def _drainable(pod) -> bool:
        """Pods the drain evicts: not daemon/node-owned, and not tolerating
        the karpenter disrupted taint (tolerating pods ride the node down)."""
        if pod_utils.is_owned_by_daemonset(pod) or pod_utils.is_owned_by_node(pod):
            return False
        return taints_tolerate_pod([DISRUPTED_TAINT], pod) is not None

    def _evict(self, pod) -> None:
        """Evict = reset to pending (modeling controller recreation)."""

        def apply(p):
            p.spec.node_name = ""
            p.status.phase = "Pending"
            p.status.start_time = None

        self.store.patch("Pod", pod.metadata.name, apply, namespace=pod.metadata.namespace)

    def _grace_period_expired(self, node) -> bool:
        raw = node.metadata.annotations.get(wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY)
        if raw is None:
            return False
        try:
            return self.clock.now() >= float(raw)
        except ValueError:
            return False

    def _claim_for(self, node):
        for nc in self.store.list("NodeClaim"):
            if nc.status.provider_id and nc.status.provider_id == node.spec.provider_id:
                return nc
        return None
