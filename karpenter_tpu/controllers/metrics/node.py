"""Node observability controller.

Reference: pkg/controllers/metrics/node/controller.go — per-node gauges:
allocatable, total pod/daemon requests, utilization percent, lifetime.
"""

from __future__ import annotations

from ... import metrics as m
from ...apis import labels as wk


class NodeMetricsController:
    def __init__(self, store, cluster, clock, registry):
        self.store = store
        self.cluster = cluster
        self.clock = clock
        self.registry = registry

    def reconcile(self) -> None:
        allocatable = self.registry.gauge(m.NODES_ALLOCATABLE)
        pod_req = self.registry.gauge(m.NODES_TOTAL_POD_REQUESTS)
        daemon_req = self.registry.gauge(m.NODES_TOTAL_DAEMON_REQUESTS)
        util = self.registry.gauge(m.NODES_UTILIZATION)
        lifetime = self.registry.gauge(m.NODES_CURRENT_LIFETIME)
        for g in (allocatable, pod_req, daemon_req, util, lifetime):
            g.reset()
        for sn in self.cluster.nodes():
            labels = sn.labels()
            pool = labels.get(wk.NODEPOOL_LABEL_KEY, "")
            zone = labels.get(wk.ZONE_LABEL_KEY, "")
            name = sn.name()
            alloc = sn.allocatable()
            requested = sn.total_pod_requests()
            daemon = sn.total_daemon_requests()
            for res_name, q in alloc.items():
                allocatable.set(q.as_float(), node_name=name, nodepool=pool, resource_type=res_name, zone=zone)
                req = requested.get(res_name)
                if req is not None:
                    pod_req.set(req.as_float(), node_name=name, nodepool=pool, resource_type=res_name)
                    if q.as_float() > 0:
                        util.set(100.0 * req.as_float() / q.as_float(), node_name=name, nodepool=pool, resource_type=res_name)
            for res_name, q in daemon.items():
                daemon_req.set(q.as_float(), node_name=name, nodepool=pool, resource_type=res_name)
            created = (
                sn.node.metadata.creation_timestamp
                if sn.node is not None
                else sn.node_claim.metadata.creation_timestamp if sn.node_claim is not None else 0.0
            )
            lifetime.set(self.clock.now() - created, node_name=name, nodepool=pool)
