"""Pod observability controller.

Reference: pkg/controllers/metrics/pod/controller.go — tracks pod scheduling
latency: creation->bound, creation->running, and the live unbound gauge. The
bound/startup histograms are the headline scheduling-latency metrics.
"""

from __future__ import annotations

from ... import metrics as m
from ...apis import labels as wk


class PodMetricsController:
    def __init__(self, store, clock, registry):
        self.store = store
        self.clock = clock
        self.registry = registry
        self._bound_seen: set[str] = set()
        self._started_seen: set[str] = set()

    def reconcile(self) -> None:
        unbound = self.registry.gauge(m.PODS_UNBOUND_TIME)
        state = self.registry.gauge(m.PODS_STATE)
        unbound.reset()
        state.reset()
        live = set()
        for pod in self.store.list("Pod"):
            key = pod.key()
            live.add(key)
            created = pod.metadata.creation_timestamp
            state.set(1, name=pod.metadata.name, namespace=pod.metadata.namespace, phase=pod.status.phase)  # solverlint: ok(metric-label-cardinality): phase is the k8s PodPhase enum (Pending/Running/Succeeded/Failed/Unknown) — bounded by the API contract, not by this module
            if not pod.spec.node_name:
                unbound.set(self.clock.now() - created, name=pod.metadata.name, namespace=pod.metadata.namespace)
                continue
            if key not in self._bound_seen:
                self._bound_seen.add(key)
                self.registry.histogram(m.PODS_BOUND_DURATION).observe(self.clock.now() - created)
                node = self.store.try_get("Node", pod.spec.node_name)
                if node is not None and wk.NODEPOOL_LABEL_KEY in node.metadata.labels:
                    self.registry.histogram(m.PODS_PROVISIONING_BOUND_DURATION).observe(self.clock.now() - created)
            if pod.status.phase == "Running" and key not in self._started_seen:
                self._started_seen.add(key)
                self.registry.histogram(m.PODS_STARTUP_DURATION).observe(self.clock.now() - created)
        self._bound_seen &= live
        self._started_seen &= live
