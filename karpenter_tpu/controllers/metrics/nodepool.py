"""NodePool observability controller.

Reference: pkg/controllers/metrics/nodepool/controller.go — per-pool
usage/limit gauges by resource type.
"""

from __future__ import annotations

from ... import metrics as m


class NodePoolMetricsController:
    def __init__(self, store, registry, cluster_cost=None):
        self.store = store
        self.registry = registry
        self.cluster_cost = cluster_cost

    def reconcile(self) -> None:
        usage = self.registry.gauge(m.NODEPOOL_USAGE)
        limit = self.registry.gauge(m.NODEPOOL_LIMIT)
        cost = self.registry.gauge(m.NODEPOOL_COST_TOTAL)
        usage.reset()
        limit.reset()
        cost.reset()
        for np in self.store.list("NodePool"):
            for res_name, q in np.status.resources.items():
                usage.set(q.as_float(), nodepool=np.metadata.name, resource_type=res_name)
            for res_name, q in np.spec.limits.items():
                limit.set(q.as_float(), nodepool=np.metadata.name, resource_type=res_name)
            if self.cluster_cost is not None:
                cost.set(self.cluster_cost.get_nodepool_cost(np.metadata.name), nodepool=np.metadata.name)
