from .node import NodeMetricsController
from .nodepool import NodePoolMetricsController
from .pod import PodMetricsController

__all__ = ["NodeMetricsController", "NodePoolMetricsController", "PodMetricsController"]
