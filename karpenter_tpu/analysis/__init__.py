"""solverlint: repo-specific static analysis for the tensor solver.

The tensor path's speed rests on invariants no general-purpose linter knows
about: `mask_encode`/`_try_delta_encode` share encode arrays BY REFERENCE
(one in-place write corrupts the cached delta base), the pack must never
host-sync mid-kernel or loop Python-side over the pod axis, every fallback
reason family must carry a hybrid tier (GLOBAL ones justified), and solver
metric labels must stay enum-bounded. The serving stack's CORRECTNESS rests
on lock conventions the same way: guarded fields, a sanctioned lock order,
reviewed thread seams, instrumentable primitives (racecheck, ISSUE 11).
This package machine-checks all of it as 9 AST rules over the modules
`[tool.solverlint]` names in pyproject.toml:

    python -m karpenter_tpu.analysis              # nonzero exit on findings
    python -m karpenter_tpu.analysis --self-test  # rule-discovery sanity gate

A finding is suppressed only by a justified pragma on (or directly above)
the offending line:

    # solverlint: ok(<rule-name>): <why this is sound>

Runtime counterparts: `karpenter_tpu/solver/contracts.py` enforces the
encode-space shape/dtype contracts under KARPENTER_SOLVER_TYPECHECK=1 (the
tier-1 test run enables it), and `mask_encode` freezes reference-shared
arrays so mutations the linter misses raise instead of corrupting caches;
`karpenter_tpu/obs/racecheck.py` enforces the concurrency contracts under
KARPENTER_SOLVER_RACECHECK=1 (also tier-1-wide) — dynamic lock-order graph
with raise-on-inversion, guarded-field owner checks, lock-wait histogram.

Everything here is stdlib-only (ast + tomllib/tomli): the gate runs in a
few seconds (the cardinality rule parses the whole package) and never
imports jax/numpy.
"""

from .core import Finding, run_analysis, run_self_test  # noqa: F401
from .rules import RULES  # noqa: F401

__all__ = ["Finding", "run_analysis", "run_self_test", "RULES"]
