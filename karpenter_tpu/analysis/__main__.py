"""CLI gate: `python -m karpenter_tpu.analysis`.

Exit codes: 0 clean, 1 findings, 2 broken analyzer (config error, rule
registry shrank, globs matching nothing, or --self-test failure) — a broken
gate must fail loudly, never pass vacuously.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from .config import ConfigError, load_config
from .core import repo_root, run_analysis, run_self_test
from .rules import RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m karpenter_tpu.analysis", description=__doc__)
    parser.add_argument("--self-test", action="store_true", help="verify every rule detects its seeded violation")
    parser.add_argument("--root", type=Path, default=None, help="repo root (default: auto-detected)")
    parser.add_argument("--rule", action="append", dest="rules", help="run only this rule (repeatable)")
    parser.add_argument("--jobs", type=int, default=1, help="run rules concurrently on N threads (parsed modules are shared either way)")
    parser.add_argument("--format", choices=("text", "json"), default="text", help="finding output format (json: file/line/rule/message/pragma-status, for CI diffing)")
    parser.add_argument("paths", nargs="*", type=Path, help="restrict the scan to these files")
    args = parser.parse_args(argv)

    t0 = time.perf_counter()
    try:
        root = args.root or repo_root()
        config = load_config(root)
        if args.self_test:
            failures = run_self_test(config)
            if failures:
                for f in failures:
                    print(f"self-test FAILED: {f}", file=sys.stderr)
                return 2
            print(f"solverlint self-test: {len(RULES)} rules healthy ({time.perf_counter() - t0:.2f}s)")
            return 0
        if len(RULES) < 15:
            print(f"solverlint: rule registry shrank to {len(RULES)} rules", file=sys.stderr)
            return 2
        for p in args.paths:
            if not p.is_file():
                # an unreadable operand is an operator error (exit 2), never
                # "findings" (exit 1) or a raw traceback
                print(f"solverlint: not a readable file: {p}", file=sys.stderr)
                return 2
        findings = run_analysis(root=root, config=config, rules=args.rules, paths=args.paths or None, jobs=args.jobs)
    except ConfigError as e:
        print(f"solverlint: broken configuration: {e}", file=sys.stderr)
        return 2
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    if args.format == "json":
        import json

        # machine-readable surface for CI and the bench lint_wall scenario:
        # finding counts diff cleanly across runs instead of being grepped
        # out of text. pragma_status distinguishes the pragma machinery's own
        # findings from ordinary unsuppressed ones (suppressed findings are
        # never emitted at all).
        status = {"solverlint-pragma": "malformed", "stale-pragma": "stale"}
        payload = {
            "rules": sorted(RULES),
            "count": len(ordered),
            "elapsed_s": round(time.perf_counter() - t0, 3),
            "findings": [
                {
                    "file": f.path,
                    "line": f.line,
                    "rule": f.rule,
                    "message": f.message,
                    "pragma_status": status.get(f.rule, "unsuppressed"),
                }
                for f in ordered
            ],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 1 if ordered else 0
    if ordered:
        for f in ordered:
            print(f)
        print(f"\nsolverlint: {len(ordered)} finding(s) ({time.perf_counter() - t0:.2f}s)", file=sys.stderr)
        return 1
    print(f"solverlint: clean ({len(RULES)} rules, {time.perf_counter() - t0:.2f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
