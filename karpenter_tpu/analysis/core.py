"""solverlint driver: module parsing, pragma suppression, rule running.

Rules (see rules.py) are pure AST passes producing `Finding`s. Suppression
is line-anchored: a finding survives unless a justified pragma

    # solverlint: ok(<rule>): <why>

sits on one of the finding's own source lines or the line directly above it.
A pragma with no `<why>` text is itself a finding (unsuppressible) — every
suppression must carry its justification.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from fnmatch import fnmatch
from pathlib import Path

from .config import Config, ConfigError, load_config

PRAGMA_RE = re.compile(r"#\s*solverlint:\s*ok\(([A-Za-z0-9_-]+)\)(?::\s*(\S.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str
    # source lines a pragma may sit on to suppress this finding (the line
    # above is added by the driver)
    span: tuple[int, int] = (0, 0)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class ParsedModule:
    """One source file: text, AST, and its solverlint pragmas by line."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # line (1-based) -> [(rule, why)] — pragmas live in real COMMENT
        # tokens only (docstrings describing the syntax never count)
        self.pragmas: dict[int, list[tuple[str, str]]] = {}
        self.malformed: list[Finding] = []
        # (line, rule) pairs whose pragma did real work this scan — either
        # suppressed a finding or was consulted as a contract marker
        # (lock-order / guarded-field caller-holds). stale-pragma reads this.
        self.used: set[tuple[int, str]] = set()
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT or "solverlint:" not in tok.string:
                continue
            i = tok.start[0]
            m = PRAGMA_RE.search(tok.string)
            if m is None:
                self.malformed.append(Finding("solverlint-pragma", relpath, i, "unparseable solverlint pragma"))
                continue
            rule, why = m.group(1), (m.group(2) or "").strip()
            if not why:
                self.malformed.append(
                    Finding(
                        "solverlint-pragma",
                        relpath,
                        i,
                        f"pragma for {rule!r} carries no justification — write the ok(...) form with a <why>",
                    )
                )
                continue
            self.pragmas.setdefault(i, []).append((rule, why))

    def suppressed(self, finding: Finding) -> bool:
        lo, hi = finding.span if finding.span != (0, 0) else (finding.line, finding.line)
        # own-span lines BEFORE the line above: when adjacent lines each
        # carry their own pragma for the same rule, each finding must mark
        # its own pragma as used, not shadow its neighbor's (stale-pragma
        # would otherwise report the second of two back-to-back pragmas)
        for line in (*range(lo, hi + 1), lo - 1):
            for rule, _why in self.pragmas.get(line, ()):
                if rule == finding.rule:
                    self.used.add((line, rule))
                    return True
        return False


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _match_globs(root: Path, globs) -> list[Path]:
    out: list[Path] = []
    seen = set()
    for pattern in globs:
        for p in sorted(root.glob(pattern)):
            if p.suffix == ".py" and p.is_file() and p not in seen:
                seen.add(p)
                out.append(p)
    return out


def run_analysis(
    root: Path | None = None,
    config: Config | None = None,
    rules: list[str] | None = None,
    paths: list[Path] | None = None,
    jobs: int = 1,
) -> list[Finding]:
    """Run the selected rules (default: all) and return surviving findings.

    Three modes: no `paths` — each rule scans the module set its config
    globs name; `paths` + explicit `rules` — run exactly those rules over
    exactly those files (fixture mode, globs bypassed); `paths` alone — the
    normal scan restricted to those files, so each rule still sees only
    files its globs cover (a non-tensor module passed on the CLI is not
    suddenly held to tensor-module rules).

    `jobs > 1` runs rules concurrently on a thread pool. Parsed modules are
    cached ONCE across all rules either way (the cross-module concurrency
    rules re-scan the same files the cardinality rule parses); findings come
    back in deterministic rule order regardless of scheduling.
    """
    import threading

    from .rules import RULES

    root = root or repo_root()
    config = config or load_config(root)
    selected = list(RULES) if rules is None else list(rules)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ConfigError(f"unknown rules requested: {unknown} (have {sorted(RULES)})")
    # default scans handle stale-pragma via the cheap post-pass below (usage
    # was already marked while the other rules ran); only an EXPLICIT --rule
    # selection runs the rule's standalone re-derivation
    stale_post = rules is None and "stale-pragma" in selected
    if stale_post:
        selected = [r for r in selected if r != "stale-pragma"]

    # path -> module, or None once it failed to parse (the parse finding is
    # emitted exactly once, not once per rule that scans the file); shared
    # across rules and worker threads
    cache: dict[Path, ParsedModule | None] = {}
    cache_lock = threading.Lock()
    parse_findings: list[Finding] = []
    scanned: dict[Path, set[str]] = {}

    def parsed(path: Path) -> ParsedModule | None:
        # parse INSIDE the lock: concurrent rules glob overlapping module
        # sets, and the GIL means parallel ast.parse buys nothing — holding
        # the lock is what makes "cached once across all rules" true
        with cache_lock:
            if path in cache:
                return cache[path]
            try:
                mod = ParsedModule(str(path.relative_to(root)) if path.is_relative_to(root) else str(path), path.read_text())
            except SyntaxError as e:
                parse_findings.append(Finding("solverlint-parse", str(path), e.lineno or 0, f"syntax error: {e.msg}"))
                mod = None
            except OSError as e:
                raise ConfigError(f"cannot read {path}: {e}") from e
            cache[path] = mod
            return mod

    def run_rule(name: str) -> list[Finding]:
        rule = RULES[name]()  # fresh instance: rules may aggregate across files
        out: list[Finding] = []
        if paths is not None and rules is not None:
            files = paths
        elif paths is not None:
            globbed = {g.resolve() for g in _match_globs(root, rule.globs(config))}
            files = [p for p in paths if Path(p).resolve() in globbed]
        else:
            files = _match_globs(root, rule.globs(config))
            if not files:
                return [Finding(name, str(root), 0, f"rule {name!r} matched no files — check [tool.solverlint] globs")]
        for path in files:
            mod = parsed(Path(path))
            if mod is None:
                continue
            with cache_lock:
                scanned.setdefault(Path(path), set()).add(name)
            for f in rule.check(mod, config, root):
                if not mod.suppressed(f):
                    out.append(f)
        out.extend(rule.finalize(config))
        return out

    if jobs > 1 and len(selected) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(jobs, len(selected))) as ex:
            per_rule = list(ex.map(run_rule, selected))
    else:
        per_rule = [run_rule(name) for name in selected]

    findings: list[Finding] = [f for fs in per_rule for f in fs]
    findings.extend(parse_findings)
    for path, rulenames in scanned.items():
        mod = cache.get(path)
        if mod is None:
            continue
        findings.extend(mod.malformed)
        if stale_post:
            # the stale post-pass: every rule that scans this file has run
            # and marked the pragmas it used; whatever is left did no work
            findings.extend(f for f in stale_pragma_findings(mod, rulenames) if not mod.suppressed(f))
    return findings


def stale_pragma_findings(mod: ParsedModule, checked: set[str]) -> list[Finding]:
    """Pragmas of `mod` that did no work during a scan where the rules in
    `checked` ran over it — dead suppressions rot into false confidence, so
    each one is a finding of its own."""
    from .rules import RULES

    out: list[Finding] = []
    for line in sorted(mod.pragmas):
        for rule, _why in mod.pragmas[line]:
            if rule == "stale-pragma" or (line, rule) in mod.used:
                continue
            if rule not in RULES:
                msg = f"pragma names unknown rule {rule!r} — it can never suppress anything; delete it"
            elif rule not in checked:
                msg = f"pragma for {rule!r} sits in a file that rule never scans — a dead suppression; delete it"
            else:
                msg = f"pragma for {rule!r} no longer suppresses any finding — dead suppressions rot; delete it"
            out.append(Finding("stale-pragma", mod.relpath, line, msg))
    return out


def run_self_test(config: Config | None = None) -> list[str]:
    """Prove every registered rule still detects its own seeded violation and
    that the pragma form suppresses it. Returns a list of failures (empty =
    healthy); the CLI gate turns any failure into exit 2 so a broken rule
    can never pass vacuously."""
    from .rules import RULES

    failures: list[str] = []
    if len(RULES) < 15:
        failures.append(f"rule registry shrank to {len(RULES)} rules (expected >= 15)")
    for name, cls in RULES.items():
        overrides = {"shared_fields": cls.SELF_TEST_SHARED_FIELDS, **cls.SELF_TEST_CONFIG}
        cfg = dataclasses.replace(config or Config(), **overrides)
        for label, src, expect_hit in (("bad", cls.SELF_TEST_BAD, True), ("ok", cls.SELF_TEST_OK, False)):
            rule = cls()
            mod = ParsedModule(f"<self-test:{name}:{label}>", src)
            hits = [f for f in rule.check(mod, cfg, repo_root()) if not mod.suppressed(f)]
            hits.extend(rule.finalize(cfg))
            if expect_hit and not hits:
                failures.append(f"rule {name!r} missed its seeded self-test violation")
            if not expect_hit and hits:
                failures.append(f"rule {name!r} flagged its suppressed/clean self-test snippet: {hits[0]}")
    return failures


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` for Name/Attribute chains, "" for anything else."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def callee_matches(func: ast.AST, patterns) -> bool:
    name = dotted_name(func)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    return any(fnmatch(name, p) or fnmatch(tail, p) for p in patterns)
