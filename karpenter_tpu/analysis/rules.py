"""The solverlint rules — this repo's real hazard classes, as AST passes.

1. shared-array-mutation     in-place writes to encode fields the registry
                             (encode.SHARED_ENCODE_FIELDS) declares shared by
                             reference between a base encode and its derived
                             masked/delta encodes.
2. host-sync-in-hot-path     `.item()` / `float()`/`int()`/`bool()` /
                             `np.asarray` on values produced by device
                             kernels inside the tensor-path modules.
3. python-loop-over-pod-axis `for` statements iterating pod-scaled
                             collections in tensor modules (per-signature
                             loops and comprehensions doing O(1) attribute
                             reads are the sanctioned cheap pass).
4. reason-family-tiers       every fallback family carries a tier, GLOBAL
                             families justify themselves, no stale entries
                             (absorbed from tests/test_solve_modes.py).
5. metric-label-cardinality  label values for bounded label keys at
                             counter/histogram call sites must be statically
                             enumerable, and the repo-wide literal set per
                             key stays under a cap.

racecheck's static arm (ISSUE 11) — the concurrency rules, scanning the
threaded serving stack (`thread_modules`):

6. guarded-field-access      classes on the threaded path declare a
                             GUARDED_FIELDS registry (field -> guarding lock
                             attr, like encode.SHARED_ENCODE_FIELDS); any
                             touch of a declared field outside a
                             `with self.<lock>` block is a finding. A
                             caller-holds helper carries the pragma on its
                             `def` line, which scopes the contract to the
                             whole method.
7. lock-order                the static lock-acquisition graph: nested
                             `with self.<lock>` blocks plus one level of
                             name-resolved method calls made while a lock is
                             held; any cycle is a potential deadlock, and any
                             blocking call (a solve, a device sync, the
                             store's watch-delivery `_drain`) under a held
                             lock is a finding.
8. thread-escape             `threading.Thread(target=...)`/`spawn_thread`
                             entry points and store-watch callbacks must be
                             in the declared thread-shared registry
                             (`[tool.solverlint] thread-shared`) — every
                             object handed to another thread is a reviewed,
                             named seam; lambdas (invisible capture) are
                             flagged outright.
9. bare-thread-primitive     raw threading.Lock/RLock/Event/Thread/...
                             construction outside obs/racecheck.py — the
                             wrapper is what lets the runtime sanitizer
                             instrument every acquisition.

faultline's static arm (ISSUE 15):

10. swallowed-exception      broad `except Exception:` handlers that
                             neither re-raise nor record (an events publish
                             or metrics emission) — a serving stack only
                             degrades gracefully when every absorbed
                             failure leaves a signal; deliberate swallows
                             carry a justified pragma.

detlint's static arm (ISSUE 19) — the determinism rules protecting the
bit-identical-placement contract (obs/detcheck.py is the runtime arm):

11. unordered-iteration-escape  iteration over a set/frozenset of
                             non-literal origin (or id()-keyed ordering)
                             landing in an ordered output — hash order
                             varies with PYTHONHASHSEED; sanctioned sites
                             use sorted(...) or a justified pragma.
12. wallclock-and-rng-in-solve-path  time.*/random/np.random/uuid4/secrets
                             reachable from solve/encode/decode entry
                             points, outside the reviewed seeded-RNG
                             registry ([tool.solverlint] seeded-rng).
13. float-reduction-order    host float accumulations over device-derived
                             or unordered operands not routed through a
                             canonical-order helper (fsum/stable_host_sum)
                             — protects mesh-N-vs-mesh-1 bit-parity.
14. env-dependent-branch     os.environ reads in solve-path modules
                             outside the registered KARPENTER_* knob table
                             ([tool.solverlint] env-knobs).
15. stale-pragma             a suppression pragma that no longer
                             suppresses any finding (dead suppressions
                             rot; usage is tracked live during the scan).

Every rule ships SELF_TEST_BAD/SELF_TEST_OK snippets; `--self-test` proves
each rule still detects its seeded violation and that the pragma suppresses
it, so the gate fails loudly if rule discovery breaks.
"""

from __future__ import annotations

import ast

from .config import Config
from .core import Finding, ParsedModule, callee_matches, dotted_name

# lambdas are NOT a scope boundary here: they cannot contain assignments, so
# their bodies read the enclosing scope's names — scanning them in place is
# what lets the rules see a mutation/sync tucked into a sort key or callback
_SCOPE_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _walk_scope(node: ast.AST):
    """All nodes of one scope, not descending into nested functions."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_KINDS):
            stack.extend(ast.iter_child_nodes(n))


def _scopes(tree: ast.Module):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _flat_targets(target: ast.AST):
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flat_targets(elt)
    else:
        yield target


def _span(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, getattr(node, "end_lineno", node.lineno) or node.lineno)


class Rule:
    name = ""
    description = ""
    SELF_TEST_BAD = ""
    SELF_TEST_OK = ""
    SELF_TEST_SHARED_FIELDS: frozenset | None = None
    # extra Config overrides applied while self-testing this rule (e.g. an
    # emptied thread-shared registry so the seeded escape is unsanctioned)
    SELF_TEST_CONFIG: dict = {}

    def globs(self, config: Config) -> tuple[str, ...]:
        return config.tensor_modules

    def check(self, mod: ParsedModule, config: Config, root) -> list[Finding]:  # pragma: no cover - interface
        raise NotImplementedError

    def finalize(self, config: Config) -> list[Finding]:
        return []

    def _finding(self, mod: ParsedModule, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, mod.relpath, node.lineno, message, span=_span(node))


class SharedArrayMutationRule(Rule):
    name = "shared-array-mutation"
    description = "in-place write to an encode field shared by reference with derived encodes"
    # ndarray methods that mutate in place
    MUTATOR_METHODS = frozenset({"fill", "sort", "resize", "itemset", "partition", "byteswap"})
    # numpy free functions (last dotted segment) whose first argument is written
    MUTATOR_FUNCS = frozenset({"put", "copyto", "place", "putmask", "at"})

    SELF_TEST_SHARED_FIELDS = frozenset({"sig_req"})
    SELF_TEST_BAD = "def f(enc):\n    enc.sig_req[0] = 1.0\n"
    SELF_TEST_OK = (
        "def f(enc):\n"
        "    enc.sig_req[0] = 1.0  # solverlint: ok(shared-array-mutation): self-test snippet, never imported\n"
    )

    def check(self, mod, config, root):
        fields = config.resolve_shared_fields(root)
        findings: list[Finding] = []
        for scope in _scopes(mod.tree):
            # flow-insensitive alias pass: a bare name stands in for a shared
            # field only when EVERY simple assignment to it reads one
            kinds: dict[str, set[str]] = {}
            alias_field: dict[str, str] = {}
            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    if isinstance(n.value, ast.Attribute) and n.value.attr in fields:
                        kinds.setdefault(n.targets[0].id, set()).add("reg")
                        alias_field[n.targets[0].id] = n.value.attr
                    else:
                        kinds.setdefault(n.targets[0].id, set()).add("other")
                elif isinstance(n, (ast.Assign, ast.AnnAssign, ast.For, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [getattr(n, "target", None)]
                    for t in targets:
                        if t is not None:
                            for leaf in _flat_targets(t):
                                if isinstance(leaf, ast.Name):
                                    kinds.setdefault(leaf.id, set()).add("other")
            aliases = {name for name, ks in kinds.items() if ks == {"reg"}}

            def shared(node) -> str | None:
                if isinstance(node, ast.Attribute) and node.attr in fields:
                    return node.attr
                if isinstance(node, ast.Name) and node.id in aliases:
                    return alias_field[node.id]
                return None

            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Subscript) and (f := shared(leaf.value)):
                                findings.append(
                                    self._finding(mod, n, f"in-place write to shared encode array {f!r}")
                                )
                elif isinstance(n, ast.AugAssign):
                    target = n.target.value if isinstance(n.target, ast.Subscript) else n.target
                    if f := shared(target):
                        findings.append(
                            self._finding(mod, n, f"augmented in-place write to shared encode array {f!r}")
                        )
                elif isinstance(n, ast.Call):
                    func = n.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr in self.MUTATOR_METHODS
                        and (f := shared(func.value))
                    ):
                        findings.append(
                            self._finding(mod, n, f".{func.attr}() mutates shared encode array {f!r}")
                        )
                    elif (
                        dotted_name(func).rsplit(".", 1)[-1] in self.MUTATOR_FUNCS
                        and n.args
                        and (f := shared(n.args[0]))
                    ):
                        findings.append(
                            self._finding(mod, n, f"{dotted_name(func)}() writes into shared encode array {f!r}")
                        )
        return findings


class HostSyncRule(Rule):
    name = "host-sync-in-hot-path"
    description = "host coercion of a device-kernel result inside a tensor-path module"
    COERCERS = frozenset({"float", "int", "bool"})
    ARRAYERS = frozenset({"np.asarray", "np.array", "numpy.asarray", "numpy.array"})
    # shape/metadata reads are static, never a device sync
    EXEMPT_ATTRS = frozenset({"shape", "size", "ndim", "dtype"})

    SELF_TEST_BAD = (
        "def f(t, items):\n"
        "    takes = greedy_pack_grouped_sharded(t, items)\n"
        "    return float(takes)\n"
    )
    SELF_TEST_OK = (
        "def f(t, items):\n"
        "    takes = greedy_pack_grouped_sharded(t, items)\n"
        "    return float(takes)  # solverlint: ok(host-sync-in-hot-path): self-test snippet, never imported\n"
    )

    def check(self, mod, config, root):
        findings: list[Finding] = []
        for scope in _scopes(mod.tree):
            tainted: set[str] = set()
            # any-assignment taint + one fixed-point pass for name-to-name copies
            copies: list[tuple[str, str]] = []
            for n in _walk_scope(scope):
                if not isinstance(n, ast.Assign):
                    continue
                if isinstance(n.value, ast.Call) and callee_matches(n.value.func, config.device_producers):
                    for t in n.targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
                elif isinstance(n.value, ast.Name) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    copies.append((n.targets[0].id, n.value.id))
            changed = True
            while changed:
                changed = False
                for dst, src in copies:
                    if src in tainted and dst not in tainted:
                        tainted.add(dst)
                        changed = True

            def device_expr(node) -> bool:
                # path-sensitive: a `.shape`/`.size`/... access prunes ONLY
                # its own subtree (a static metadata read), never the rest of
                # the expression — `float(takes.sum() / takes.shape[0])` is
                # still a sync on `takes.sum()`
                if isinstance(node, ast.Attribute) and node.attr in self.EXEMPT_ATTRS:
                    return False
                if isinstance(node, ast.Name):
                    return node.id in tainted
                if isinstance(node, ast.Call) and callee_matches(node.func, config.device_producers):
                    return True
                return any(device_expr(child) for child in ast.iter_child_nodes(node))

            for n in _walk_scope(scope):
                if not isinstance(n, ast.Call):
                    continue
                func = n.func
                if isinstance(func, ast.Attribute) and func.attr == "item" and not n.args and device_expr(func.value):
                    findings.append(self._finding(mod, n, ".item() host-syncs a device value"))
                elif (
                    isinstance(func, ast.Name)
                    and func.id in self.COERCERS
                    and len(n.args) == 1
                    and device_expr(n.args[0])
                ):
                    findings.append(
                        self._finding(mod, n, f"{func.id}() coerces a device value to host (blocking sync)")
                    )
                elif dotted_name(func) in self.ARRAYERS and n.args and device_expr(n.args[0]):
                    findings.append(
                        self._finding(mod, n, f"{dotted_name(func)}() lands a device array on host")
                    )
        return findings


class PodAxisLoopRule(Rule):
    name = "python-loop-over-pod-axis"
    description = "Python-level `for` statement iterating a pod-scaled collection in a tensor module"

    # seeded on the decode-materialization shape: grouping pods into slots by
    # walking the pod axis in Python is exactly the O(pods) host tail the
    # decode-delta memo + columnar gather removed (bad_decode_loop /
    # ok_decode_columnar in the fixture file carry the full pair)
    SELF_TEST_BAD = (
        "def decode(enc, assignment):\n"
        "    slots = {}\n"
        "    for i, p in enumerate(enc.pods):\n"
        "        slots.setdefault(assignment[i], []).append(p)\n"
        "    return slots\n"
    )
    SELF_TEST_OK = (
        "def decode(enc, assignment):\n"
        "    slots = {}\n"
        "    for i, p in enumerate(enc.pods):  # solverlint: ok(python-loop-over-pod-axis): self-test snippet, never imported\n"
        "        slots.setdefault(assignment[i], []).append(p)\n"
        "    return slots\n"
    )

    def check(self, mod, config, root):
        names = set(config.pod_axis_names)
        findings: list[Finding] = []
        for n in ast.walk(mod.tree):
            if not isinstance(n, (ast.For, ast.AsyncFor)):
                continue
            hit = None
            for sub in ast.walk(n.iter):
                if isinstance(sub, ast.Name) and sub.id in names:
                    hit = sub.id
                elif isinstance(sub, ast.Attribute) and sub.attr in names:
                    hit = dotted_name(sub) or sub.attr
                if hit:
                    break
            if hit:
                findings.append(
                    Finding(
                        self.name,
                        mod.relpath,
                        n.lineno,
                        f"Python loop over pod-scaled {hit!r} — vectorize, or justify with a pragma",
                        span=(n.lineno, n.iter.end_lineno or n.lineno),
                    )
                )
        return findings


class ReasonFamilyTiersRule(Rule):
    name = "reason-family-tiers"
    description = "fallback families must carry tiers; GLOBAL families must justify themselves"

    SELF_TEST_BAD = (
        'GLOBAL = "global"\n'
        'POD_LOCAL = "pod-local"\n'
        'REASON_FAMILIES = (("needle a", "fam-a"), ("needle b", "fam-b"))\n'
        "FAMILY_TIERS = {\n"
        '    "fam-a": GLOBAL,\n'
        '    "other": GLOBAL,\n'
        "}\n"
    )
    SELF_TEST_OK = (
        'GLOBAL = "global"\n'
        'POD_LOCAL = "pod-local"\n'
        'REASON_FAMILIES = (("needle a", "fam-a"), ("needle b", "fam-b"))\n'
        "FAMILY_TIERS = {\n"
        "    # the kernel cannot express this family's semantics\n"
        '    "fam-a": GLOBAL,\n'
        '    "fam-b": POD_LOCAL,\n'
        '    "other": GLOBAL,  # unattributable reasons take the conservative path\n'
        "}\n"
    )

    def globs(self, config):
        return (config.fallback_module,)

    def check(self, mod, config, root):
        findings: list[Finding] = []
        families: list[tuple[str, int]] | None = None
        tiers: ast.Dict | None = None
        for n in mod.tree.body:
            target = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                target = n.targets[0].id
            elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
                target = n.target.id
            if target == "REASON_FAMILIES" and isinstance(n.value, (ast.Tuple, ast.List)):
                families = []
                for elt in n.value.elts:
                    if (
                        isinstance(elt, (ast.Tuple, ast.List))
                        and len(elt.elts) == 2
                        and isinstance(elt.elts[1], ast.Constant)
                    ):
                        families.append((elt.elts[1].value, elt.lineno))
                    else:
                        findings.append(self._finding(mod, elt, "REASON_FAMILIES entry is not a (needle, family) pair"))
            elif target == "FAMILY_TIERS" and isinstance(n.value, ast.Dict):
                tiers = n.value
        if families is None or tiers is None:
            findings.append(
                Finding(self.name, mod.relpath, 1, "REASON_FAMILIES / FAMILY_TIERS registry not found in module")
            )
            return findings

        entries: list[tuple[str, int, ast.AST]] = []
        for key, value in zip(tiers.keys, tiers.values):
            if not isinstance(key, ast.Constant) or not isinstance(key.value, str):
                findings.append(self._finding(mod, key or tiers, "FAMILY_TIERS key is not a string literal"))
                continue
            entries.append((key.value, key.lineno, value))
            if not (isinstance(value, ast.Name) and value.id in ("GLOBAL", "POD_LOCAL")):
                findings.append(
                    self._finding(mod, value, f"tier for {key.value!r} must be the GLOBAL or POD_LOCAL constant")
                )
        keys = {k for k, _l, _v in entries}
        enum = {fam for fam, _l in families}
        for fam, line in families:
            if fam not in keys:
                findings.append(Finding(self.name, mod.relpath, line, f"family {fam!r} has no tier in FAMILY_TIERS"))
        if "other" not in keys:
            findings.append(
                Finding(self.name, mod.relpath, tiers.lineno, 'FAMILY_TIERS lacks the "other" conservative entry')
            )
        for key, line, _v in entries:
            if key not in enum and key != "other":
                findings.append(
                    Finding(self.name, mod.relpath, line, f"stale tier entry {key!r}: no such family in REASON_FAMILIES")
                )

        # every GLOBAL entry justifies itself: a trailing comment on the
        # entry, or a comment block heading its contiguous GLOBAL run
        global_lines = {
            line for _k, line, v in entries if isinstance(v, ast.Name) and v.id == "GLOBAL"
        }
        for key, line, value in entries:
            if not (isinstance(value, ast.Name) and value.id == "GLOBAL"):
                continue
            text = mod.lines[line - 1] if line - 1 < len(mod.lines) else ""
            tail = text[value.end_col_offset:] if value.end_lineno == line else ""
            if "#" in tail:
                continue
            j = line - 2  # 0-based index of the line above
            while j >= 0 and (j + 1) in global_lines:
                j -= 1
            if j >= 0 and mod.lines[j].lstrip().startswith("#"):
                continue
            findings.append(
                Finding(
                    self.name,
                    mod.relpath,
                    line,
                    f"GLOBAL family {key!r} lacks a one-line justification comment",
                )
            )
        return findings


class MetricLabelCardinalityRule(Rule):
    name = "metric-label-cardinality"
    description = "bounded metric labels must carry statically enumerable values"
    _ITER_WRAPPERS = frozenset({"sorted", "set", "list", "tuple"})

    # the seeded violation is a decode-delta one: the decode counter's `mode`
    # label fed a runtime trace-attribution value instead of the two-literal
    # {full | delta-reuse} enum the decode itself branches on — exactly the
    # cardinality leak a future decode mode added without a literal at the
    # call site would regress into
    SELF_TEST_BAD = (
        "def publish(registry, trace):\n"
        '    registry.counter("karpenter_solver_decode_total").inc(mode=trace.attribution["decode_mode"])\n'
    )
    SELF_TEST_OK = (
        "def publish(registry, reused_slots):\n"
        '    registry.counter("karpenter_solver_decode_total").inc(mode="delta-reuse" if reused_slots else "full")\n'
    )

    def __init__(self):
        # label -> value -> first (path, line) seen, for the repo-wide cap
        self._literals: dict[str, dict[str, tuple[str, int]]] = {}

    def globs(self, config):
        return config.metrics_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        bounded_labels = set(config.bounded_labels)
        wrappers = set(config.metric_wrappers)

        # (call, enclosing scope, enclosing function name)
        stack: list[tuple[ast.AST, ast.AST, str]] = [(mod.tree, mod.tree, "")]
        calls: list[tuple[ast.Call, ast.AST, str]] = []
        while stack:
            node, scope, fname = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    stack.append((child, child, child.name))
                else:
                    if isinstance(child, ast.Call):
                        calls.append((child, scope, fname))
                    stack.append((child, scope, fname))

        bindings_cache: dict[int, dict[str, list]] = {}

        def bindings(scope) -> dict[str, list]:
            cached = bindings_cache.get(id(scope))
            if cached is not None:
                return cached
            b: dict[str, list] = {}
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = scope.args
                for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs, a.vararg, a.kwarg]:
                    if arg is not None:
                        b.setdefault(arg.arg, []).append(("opaque", None))
            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    b.setdefault(n.targets[0].id, []).append(("expr", n.value))
                elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name) and n.value is not None:
                    b.setdefault(n.target.id, []).append(("expr", n.value))
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    for leaf in _flat_targets(n.target):
                        if isinstance(leaf, ast.Name):
                            b.setdefault(leaf.id, []).append(("for", n.iter))
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                    for t in targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Name):
                                b.setdefault(leaf.id, []).append(("opaque", None))
            bindings_cache[id(scope)] = b
            return b

        def bounded(expr, scope, depth=0) -> tuple[bool, list[str]]:
            if depth > 6:
                return False, []
            if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
                return True, [expr.value]
            if isinstance(expr, ast.IfExp):
                ok1, l1 = bounded(expr.body, scope, depth + 1)
                ok2, l2 = bounded(expr.orelse, scope, depth + 1)
                return ok1 and ok2, l1 + l2
            if isinstance(expr, ast.BoolOp):
                lits: list[str] = []
                for v in expr.values:
                    ok, ls = bounded(v, scope, depth + 1)
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            if isinstance(expr, ast.Call) and callee_matches(expr.func, config.bounded_label_producers):
                return True, []
            if isinstance(expr, ast.Name):
                entries = bindings(scope).get(expr.id)
                if not entries:
                    return False, []
                lits = []
                for kind, val in entries:
                    if kind == "expr":
                        ok, ls = bounded(val, scope, depth + 1)
                    elif kind == "for":
                        ok, ls = bounded_iter(val, scope, depth + 1)
                    else:
                        ok, ls = False, []
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            return False, []

        def bounded_iter(expr, scope, depth=0) -> tuple[bool, list[str]]:
            if depth > 6:
                return False, []
            if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) and expr.func.id in self._ITER_WRAPPERS:
                return bounded_iter(expr.args[0], scope, depth + 1) if expr.args else (False, [])
            if isinstance(expr, (ast.SetComp, ast.ListComp, ast.GeneratorExp)):
                return bounded(expr.elt, scope, depth + 1)
            if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
                lits = []
                for elt in expr.elts:
                    ok, ls = bounded(elt, scope, depth + 1)
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            return False, []

        def record(label: str, literals: list[str], node):
            for v in literals:
                self._literals.setdefault(label, {}).setdefault(v, (mod.relpath, node.lineno))

        def check_kw(label: str, value, scope, node):
            ok, literals = bounded(value, scope)
            if ok:
                record(label, literals, node)
            else:
                findings.append(
                    self._finding(
                        mod,
                        node,
                        f"label {label!r} value is not statically enumerable — pass a literal, an enum-bounded producer result, or justify with a pragma",
                    )
                )

        def dict_labels(expr) -> list[tuple[str, ast.AST]] | None:
            if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) and expr.func.id == "dict" and not expr.args:
                return [(kw.arg, kw.value) for kw in expr.keywords if kw.arg is not None]
            if isinstance(expr, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str) for k in expr.keys
            ):
                return [(k.value, v) for k, v in zip(expr.keys, expr.values)]
            return None

        for call, scope, fname in calls:
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            # gauge .set carries labels exactly like counter .inc / histogram
            # .observe — an unbounded gauge label leaks series just the same
            if func.attr not in ("inc", "observe", "set") and func.attr not in wrappers:
                continue
            if fname in wrappers:
                continue  # the wrapper's own **labels forwarding
            for kw in call.keywords:
                if kw.arg is not None:
                    if kw.arg in bounded_labels:
                        check_kw(kw.arg, kw.value, scope, call)
                    continue
                # **splat: resolve a locally-built dict literal
                resolved = None
                if isinstance(kw.value, ast.Name):
                    entries = bindings(scope).get(kw.value.id, [])
                    if len(entries) == 1 and entries[0][0] == "expr":
                        resolved = dict_labels(entries[0][1])
                else:
                    resolved = dict_labels(kw.value)
                if resolved is None:
                    findings.append(
                        self._finding(mod, call, "cannot statically bound **labels splat at metric call site")
                    )
                    continue
                for label, value in resolved:
                    if label in bounded_labels:
                        check_kw(label, value, scope, call)
        return findings

    def finalize(self, config):
        findings = []
        for label, values in self._literals.items():
            if len(values) > config.max_label_values:
                path, line = next(iter(values.values()))
                sample = ", ".join(sorted(values)[:6])
                findings.append(
                    Finding(
                        self.name,
                        path,
                        line,
                        f"label {label!r} carries {len(values)} distinct literal values repo-wide "
                        f"(cap {config.max_label_values}): {sample}, ... — an aggregate finding no "
                        f"line pragma can suppress; shrink the value set or raise max-label-values "
                        f"in [tool.solverlint]",
                    )
                )
        return findings


# -- racecheck: the concurrency rules (ISSUE 11) ------------------------------


def _self_lock_attr(node: ast.AST) -> str | None:
    """`self.<attr>` -> attr, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _class_lock_attrs(cls: ast.ClassDef, config: Config, imports=None) -> set[str]:
    """Attrs assigned `self.<attr> = <lock factory>(...)` anywhere in the
    class (normally __init__). `imports` is the module's threading import
    table so `from threading import Lock as L; self._x = L()` is still
    recognized as a lock."""
    mods, names = imports or (set(), {})
    attrs: set[str] = set()
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
            continue
        if callee_matches(n.value.func, config.lock_factories) or _threading_construct(n.value, mods, names) in ("Lock", "RLock"):
            for t in n.targets:
                a = _self_lock_attr(t)
                if a is not None:
                    attrs.add(a)
    return attrs


def _module_lock_attrs(tree: ast.Module, config: Config) -> dict[str, tuple[set[str], bool]]:
    """Per class: (effective lock attrs incl. same-module bases, has an
    out-of-module base). `Counter._lock` lives on `_Metric.__init__` — the
    single-inheritance resolution here is what lets subclasses inherit the
    guard declaration."""
    imports = _threading_imports(tree)
    classes = {n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)}
    own = {name: _class_lock_attrs(cls, config, imports) for name, cls in classes.items()}
    out: dict[str, tuple[set[str], bool]] = {}

    def resolve(name: str, seen: frozenset) -> tuple[set[str], bool]:
        if name in out:
            return out[name]
        attrs = set(own.get(name, ()))
        unknown = False
        for base in classes[name].bases:
            bname = dotted_name(base).rsplit(".", 1)[-1]
            if bname in classes and bname not in seen:
                battrs, bunknown = resolve(bname, seen | {name})
                attrs |= battrs
                unknown |= bunknown
            elif bname not in ("object",):
                unknown = True
        out[name] = (attrs, unknown)
        return out[name]

    for name in classes:
        resolve(name, frozenset())
    return out


def _import_table(tree: ast.Module, module: str) -> tuple[set[str], dict[str, str]]:
    """(aliases `module` is bound to, {local name: module attr} for
    from-imports) — so `import random as rnd; rnd.shuffle()` and
    `from random import shuffle as sh; sh()` resolve instead of evading a
    rule via a rename. The same table serves threading (racecheck's rules)
    and time/random/os/uuid (detlint's)."""
    mods: set[str] = set()
    names: dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name == module:
                    mods.add(a.asname or module)
        elif isinstance(n, ast.ImportFrom) and n.module == module:
            for a in n.names:
                names[a.asname or a.name] = a.name
    return mods, names


def _threading_imports(tree: ast.Module) -> tuple[set[str], dict[str, str]]:
    return _import_table(tree, "threading")


def _module_construct(call: ast.Call, mods: set[str], names: dict[str, str]) -> str | None:
    """The module attribute this call invokes ("Lock", "shuffle", ...),
    resolved through module aliases and from-imports; None otherwise."""
    name = dotted_name(call.func)
    if not name:
        return None
    base, _, tail = name.rpartition(".")
    if base in mods:
        return tail
    if not base:
        return names.get(tail)
    return None


# racecheck's rules predate the generic table; keep their vocabulary
_threading_construct = _module_construct


def _has_pragma(mod: ParsedModule, rule: str, line: int) -> bool:
    """A justified pragma for `rule` on `line` or the line directly above.
    Consultation counts as usage: a caller-holds / ordering-contract pragma
    never flows through mod.suppressed(), so it is marked live here lest
    stale-pragma report every contract marker as dead."""
    for i in (line, line - 1):
        for r, _why in mod.pragmas.get(i, ()):
            if r == rule:
                mod.used.add((i, rule))
                return True
    return False


class GuardedFieldAccessRule(Rule):
    name = "guarded-field-access"
    description = "a GUARDED_FIELDS-declared field touched outside a `with self.<lock>` block"

    SELF_TEST_BAD = (
        "class Stats:\n"
        '    GUARDED_FIELDS = {"hits": "_lock"}\n'
        "    def __init__(self):\n"
        '        self._lock = make_lock("stats")\n'
        "        self.hits = 0\n"
        "    def bump(self):\n"
        "        self.hits += 1\n"
    )
    SELF_TEST_OK = (
        "class Stats:\n"
        '    GUARDED_FIELDS = {"hits": "_lock"}\n'
        "    def __init__(self):\n"
        '        self._lock = make_lock("stats")\n'
        "        self.hits = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.hits += 1\n"
        "    def bump_unlocked(self):  # solverlint: ok(guarded-field-access): self-test snippet — caller-holds contract demo\n"
        "        self.hits += 1\n"
    )

    def globs(self, config):
        return config.thread_modules

    @staticmethod
    def _registry(cls: ast.ClassDef, config: Config):
        """The class's GUARDED_FIELDS literal as {field: lock attr}, plus the
        registry node (for malformed-registry findings)."""
        for n in cls.body:
            target = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                target = n.targets[0].id
            elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
                target = n.target.id
            if target != config.guarded_registry_attr:
                continue
            value = n.value
            if not isinstance(value, ast.Dict):
                return None, n
            reg: dict[str, str] = {}
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str) and isinstance(v, ast.Constant) and isinstance(v.value, str)):
                    return None, n
                reg[k.value] = v.value
            return reg, n
        return {}, None

    def check(self, mod, config, root):
        findings: list[Finding] = []
        lock_map = _module_lock_attrs(mod.tree, config)
        for cls in [n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]:
            registry, reg_node = self._registry(cls, config)
            if registry is None:
                findings.append(
                    self._finding(mod, reg_node, f"{config.guarded_registry_attr} must be a literal {{'field': 'lock attr'}} dict — the runtime sanitizer reads it too")
                )
                continue
            if not registry:
                continue
            lock_attrs, unknown_base = lock_map.get(cls.name, (set(), True))
            for field, lockattr in registry.items():
                if lockattr not in lock_attrs and not unknown_base:
                    findings.append(
                        self._finding(mod, reg_node, f"guard {lockattr!r} for field {field!r} is never assigned from a lock factory in {cls.name}")
                    )
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if meth.name == "__init__":
                    continue  # construction happens-before thread publication
                if _has_pragma(mod, self.name, meth.lineno):
                    # method-level caller-holds contract: the pragma on the
                    # `def` line declares every call site holds the lock
                    continue
                for child in ast.iter_child_nodes(meth):
                    self._scan(child, registry, frozenset(), findings, mod, cls.name)
        return findings

    def _scan(self, child, registry, held, findings, mod, clsname):
        """One node, with the set of lock attrs lexically held around it."""
        if isinstance(child, _SCOPE_KINDS):
            # a nested def may run on any thread later: scan it with no
            # locks assumed held
            for sub in ast.iter_child_nodes(child):
                self._scan(sub, registry, frozenset(), findings, mod, clsname)
            return
        if isinstance(child, ast.With):
            newly = set()
            for item in child.items:
                # the acquire expression itself is evaluated unlocked
                self._scan(item.context_expr, registry, held, findings, mod, clsname)
                a = _self_lock_attr(item.context_expr)
                if a is not None:
                    newly.add(a)
            for stmt in child.body:
                self._scan(stmt, registry, held | newly, findings, mod, clsname)
            return
        a = _self_lock_attr(child) if isinstance(child, ast.Attribute) else None
        if a is not None and a in registry and registry[a] not in held:
            findings.append(
                self._finding(
                    mod,
                    child,
                    f"field {clsname}.{a!r} is declared guarded by {registry[a]!r} but touched outside `with self.{registry[a]}`",
                )
            )
            return  # the chain below is just `self`
        for sub in ast.iter_child_nodes(child):
            self._scan(sub, registry, held, findings, mod, clsname)


class LockOrderRule(Rule):
    name = "lock-order"
    description = "cycle in the static lock-acquisition graph, or a blocking call under a held lock"

    SELF_TEST_BAD = (
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    SELF_TEST_OK = (
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
    )

    def __init__(self):
        # node = "ClassName.lockattr"; edge (a, b): a held while acquiring b
        self._edges: dict[tuple[str, str], tuple[str, int]] = {}
        # method tail name -> set of lock nodes it acquires directly
        self._method_acquires: dict[str, set[str]] = {}
        # calls made while holding a lock, resolved against methods in finalize
        self._held_calls: list[tuple[str, str, str, int]] = []  # (held node, callee tail, path, line)

    def globs(self, config):
        return config.thread_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        lock_map = _module_lock_attrs(mod.tree, config)
        for cls in [n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]:
            lock_attrs, _unknown = lock_map.get(cls.name, (set(), False))
            if not lock_attrs:
                continue
            node_of = {a: f"{cls.name}.{a}" for a in lock_attrs}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                acquires = self._method_acquires.setdefault(meth.name, set())
                for child in ast.iter_child_nodes(meth):
                    self._walk(child, node_of, [], acquires, findings, mod, config)
        return findings

    def _walk(self, child, node_of, held: list, acquires: set, findings, mod, config):
        """One node, with the stack of lock nodes lexically held around it."""
        if isinstance(child, _SCOPE_KINDS):
            return  # nested defs execute later, on their own stack
        if isinstance(child, ast.With):
            newly = []
            for item in child.items:
                a = _self_lock_attr(item.context_expr)
                if a in node_of:
                    n = node_of[a]
                    acquires.add(n)
                    if not _has_pragma(mod, self.name, item.context_expr.lineno):
                        # held + newly-so-far: `with self._a, self._b:`
                        # acquires sequentially, so the combined form orders
                        # a before b exactly like nested withs
                        for h in held + newly:
                            if h != n:
                                self._edges.setdefault((h, n), (mod.relpath, child.lineno))
                    newly.append(n)
            for stmt in child.body:
                self._walk(stmt, node_of, held + newly, acquires, findings, mod, config)
            return
        if isinstance(child, ast.Call) and held:
            if callee_matches(child.func, config.lock_blocking_calls):
                findings.append(
                    self._finding(
                        mod,
                        child,
                        f"blocking call {dotted_name(child.func) or '<call>'}() while holding {held[-1]} — "
                        f"a solve/device-sync/watch-delivery under a lock stalls every contender "
                        f"(see {config.thread_inventory_doc})",
                    )
                )
            tail = dotted_name(child.func).rsplit(".", 1)[-1]
            if tail and tail not in config.lock_call_blacklist and not _has_pragma(mod, self.name, child.lineno):
                self._held_calls.append((held[-1], tail, mod.relpath, child.lineno))
        for sub in ast.iter_child_nodes(child):
            self._walk(sub, node_of, held, acquires, findings, mod, config)

    def finalize(self, config):
        # resolve one level of held-call edges by method name (the dynamic
        # arm covers what name-based resolution cannot see: fn-pointer watch
        # callbacks, cross-object calls on ambiguous names)
        for held, tail, path, line in self._held_calls:
            for node in self._method_acquires.get(tail, ()):
                if node != held:
                    self._edges.setdefault((held, node), (path, line))
        adj: dict[str, set[str]] = {}
        for a, b in self._edges:
            adj.setdefault(a, set()).add(b)
        findings: list[Finding] = []
        seen_cycles: set[frozenset] = set()
        for a, b in sorted(self._edges):
            path = self._path(adj, b, a)
            if path is None:
                continue
            cycle = [a, *path]  # path runs b..a, so the chain ends where it starts
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            src, line = self._edges[(a, b)]
            findings.append(
                Finding(
                    self.name,
                    src,
                    line,
                    "lock-order cycle (potential deadlock): "
                    + " -> ".join(cycle)
                    + f" — pick one order and record it in {config.thread_inventory_doc}",
                )
            )
        return findings

    @staticmethod
    def _path(adj, src, dst):
        """A path src..dst in the edge graph, or None."""
        stack, prev = [src], {src: None}
        while stack:
            n = stack.pop()
            if n == dst:
                out = []
                while n is not None:
                    out.append(n)
                    n = prev[n]
                return list(reversed(out))
            for nxt in adj.get(n, ()):
                if nxt not in prev:
                    prev[nxt] = n
                    stack.append(nxt)
        return None


class ThreadEscapeRule(Rule):
    name = "thread-escape"
    description = "a thread entry point or watch callback outside the declared thread-shared registry"

    SELF_TEST_CONFIG = {"thread_shared": ()}
    SELF_TEST_BAD = (
        "import threading\n"
        "class Escapee:\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._run, daemon=True)\n"
        "        t.start()\n"
    )
    SELF_TEST_OK = (
        "import threading\n"
        "class Escapee:\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._run, daemon=True)  # solverlint: ok(thread-escape): self-test snippet, never imported\n"
        "        t.start()\n"
    )

    def globs(self, config):
        return config.thread_modules

    def check(self, mod, config, root):
        if mod.relpath == config.racecheck_module:
            return []  # the wrapper's own Thread(...) takes its caller's target
        findings: list[Finding] = []
        # enclosing class per call site, for "ClassName.method" candidates
        enclosing: dict[int, str] = {}

        def mark(node, clsname):
            for child in ast.iter_child_nodes(node):
                name = child.name if isinstance(child, ast.ClassDef) else clsname
                if isinstance(child, ast.Call):
                    enclosing[id(child)] = name
                mark(child, name)

        mark(mod.tree, "")

        def sanctioned(expr, call) -> bool:
            name = dotted_name(expr)
            if not name:
                return False
            tail = name.rsplit(".", 1)[-1]
            # bare names also match path-qualified entries
            # ("karpenter_tpu/state/informer.py:on_*"), so a generic callback
            # name is sanctioned only in the module that was actually
            # reviewed, not anywhere a same-named function appears later
            candidates = {name, tail, f"{mod.relpath}:{name}", f"{mod.relpath}:{tail}"}
            if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) and expr.value.id == "self":
                cls = enclosing.get(id(call), "")
                if cls:
                    candidates.add(f"{cls}.{expr.attr}")
            from fnmatch import fnmatch

            return any(fnmatch(c, p) for c in candidates for p in config.thread_shared)

        def flag(expr, call, what):
            if isinstance(expr, ast.Lambda):
                findings.append(
                    self._finding(mod, call, f"lambda as {what}: captured state is invisible to review — register a named callback from the thread-shared registry or justify with a pragma")
                )
            elif not sanctioned(expr, call):
                findings.append(
                    self._finding(
                        mod,
                        call,
                        f"{what} {dotted_name(expr) or '<expression>'} is not in the thread-shared registry "
                        f"([tool.solverlint] thread-shared) — objects handed to another thread must be reviewed, named seams",
                    )
                )

        mods, names = _threading_imports(mod.tree)
        for call in [n for n in ast.walk(mod.tree) if isinstance(n, ast.Call)]:
            func = call.func
            tail = dotted_name(func).rsplit(".", 1)[-1]
            if _threading_construct(call, mods, names) == "Thread":
                target = next((kw.value for kw in call.keywords if kw.arg == "target"), None)
                if target is not None:
                    flag(target, call, "thread target")
            elif tail == "spawn_thread":
                target = call.args[0] if call.args else next((kw.value for kw in call.keywords if kw.arg == "target"), None)
                if target is not None:
                    flag(target, call, "thread target")
            elif tail in config.watch_register_methods and isinstance(func, ast.Attribute):
                cb = call.args[1] if len(call.args) >= 2 else next((kw.value for kw in call.keywords if kw.arg == "fn"), None)
                if cb is not None:
                    flag(cb, call, "watch callback")
        return findings


class BareThreadPrimitiveRule(Rule):
    name = "bare-thread-primitive"
    description = "raw threading primitive constructed outside the sanctioned racecheck wrapper"
    PRIMITIVES = frozenset({"Lock", "RLock", "Event", "Thread", "Condition", "Semaphore", "BoundedSemaphore", "Barrier"})
    # threading.local is deliberately exempt: thread-local state is the
    # opposite of shared state, and instrumenting it buys nothing

    SELF_TEST_BAD = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
    )
    SELF_TEST_OK = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()  # solverlint: ok(bare-thread-primitive): self-test snippet, never imported\n"
    )

    def globs(self, config):
        return config.thread_modules

    def check(self, mod, config, root):
        if mod.relpath == config.racecheck_module:
            return []  # the wrapper itself necessarily constructs primitives
        findings: list[Finding] = []
        mods, names = _threading_imports(mod.tree)
        for call in [n for n in ast.walk(mod.tree) if isinstance(n, ast.Call)]:
            prim = _threading_construct(call, mods, names)
            if prim in self.PRIMITIVES:
                findings.append(
                    self._finding(
                        mod,
                        call,
                        f"bare {dotted_name(call.func)}() constructs threading.{prim} — go through obs.racecheck "
                        f"(make_lock/make_rlock/make_event/spawn_thread) so KARPENTER_SOLVER_RACECHECK=1 can instrument it",
                    )
                )
        return findings


class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    description = "broad except handler that neither re-raises nor records the failure"

    # a serving stack only degrades gracefully when every absorbed failure
    # leaves a signal: a bare `except Exception: pass` is an invisible
    # failure domain. Handlers must re-raise, narrow the except to the
    # expected exception types, call a recorder (events publish / metrics
    # emission — config `exception_recorders`), or carry a justified pragma.
    SELF_TEST_BAD = (
        "def reconcile(store, nc):\n"
        "    try:\n"
        "        store.update(nc)\n"
        "    except Exception:\n"
        "        pass\n"
    )
    SELF_TEST_OK = (
        "def reconcile(store, nc, recorder, registry):\n"
        "    try:\n"
        "        store.update(nc)\n"
        "    except Exception as e:\n"
        '        recorder.publish(nc, "ReconcileError", str(e), type_="Warning")\n'
        "    try:\n"
        "        store.update(nc)\n"
        "    except Exception:\n"
        "        registry.inc()\n"
        "    try:\n"
        "        store.update(nc)\n"
        "    except Exception:\n"
        "        raise\n"
        "    try:\n"
        "        store.update(nc)\n"
        "    except ValueError:\n"
        "        pass\n"
        "    try:\n"
        "        store.update(nc)\n"
        "    except Exception:  # solverlint: ok(swallowed-exception): self-test snippet — proves the pragma form suppresses\n"
        "        pass\n"
    )

    _BROAD = frozenset({"Exception", "BaseException"})

    def globs(self, config):
        return config.exception_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.ExceptHandler):
                continue
            if n.type is not None:
                # `except (Exception, OSError):` is as broad as the unparenthesized
                # form — check every element of a tuple handler, not just the
                # single-name case (dotted_name returns "" for ast.Tuple)
                types = n.type.elts if isinstance(n.type, ast.Tuple) else (n.type,)
                if not any(dotted_name(t).rsplit(".", 1)[-1] in self._BROAD for t in types):
                    continue
            if any(isinstance(sub, ast.Raise) for stmt in n.body for sub in ast.walk(stmt)):
                continue
            def records(call: ast.Call) -> bool:
                # callee_matches resolves Name/Attribute chains; a CHAINED
                # call like registry.counter("m").inc(...) has a Call base,
                # so also match the bare method tail against the patterns
                if callee_matches(call.func, config.exception_recorders):
                    return True
                if isinstance(call.func, ast.Attribute):
                    from fnmatch import fnmatch

                    return any(fnmatch(f"x.{call.func.attr}", p) for p in config.exception_recorders)
                return False

            if any(
                isinstance(sub, ast.Call) and records(sub)
                for stmt in n.body
                for sub in ast.walk(stmt)
            ):
                continue
            caught = ", ".join(dotted_name(t) for t in types) if n.type is not None else "<bare except>"
            findings.append(
                Finding(
                    self.name,
                    mod.relpath,
                    n.lineno,
                    f"broad `except {caught}` handler neither re-raises nor records — narrow it, emit an "
                    f"event/metric, or justify with a pragma (silent failures defeat the degradation ladder)",
                )
            )
        return findings


# -- detlint: the determinism rules (ISSUE 19) --------------------------------


_SET_ANN_NAMES = frozenset({"set", "frozenset", "Set", "FrozenSet", "AbstractSet", "MutableSet"})
# set methods whose result is itself a set (order re-randomized, still unordered)
_SET_RETURNING_METHODS = frozenset({"union", "intersection", "difference", "symmetric_difference", "copy"})


def _ann_is_set(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    return dotted_name(ann).rsplit(".", 1)[-1] in _SET_ANN_NAMES


def _set_expr(node: ast.AST, setnames, self_attrs=frozenset()) -> bool:
    """Statically set-typed expression of non-literal origin. Literal
    `{a, b}` displays are the author's explicit enumeration and stay exempt;
    everything reaching here iterates in hash order."""
    if isinstance(node, ast.Name):
        return node.id in setnames
    if isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Attribute):
        return isinstance(node.value, ast.Name) and node.value.id == "self" and node.attr in self_attrs
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        return (
            isinstance(f, ast.Attribute)
            and f.attr in _SET_RETURNING_METHODS
            and _set_expr(f.value, setnames, self_attrs)
        )
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _set_expr(node.left, setnames, self_attrs) or _set_expr(node.right, setnames, self_attrs)
    if isinstance(node, ast.IfExp):
        return _set_expr(node.body, setnames, self_attrs) or _set_expr(node.orelse, setnames, self_attrs)
    return False


def _set_names(scope: ast.AST) -> set[str]:
    """Names of one scope that are set-typed on EVERY binding (the same
    flow-insensitive discipline as SharedArrayMutationRule's alias pass),
    grown to a fixpoint so `a = set(x); b = a | other` resolves."""
    entries: dict[str, list] = {}

    def note(name: str, kind: str, value=None):
        entries.setdefault(name, []).append((kind, value))

    if isinstance(scope, _SCOPE_KINDS):
        a = scope.args
        for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs, a.vararg, a.kwarg]:
            if arg is not None:
                note(arg.arg, "set" if _ann_is_set(arg.annotation) else "other")
    for n in _walk_scope(scope):
        if isinstance(n, ast.Assign):
            if len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                note(n.targets[0].id, "expr", n.value)
            else:
                for t in n.targets:
                    for leaf in _flat_targets(t):
                        if isinstance(leaf, ast.Name):
                            note(leaf.id, "other")
        elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
            if _ann_is_set(n.annotation):
                note(n.target.id, "set")
            elif n.value is not None:
                note(n.target.id, "expr", n.value)
            else:
                note(n.target.id, "other")
        elif isinstance(n, ast.AugAssign):
            # |=, &=, -=, ^= are kind-preserving on sets: no note, so
            # `s = set(); s |= more` keeps `s` set-typed
            if not isinstance(n.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
                for leaf in _flat_targets(n.target):
                    if isinstance(leaf, ast.Name):
                        note(leaf.id, "other")
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for leaf in _flat_targets(n.target):
                if isinstance(leaf, ast.Name):
                    note(leaf.id, "other")
        elif isinstance(n, ast.comprehension):
            for leaf in _flat_targets(n.target):
                if isinstance(leaf, ast.Name):
                    note(leaf.id, "other")
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            for leaf in _flat_targets(n.optional_vars):
                if isinstance(leaf, ast.Name):
                    note(leaf.id, "other")

    setnames: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, ents in entries.items():
            if name in setnames:
                continue
            if ents and all(
                kind == "set" or (kind == "expr" and _set_expr(value, setnames)) for kind, value in ents
            ):
                setnames.add(name)
                changed = True
    return setnames


class UnorderedIterationEscapeRule(Rule):
    name = "unordered-iteration-escape"
    description = "set/frozenset iteration (or id()-keyed ordering) escaping into ordered solver outputs"

    # callees that materialize/expose their argument's iteration order
    _ORDER_SENSITIVE_FUNCS = frozenset({"list", "tuple", "enumerate", "iter", "reversed", "map", "zip", "filter"})
    _ORDER_SENSITIVE_TAILS = frozenset({"array", "asarray", "fromiter", "fromkeys", "join", "extend"})
    # order-insensitive consumers: a generator over a set feeding one of
    # these never lands hash order in an output
    _ORDER_OK_FUNCS = frozenset({"sorted", "set", "frozenset", "sum", "len", "any", "all", "min", "max", "bool", "fsum", "stable_host_sum"})

    SELF_TEST_BAD = (
        "def decode(enc):\n"
        "    pending = set(enc.pending)\n"
        "    order = []\n"
        "    for p in pending:\n"
        "        order.append(p)\n"
        "    return order\n"
    )
    SELF_TEST_OK = (
        "def decode(enc):\n"
        "    pending = set(enc.pending)\n"
        "    order = []\n"
        "    for p in sorted(pending):\n"
        "        order.append(p)\n"
        "    for p in pending:  # solverlint: ok(unordered-iteration-escape): self-test snippet, never imported\n"
        "        order.append(p)\n"
        "    return order\n"
    )

    def globs(self, config):
        return config.det_modules

    @staticmethod
    def _id_key(key: ast.AST) -> bool:
        if isinstance(key, ast.Name) and key.id == "id":
            return True
        return (
            isinstance(key, ast.Lambda)
            and isinstance(key.body, ast.Call)
            and isinstance(key.body.func, ast.Name)
            and key.body.func.id == "id"
        )

    def check(self, mod, config, root):
        findings: list[Finding] = []
        # per-class: self attrs set-typed on every assignment module-wide,
        # so `self._groups = set()` in __init__ covers method bodies
        class_attrs: dict[int, frozenset] = {}
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            per: dict[str, list] = {}
            for n in ast.walk(cls):
                if isinstance(n, ast.Assign) and len(n.targets) == 1:
                    a = _self_lock_attr(n.targets[0])
                    if a is not None:
                        per.setdefault(a, []).append(("expr", n.value))
                elif isinstance(n, ast.AnnAssign):
                    a = _self_lock_attr(n.target)
                    if a is not None:
                        per.setdefault(a, []).append(("set", None) if _ann_is_set(n.annotation) else ("expr", n.value))
            attrs = frozenset(
                a
                for a, ents in per.items()
                if all(k == "set" or (v is not None and _set_expr(v, frozenset())) for k, v in ents)
            )
            if attrs:
                for meth in cls.body:
                    if isinstance(meth, _SCOPE_KINDS):
                        class_attrs[id(meth)] = attrs

        suggest = "iterate sorted(...), or justify with a pragma"
        for scope in _scopes(mod.tree):
            setnames = _set_names(scope)
            self_attrs = class_attrs.get(id(scope), frozenset())

            def is_set(node) -> bool:
                return _set_expr(node, setnames, self_attrs)

            # generator expressions whose sole consumer is order-insensitive
            exempt: set[int] = set()
            for n in _walk_scope(scope):
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id in self._ORDER_OK_FUNCS:
                    for a in n.args:
                        if isinstance(a, ast.GeneratorExp):
                            exempt.add(id(a))

            for n in _walk_scope(scope):
                if isinstance(n, (ast.For, ast.AsyncFor)) and is_set(n.iter):
                    findings.append(
                        Finding(
                            self.name,
                            mod.relpath,
                            n.lineno,
                            f"for-loop iterates a set: hash order escapes into the loop body — {suggest}",
                            span=(n.lineno, n.iter.end_lineno or n.lineno),
                        )
                    )
                elif isinstance(n, (ast.ListComp, ast.GeneratorExp, ast.DictComp)):
                    # a SetComp over a set stays unordered; list/dict/generator
                    # comprehensions freeze the hash order into their output
                    if isinstance(n, ast.GeneratorExp) and id(n) in exempt:
                        continue
                    if any(is_set(gen.iter) for gen in n.generators):
                        findings.append(
                            self._finding(mod, n, f"comprehension over a set freezes hash order into an ordered result — {suggest}")
                        )
                elif isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Name) and f.id in ("sorted", "min", "max"):
                        key = next((kw.value for kw in n.keywords if kw.arg == "key"), None)
                        if key is not None and self._id_key(key):
                            findings.append(
                                self._finding(mod, n, f"{f.id}(..., key=id) orders by memory address — address order varies run to run; key on content instead")
                            )
                    elif isinstance(f, ast.Name) and f.id in self._ORDER_SENSITIVE_FUNCS and any(is_set(a) for a in n.args):
                        findings.append(
                            self._finding(mod, n, f"{f.id}() materializes a set's hash order into an ordered value — {suggest}")
                        )
                    elif isinstance(f, ast.Attribute) and f.attr in self._ORDER_SENSITIVE_TAILS and any(is_set(a) for a in n.args):
                        findings.append(
                            self._finding(mod, n, f".{f.attr}() materializes a set's hash order into an ordered value — {suggest}")
                        )
                    elif isinstance(f, ast.Attribute) and f.attr == "pop" and not n.args and is_set(f.value):
                        findings.append(
                            self._finding(mod, n, "set.pop() takes a hash-order-arbitrary element — pick by sorted order or justify with a pragma")
                        )
                elif isinstance(n, ast.Starred) and is_set(n.value):
                    findings.append(
                        self._finding(mod, n, f"*-unpacking a set materializes hash order — {suggest}")
                    )
                elif isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], (ast.Tuple, ast.List)) and is_set(n.value):
                    findings.append(
                        self._finding(mod, n, "unpacking a set binds hash-order-arbitrary elements — sort first")
                    )
        return findings


class WallclockRngRule(Rule):
    name = "wallclock-and-rng-in-solve-path"
    description = "wallclock read or unseeded randomness reachable from the solve path"

    _TIME_FUNCS = frozenset({
        "time", "time_ns", "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
        "process_time", "process_time_ns", "thread_time", "thread_time_ns",
        "clock_gettime", "localtime", "gmtime", "ctime",
    })
    _DATETIME_FUNCS = frozenset({"now", "utcnow", "today"})
    _UUID_FUNCS = frozenset({"uuid1", "uuid4"})
    # constructors that are deterministic WHEN handed an explicit seed
    _SEEDED_WITH_ARG = frozenset({"Random", "default_rng", "RandomState", "seed", "SeedSequence", "Generator", "PRNGKey"})

    SELF_TEST_BAD = (
        "import random as rnd\n"
        "def tiebreak(order):\n"
        "    rnd.shuffle(order)\n"
        "    return order\n"
    )
    SELF_TEST_OK = (
        "import random as rnd\n"
        "def tiebreak(order, seed):\n"
        "    rng = rnd.Random(seed)\n"
        "    rng.shuffle(order)\n"
        "    rnd.shuffle(order)  # solverlint: ok(wallclock-and-rng-in-solve-path): self-test snippet, never imported\n"
        "    return order\n"
    )

    def globs(self, config):
        return config.solve_path_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        tree = mod.tree
        tm = _import_table(tree, "time")
        rd = _import_table(tree, "random")
        uu = _import_table(tree, "uuid")
        sec = _import_table(tree, "secrets")
        dt = _import_table(tree, "datetime")
        np_mods, np_names = _import_table(tree, "numpy")
        npr_mods, npr_names = _import_table(tree, "numpy.random")
        # names the numpy.random MODULE itself is bound to (import numpy.random
        # as npr / from numpy import random as nr)
        npr_aliases = set(npr_mods) | {local for local, attr in np_names.items() if attr == "random"}

        def flag(call, what):
            findings.append(self._finding(mod, call, what))

        for call in [n for n in ast.walk(tree) if isinstance(n, ast.Call)]:
            name = dotted_name(call.func)
            if not name:
                continue
            if callee_matches(call.func, config.seeded_rng):
                continue  # the reviewed seeded-RNG registry
            parts = name.split(".")
            tail = parts[-1]
            seeded = tail in self._SEEDED_WITH_ARG and bool(call.args or call.keywords)

            if _module_construct(call, *tm) in self._TIME_FUNCS:
                flag(call, f"{name}() reads the wallclock on the solve path — solve inputs must be replay-stable; take time from the injected clock seam or justify with a pragma")
            elif (len(parts) == 2 and parts[0] in rd[0]) or (len(parts) == 1 and parts[0] in rd[1]):
                resolved = rd[1].get(parts[0], tail) if len(parts) == 1 else tail
                if not (resolved in self._SEEDED_WITH_ARG and bool(call.args or call.keywords)) or resolved == "SystemRandom":
                    flag(call, f"{name}() draws unseeded randomness on the solve path — seed it explicitly or register the producer in [tool.solverlint] seeded-rng")
            elif (len(parts) >= 3 and parts[0] in np_mods and parts[1] == "random") or (len(parts) >= 2 and parts[0] in npr_aliases):
                if not seeded:
                    flag(call, f"{name}() draws from numpy's global/unseeded RNG on the solve path — use a seeded default_rng(seed) or register in seeded-rng")
            elif len(parts) == 1 and parts[0] in npr_names:
                if not (npr_names[parts[0]] in self._SEEDED_WITH_ARG and bool(call.args or call.keywords)):
                    flag(call, f"{name}() (from numpy.random) draws unseeded randomness on the solve path")
            elif _module_construct(call, *uu) in self._UUID_FUNCS:
                flag(call, f"{name}() mints a nondeterministic id on the solve path — derive ids from solve inputs (uuid5 over content, or a counter) or justify with a pragma")
            elif _module_construct(call, *sec) is not None:
                flag(call, f"{name}() reads OS entropy on the solve path — never replay-stable")
            elif tail in self._DATETIME_FUNCS and (
                (len(parts) >= 3 and parts[0] in dt[0]) or (len(parts) == 2 and dt[1].get(parts[0]) in ("datetime", "date"))
            ):
                flag(call, f"{name}() reads the wallclock on the solve path — take time from the injected clock seam")
        return findings


class FloatReductionOrderRule(Rule):
    name = "float-reduction-order"
    description = "order-sensitive float accumulation not routed through a canonical-order helper"

    SELF_TEST_BAD = (
        "def total(ts, items):\n"
        "    takes = greedy_pack_grouped_sharded(ts, items)\n"
        "    return sum(takes)\n"
    )
    SELF_TEST_OK = (
        "import math\n"
        "def total(ts, items):\n"
        "    takes = greedy_pack_grouped_sharded(ts, items)\n"
        "    a = math.fsum(takes)\n"
        "    b = sum(sorted(takes))\n"
        "    c = sum(takes)  # solverlint: ok(float-reduction-order): self-test snippet, never imported\n"
        "    return a + b + c\n"
    )

    def globs(self, config):
        return config.float_order_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        helpers = ", ".join(config.canonical_reduce_helpers)
        for scope in _scopes(mod.tree):
            setnames = _set_names(scope)
            # the HostSyncRule taint discipline: names assigned from device
            # producers, plus one fixpoint pass for name-to-name copies
            tainted: set[str] = set()
            copies: list[tuple[str, str]] = []
            for n in _walk_scope(scope):
                if not isinstance(n, ast.Assign):
                    continue
                if isinstance(n.value, ast.Call) and callee_matches(n.value.func, config.device_producers):
                    for t in n.targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
                elif isinstance(n.value, ast.Name) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    copies.append((n.targets[0].id, n.value.id))
            changed = True
            while changed:
                changed = False
                for dst, src in copies:
                    if src in tainted and dst not in tainted:
                        tainted.add(dst)
                        changed = True

            def device_expr(node) -> bool:
                if isinstance(node, ast.Name):
                    return node.id in tainted
                if isinstance(node, ast.Call) and callee_matches(node.func, config.device_producers):
                    return True
                return any(device_expr(child) for child in ast.iter_child_nodes(node))

            for n in _walk_scope(scope):
                if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id == "sum" and n.args):
                    continue
                arg = n.args[0]
                if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) and arg.func.id == "sorted":
                    continue  # canonical order imposed in place
                unordered = _set_expr(arg, setnames) or (
                    isinstance(arg, (ast.GeneratorExp, ast.ListComp))
                    and any(_set_expr(gen.iter, setnames) for gen in arg.generators)
                )
                if device_expr(arg):
                    findings.append(
                        self._finding(mod, n, f"builtin sum() folds device-derived floats in argument order — float addition does not commute bitwise; route through a canonical-order helper ({helpers}) or sum(sorted(...))")
                    )
                elif unordered:
                    findings.append(
                        self._finding(mod, n, f"builtin sum() folds floats in set hash order — route through a canonical-order helper ({helpers}) or sum(sorted(...))")
                    )
        return findings


class EnvDependentBranchRule(Rule):
    name = "env-dependent-branch"
    description = "os.environ read outside the registered KARPENTER_* knob table"

    SELF_TEST_BAD = (
        "import os as o\n"
        "def pick_mode():\n"
        '    return o.environ.get("KARPENTER_SOLVER_SECRET", "")\n'
    )
    SELF_TEST_OK = (
        "import os\n"
        "def pick_mode():\n"
        '    a = os.environ.get("KARPENTER_SOLVER_MESH", "")\n'
        '    b = os.getenv("KARPENTER_SOLVER_BUCKET")\n'
        '    c = os.environ.get("KARPENTER_SOLVER_SECRET", "")  # solverlint: ok(env-dependent-branch): self-test snippet, never imported\n'
        "    return a + (b or \"\") + c\n"
    )

    def globs(self, config):
        return config.solve_path_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        mods, names = _import_table(mod.tree, "os")
        knobs = set(config.env_knobs)

        def environ_expr(node) -> bool:
            if isinstance(node, ast.Attribute) and node.attr == "environ":
                return isinstance(node.value, ast.Name) and node.value.id in mods
            return isinstance(node, ast.Name) and names.get(node.id) == "environ"

        def check_key(node, key):
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                if key.value not in knobs:
                    findings.append(
                        self._finding(mod, node, f"env knob {key.value!r} is not in the registered knob table ([tool.solverlint] env-knobs) — an unregistered env probe can fork behavior between shard workers; register it or justify with a pragma")
                    )
            else:
                findings.append(
                    self._finding(mod, node, "os.environ read with a non-literal key — the knob table cannot review dynamic env probes; use a literal registered knob or justify with a pragma")
                )

        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call):
                f = n.func
                if _module_construct(n, mods, names) == "getenv":
                    check_key(n, n.args[0] if n.args else None)
                elif isinstance(f, ast.Attribute) and f.attr in ("get", "pop", "setdefault") and environ_expr(f.value):
                    check_key(n, n.args[0] if n.args else None)
                elif isinstance(f, ast.Attribute) and f.attr in ("items", "keys", "values", "copy") and environ_expr(f.value):
                    findings.append(
                        self._finding(mod, n, "bulk os.environ read on the solve path — enumerate registered knobs explicitly instead")
                    )
            elif isinstance(n, ast.Subscript) and environ_expr(n.value):
                check_key(n, n.slice)
            elif isinstance(n, ast.Compare) and any(isinstance(op, (ast.In, ast.NotIn)) for op in n.ops):
                if any(environ_expr(c) for c in n.comparators):
                    check_key(n, n.left)
        return findings


class StalePragmaRule(Rule):
    name = "stale-pragma"
    description = "a suppression pragma that no longer suppresses any finding"

    SELF_TEST_SHARED_FIELDS = frozenset({"sig_req"})
    SELF_TEST_BAD = (
        "def f(enc):\n"
        "    x = 1  # solverlint: ok(shared-array-mutation): suppresses nothing here — a dead pragma\n"
        "    return x\n"
    )
    SELF_TEST_OK = (
        "def f(enc):\n"
        "    enc.sig_req[0] = 1.0  # solverlint: ok(shared-array-mutation): live suppression — the pragma is load-bearing\n"
        "    return enc\n"
    )

    def globs(self, config):
        # standalone mode (--rule stale-pragma / fixture runs) re-derives
        # pragma usage by running every other rule on the module; the full
        # scan instead uses the driver's cheap post-pass over already-marked
        # modules (see core.run_analysis)
        return ("karpenter_tpu/**/*.py",)

    def check(self, mod, config, root):
        from .core import stale_pragma_findings

        for name, cls in RULES.items():
            if name == self.name:
                continue
            rule = cls()
            for f in rule.check(mod, config, root):
                mod.suppressed(f)  # marks pragma usage; the findings belong to the other rules
        return stale_pragma_findings(mod, set(RULES))


RULES: dict[str, type[Rule]] = {
    cls.name: cls
    for cls in (
        SharedArrayMutationRule,
        HostSyncRule,
        PodAxisLoopRule,
        ReasonFamilyTiersRule,
        MetricLabelCardinalityRule,
        GuardedFieldAccessRule,
        LockOrderRule,
        ThreadEscapeRule,
        BareThreadPrimitiveRule,
        SwallowedExceptionRule,
        UnorderedIterationEscapeRule,
        WallclockRngRule,
        FloatReductionOrderRule,
        EnvDependentBranchRule,
        StalePragmaRule,
    )
}
