"""The solverlint rules — this repo's real hazard classes, as AST passes.

1. shared-array-mutation     in-place writes to encode fields the registry
                             (encode.SHARED_ENCODE_FIELDS) declares shared by
                             reference between a base encode and its derived
                             masked/delta encodes.
2. host-sync-in-hot-path     `.item()` / `float()`/`int()`/`bool()` /
                             `np.asarray` on values produced by device
                             kernels inside the tensor-path modules.
3. python-loop-over-pod-axis `for` statements iterating pod-scaled
                             collections in tensor modules (per-signature
                             loops and comprehensions doing O(1) attribute
                             reads are the sanctioned cheap pass).
4. reason-family-tiers       every fallback family carries a tier, GLOBAL
                             families justify themselves, no stale entries
                             (absorbed from tests/test_solve_modes.py).
5. metric-label-cardinality  label values for bounded label keys at
                             counter/histogram call sites must be statically
                             enumerable, and the repo-wide literal set per
                             key stays under a cap.

Every rule ships SELF_TEST_BAD/SELF_TEST_OK snippets; `--self-test` proves
each rule still detects its seeded violation and that the pragma suppresses
it, so the gate fails loudly if rule discovery breaks.
"""

from __future__ import annotations

import ast

from .config import Config
from .core import Finding, ParsedModule, callee_matches, dotted_name

# lambdas are NOT a scope boundary here: they cannot contain assignments, so
# their bodies read the enclosing scope's names — scanning them in place is
# what lets the rules see a mutation/sync tucked into a sort key or callback
_SCOPE_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _walk_scope(node: ast.AST):
    """All nodes of one scope, not descending into nested functions."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_KINDS):
            stack.extend(ast.iter_child_nodes(n))


def _scopes(tree: ast.Module):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _flat_targets(target: ast.AST):
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flat_targets(elt)
    else:
        yield target


def _span(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, getattr(node, "end_lineno", node.lineno) or node.lineno)


class Rule:
    name = ""
    description = ""
    SELF_TEST_BAD = ""
    SELF_TEST_OK = ""
    SELF_TEST_SHARED_FIELDS: frozenset | None = None

    def globs(self, config: Config) -> tuple[str, ...]:
        return config.tensor_modules

    def check(self, mod: ParsedModule, config: Config, root) -> list[Finding]:  # pragma: no cover - interface
        raise NotImplementedError

    def finalize(self, config: Config) -> list[Finding]:
        return []

    def _finding(self, mod: ParsedModule, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, mod.relpath, node.lineno, message, span=_span(node))


class SharedArrayMutationRule(Rule):
    name = "shared-array-mutation"
    description = "in-place write to an encode field shared by reference with derived encodes"
    # ndarray methods that mutate in place
    MUTATOR_METHODS = frozenset({"fill", "sort", "resize", "itemset", "partition", "byteswap"})
    # numpy free functions (last dotted segment) whose first argument is written
    MUTATOR_FUNCS = frozenset({"put", "copyto", "place", "putmask", "at"})

    SELF_TEST_SHARED_FIELDS = frozenset({"sig_req"})
    SELF_TEST_BAD = "def f(enc):\n    enc.sig_req[0] = 1.0\n"
    SELF_TEST_OK = (
        "def f(enc):\n"
        "    enc.sig_req[0] = 1.0  # solverlint: ok(shared-array-mutation): self-test snippet, never imported\n"
    )

    def check(self, mod, config, root):
        fields = config.resolve_shared_fields(root)
        findings: list[Finding] = []
        for scope in _scopes(mod.tree):
            # flow-insensitive alias pass: a bare name stands in for a shared
            # field only when EVERY simple assignment to it reads one
            kinds: dict[str, set[str]] = {}
            alias_field: dict[str, str] = {}
            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    if isinstance(n.value, ast.Attribute) and n.value.attr in fields:
                        kinds.setdefault(n.targets[0].id, set()).add("reg")
                        alias_field[n.targets[0].id] = n.value.attr
                    else:
                        kinds.setdefault(n.targets[0].id, set()).add("other")
                elif isinstance(n, (ast.Assign, ast.AnnAssign, ast.For, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [getattr(n, "target", None)]
                    for t in targets:
                        if t is not None:
                            for leaf in _flat_targets(t):
                                if isinstance(leaf, ast.Name):
                                    kinds.setdefault(leaf.id, set()).add("other")
            aliases = {name for name, ks in kinds.items() if ks == {"reg"}}

            def shared(node) -> str | None:
                if isinstance(node, ast.Attribute) and node.attr in fields:
                    return node.attr
                if isinstance(node, ast.Name) and node.id in aliases:
                    return alias_field[node.id]
                return None

            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Subscript) and (f := shared(leaf.value)):
                                findings.append(
                                    self._finding(mod, n, f"in-place write to shared encode array {f!r}")
                                )
                elif isinstance(n, ast.AugAssign):
                    target = n.target.value if isinstance(n.target, ast.Subscript) else n.target
                    if f := shared(target):
                        findings.append(
                            self._finding(mod, n, f"augmented in-place write to shared encode array {f!r}")
                        )
                elif isinstance(n, ast.Call):
                    func = n.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr in self.MUTATOR_METHODS
                        and (f := shared(func.value))
                    ):
                        findings.append(
                            self._finding(mod, n, f".{func.attr}() mutates shared encode array {f!r}")
                        )
                    elif (
                        dotted_name(func).rsplit(".", 1)[-1] in self.MUTATOR_FUNCS
                        and n.args
                        and (f := shared(n.args[0]))
                    ):
                        findings.append(
                            self._finding(mod, n, f"{dotted_name(func)}() writes into shared encode array {f!r}")
                        )
        return findings


class HostSyncRule(Rule):
    name = "host-sync-in-hot-path"
    description = "host coercion of a device-kernel result inside a tensor-path module"
    COERCERS = frozenset({"float", "int", "bool"})
    ARRAYERS = frozenset({"np.asarray", "np.array", "numpy.asarray", "numpy.array"})
    # shape/metadata reads are static, never a device sync
    EXEMPT_ATTRS = frozenset({"shape", "size", "ndim", "dtype"})

    SELF_TEST_BAD = (
        "def f(t, items):\n"
        "    takes = greedy_pack_grouped_sharded(t, items)\n"
        "    return float(takes)\n"
    )
    SELF_TEST_OK = (
        "def f(t, items):\n"
        "    takes = greedy_pack_grouped_sharded(t, items)\n"
        "    return float(takes)  # solverlint: ok(host-sync-in-hot-path): self-test snippet, never imported\n"
    )

    def check(self, mod, config, root):
        findings: list[Finding] = []
        for scope in _scopes(mod.tree):
            tainted: set[str] = set()
            # any-assignment taint + one fixed-point pass for name-to-name copies
            copies: list[tuple[str, str]] = []
            for n in _walk_scope(scope):
                if not isinstance(n, ast.Assign):
                    continue
                if isinstance(n.value, ast.Call) and callee_matches(n.value.func, config.device_producers):
                    for t in n.targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
                elif isinstance(n.value, ast.Name) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    copies.append((n.targets[0].id, n.value.id))
            changed = True
            while changed:
                changed = False
                for dst, src in copies:
                    if src in tainted and dst not in tainted:
                        tainted.add(dst)
                        changed = True

            def device_expr(node) -> bool:
                # path-sensitive: a `.shape`/`.size`/... access prunes ONLY
                # its own subtree (a static metadata read), never the rest of
                # the expression — `float(takes.sum() / takes.shape[0])` is
                # still a sync on `takes.sum()`
                if isinstance(node, ast.Attribute) and node.attr in self.EXEMPT_ATTRS:
                    return False
                if isinstance(node, ast.Name):
                    return node.id in tainted
                if isinstance(node, ast.Call) and callee_matches(node.func, config.device_producers):
                    return True
                return any(device_expr(child) for child in ast.iter_child_nodes(node))

            for n in _walk_scope(scope):
                if not isinstance(n, ast.Call):
                    continue
                func = n.func
                if isinstance(func, ast.Attribute) and func.attr == "item" and not n.args and device_expr(func.value):
                    findings.append(self._finding(mod, n, ".item() host-syncs a device value"))
                elif (
                    isinstance(func, ast.Name)
                    and func.id in self.COERCERS
                    and len(n.args) == 1
                    and device_expr(n.args[0])
                ):
                    findings.append(
                        self._finding(mod, n, f"{func.id}() coerces a device value to host (blocking sync)")
                    )
                elif dotted_name(func) in self.ARRAYERS and n.args and device_expr(n.args[0]):
                    findings.append(
                        self._finding(mod, n, f"{dotted_name(func)}() lands a device array on host")
                    )
        return findings


class PodAxisLoopRule(Rule):
    name = "python-loop-over-pod-axis"
    description = "Python-level `for` statement iterating a pod-scaled collection in a tensor module"

    SELF_TEST_BAD = "def f(enc):\n    for p in enc.pods:\n        p.key()\n"
    SELF_TEST_OK = (
        "def f(enc):\n"
        "    for p in enc.pods:  # solverlint: ok(python-loop-over-pod-axis): self-test snippet, never imported\n"
        "        p.key()\n"
    )

    def check(self, mod, config, root):
        names = set(config.pod_axis_names)
        findings: list[Finding] = []
        for n in ast.walk(mod.tree):
            if not isinstance(n, (ast.For, ast.AsyncFor)):
                continue
            hit = None
            for sub in ast.walk(n.iter):
                if isinstance(sub, ast.Name) and sub.id in names:
                    hit = sub.id
                elif isinstance(sub, ast.Attribute) and sub.attr in names:
                    hit = dotted_name(sub) or sub.attr
                if hit:
                    break
            if hit:
                findings.append(
                    Finding(
                        self.name,
                        mod.relpath,
                        n.lineno,
                        f"Python loop over pod-scaled {hit!r} — vectorize, or justify with a pragma",
                        span=(n.lineno, n.iter.end_lineno or n.lineno),
                    )
                )
        return findings


class ReasonFamilyTiersRule(Rule):
    name = "reason-family-tiers"
    description = "fallback families must carry tiers; GLOBAL families must justify themselves"

    SELF_TEST_BAD = (
        'GLOBAL = "global"\n'
        'POD_LOCAL = "pod-local"\n'
        'REASON_FAMILIES = (("needle a", "fam-a"), ("needle b", "fam-b"))\n'
        "FAMILY_TIERS = {\n"
        '    "fam-a": GLOBAL,\n'
        '    "other": GLOBAL,\n'
        "}\n"
    )
    SELF_TEST_OK = (
        'GLOBAL = "global"\n'
        'POD_LOCAL = "pod-local"\n'
        'REASON_FAMILIES = (("needle a", "fam-a"), ("needle b", "fam-b"))\n'
        "FAMILY_TIERS = {\n"
        "    # the kernel cannot express this family's semantics\n"
        '    "fam-a": GLOBAL,\n'
        '    "fam-b": POD_LOCAL,\n'
        '    "other": GLOBAL,  # unattributable reasons take the conservative path\n'
        "}\n"
    )

    def globs(self, config):
        return (config.fallback_module,)

    def check(self, mod, config, root):
        findings: list[Finding] = []
        families: list[tuple[str, int]] | None = None
        tiers: ast.Dict | None = None
        for n in mod.tree.body:
            target = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                target = n.targets[0].id
            elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
                target = n.target.id
            if target == "REASON_FAMILIES" and isinstance(n.value, (ast.Tuple, ast.List)):
                families = []
                for elt in n.value.elts:
                    if (
                        isinstance(elt, (ast.Tuple, ast.List))
                        and len(elt.elts) == 2
                        and isinstance(elt.elts[1], ast.Constant)
                    ):
                        families.append((elt.elts[1].value, elt.lineno))
                    else:
                        findings.append(self._finding(mod, elt, "REASON_FAMILIES entry is not a (needle, family) pair"))
            elif target == "FAMILY_TIERS" and isinstance(n.value, ast.Dict):
                tiers = n.value
        if families is None or tiers is None:
            findings.append(
                Finding(self.name, mod.relpath, 1, "REASON_FAMILIES / FAMILY_TIERS registry not found in module")
            )
            return findings

        entries: list[tuple[str, int, ast.AST]] = []
        for key, value in zip(tiers.keys, tiers.values):
            if not isinstance(key, ast.Constant) or not isinstance(key.value, str):
                findings.append(self._finding(mod, key or tiers, "FAMILY_TIERS key is not a string literal"))
                continue
            entries.append((key.value, key.lineno, value))
            if not (isinstance(value, ast.Name) and value.id in ("GLOBAL", "POD_LOCAL")):
                findings.append(
                    self._finding(mod, value, f"tier for {key.value!r} must be the GLOBAL or POD_LOCAL constant")
                )
        keys = {k for k, _l, _v in entries}
        enum = {fam for fam, _l in families}
        for fam, line in families:
            if fam not in keys:
                findings.append(Finding(self.name, mod.relpath, line, f"family {fam!r} has no tier in FAMILY_TIERS"))
        if "other" not in keys:
            findings.append(
                Finding(self.name, mod.relpath, tiers.lineno, 'FAMILY_TIERS lacks the "other" conservative entry')
            )
        for key, line, _v in entries:
            if key not in enum and key != "other":
                findings.append(
                    Finding(self.name, mod.relpath, line, f"stale tier entry {key!r}: no such family in REASON_FAMILIES")
                )

        # every GLOBAL entry justifies itself: a trailing comment on the
        # entry, or a comment block heading its contiguous GLOBAL run
        global_lines = {
            line for _k, line, v in entries if isinstance(v, ast.Name) and v.id == "GLOBAL"
        }
        for key, line, value in entries:
            if not (isinstance(value, ast.Name) and value.id == "GLOBAL"):
                continue
            text = mod.lines[line - 1] if line - 1 < len(mod.lines) else ""
            tail = text[value.end_col_offset:] if value.end_lineno == line else ""
            if "#" in tail:
                continue
            j = line - 2  # 0-based index of the line above
            while j >= 0 and (j + 1) in global_lines:
                j -= 1
            if j >= 0 and mod.lines[j].lstrip().startswith("#"):
                continue
            findings.append(
                Finding(
                    self.name,
                    mod.relpath,
                    line,
                    f"GLOBAL family {key!r} lacks a one-line justification comment",
                )
            )
        return findings


class MetricLabelCardinalityRule(Rule):
    name = "metric-label-cardinality"
    description = "bounded metric labels must carry statically enumerable values"
    _ITER_WRAPPERS = frozenset({"sorted", "set", "list", "tuple"})

    # the seeded violation is a churn-label one: an events counter whose
    # `event` label carries a runtime value instead of the
    # {arrival | departure} enum — exactly the drift the serving loop's
    # call sites must never regress into
    SELF_TEST_BAD = (
        "def record(registry, batch, kind):\n"
        '    registry.counter("karpenter_solver_churn_events_total").inc(len(batch), event=kind)\n'
    )
    SELF_TEST_OK = (
        "def record(registry, pod):\n"
        '    registry.counter("m").inc(reason="bounded-value")\n'
    )

    def __init__(self):
        # label -> value -> first (path, line) seen, for the repo-wide cap
        self._literals: dict[str, dict[str, tuple[str, int]]] = {}

    def globs(self, config):
        return config.metrics_modules

    def check(self, mod, config, root):
        findings: list[Finding] = []
        bounded_labels = set(config.bounded_labels)
        wrappers = set(config.metric_wrappers)

        # (call, enclosing scope, enclosing function name)
        stack: list[tuple[ast.AST, ast.AST, str]] = [(mod.tree, mod.tree, "")]
        calls: list[tuple[ast.Call, ast.AST, str]] = []
        while stack:
            node, scope, fname = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    stack.append((child, child, child.name))
                else:
                    if isinstance(child, ast.Call):
                        calls.append((child, scope, fname))
                    stack.append((child, scope, fname))

        bindings_cache: dict[int, dict[str, list]] = {}

        def bindings(scope) -> dict[str, list]:
            cached = bindings_cache.get(id(scope))
            if cached is not None:
                return cached
            b: dict[str, list] = {}
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = scope.args
                for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs, a.vararg, a.kwarg]:
                    if arg is not None:
                        b.setdefault(arg.arg, []).append(("opaque", None))
            for n in _walk_scope(scope):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    b.setdefault(n.targets[0].id, []).append(("expr", n.value))
                elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name) and n.value is not None:
                    b.setdefault(n.target.id, []).append(("expr", n.value))
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    for leaf in _flat_targets(n.target):
                        if isinstance(leaf, ast.Name):
                            b.setdefault(leaf.id, []).append(("for", n.iter))
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                    for t in targets:
                        for leaf in _flat_targets(t):
                            if isinstance(leaf, ast.Name):
                                b.setdefault(leaf.id, []).append(("opaque", None))
            bindings_cache[id(scope)] = b
            return b

        def bounded(expr, scope, depth=0) -> tuple[bool, list[str]]:
            if depth > 6:
                return False, []
            if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
                return True, [expr.value]
            if isinstance(expr, ast.IfExp):
                ok1, l1 = bounded(expr.body, scope, depth + 1)
                ok2, l2 = bounded(expr.orelse, scope, depth + 1)
                return ok1 and ok2, l1 + l2
            if isinstance(expr, ast.BoolOp):
                lits: list[str] = []
                for v in expr.values:
                    ok, ls = bounded(v, scope, depth + 1)
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            if isinstance(expr, ast.Call) and callee_matches(expr.func, config.bounded_label_producers):
                return True, []
            if isinstance(expr, ast.Name):
                entries = bindings(scope).get(expr.id)
                if not entries:
                    return False, []
                lits = []
                for kind, val in entries:
                    if kind == "expr":
                        ok, ls = bounded(val, scope, depth + 1)
                    elif kind == "for":
                        ok, ls = bounded_iter(val, scope, depth + 1)
                    else:
                        ok, ls = False, []
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            return False, []

        def bounded_iter(expr, scope, depth=0) -> tuple[bool, list[str]]:
            if depth > 6:
                return False, []
            if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) and expr.func.id in self._ITER_WRAPPERS:
                return bounded_iter(expr.args[0], scope, depth + 1) if expr.args else (False, [])
            if isinstance(expr, (ast.SetComp, ast.ListComp, ast.GeneratorExp)):
                return bounded(expr.elt, scope, depth + 1)
            if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
                lits = []
                for elt in expr.elts:
                    ok, ls = bounded(elt, scope, depth + 1)
                    if not ok:
                        return False, []
                    lits += ls
                return True, lits
            return False, []

        def record(label: str, literals: list[str], node):
            for v in literals:
                self._literals.setdefault(label, {}).setdefault(v, (mod.relpath, node.lineno))

        def check_kw(label: str, value, scope, node):
            ok, literals = bounded(value, scope)
            if ok:
                record(label, literals, node)
            else:
                findings.append(
                    self._finding(
                        mod,
                        node,
                        f"label {label!r} value is not statically enumerable — pass a literal, an enum-bounded producer result, or justify with a pragma",
                    )
                )

        def dict_labels(expr) -> list[tuple[str, ast.AST]] | None:
            if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) and expr.func.id == "dict" and not expr.args:
                return [(kw.arg, kw.value) for kw in expr.keywords if kw.arg is not None]
            if isinstance(expr, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str) for k in expr.keys
            ):
                return [(k.value, v) for k, v in zip(expr.keys, expr.values)]
            return None

        for call, scope, fname in calls:
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in ("inc", "observe") and func.attr not in wrappers:
                continue
            if fname in wrappers:
                continue  # the wrapper's own **labels forwarding
            for kw in call.keywords:
                if kw.arg is not None:
                    if kw.arg in bounded_labels:
                        check_kw(kw.arg, kw.value, scope, call)
                    continue
                # **splat: resolve a locally-built dict literal
                resolved = None
                if isinstance(kw.value, ast.Name):
                    entries = bindings(scope).get(kw.value.id, [])
                    if len(entries) == 1 and entries[0][0] == "expr":
                        resolved = dict_labels(entries[0][1])
                else:
                    resolved = dict_labels(kw.value)
                if resolved is None:
                    findings.append(
                        self._finding(mod, call, "cannot statically bound **labels splat at metric call site")
                    )
                    continue
                for label, value in resolved:
                    if label in bounded_labels:
                        check_kw(label, value, scope, call)
        return findings

    def finalize(self, config):
        findings = []
        for label, values in self._literals.items():
            if len(values) > config.max_label_values:
                path, line = next(iter(values.values()))
                sample = ", ".join(sorted(values)[:6])
                findings.append(
                    Finding(
                        self.name,
                        path,
                        line,
                        f"label {label!r} carries {len(values)} distinct literal values repo-wide "
                        f"(cap {config.max_label_values}): {sample}, ... — an aggregate finding no "
                        f"line pragma can suppress; shrink the value set or raise max-label-values "
                        f"in [tool.solverlint]",
                    )
                )
        return findings


RULES: dict[str, type[Rule]] = {
    cls.name: cls
    for cls in (
        SharedArrayMutationRule,
        HostSyncRule,
        PodAxisLoopRule,
        ReasonFamilyTiersRule,
        MetricLabelCardinalityRule,
    )
}
