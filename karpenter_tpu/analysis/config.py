"""solverlint configuration: `[tool.solverlint]` in pyproject.toml.

Defaults below ARE the repo's configuration; pyproject entries override them
key-by-key (kebab-case keys map to the dataclass fields). The shared-field
registry is extracted from `solver/encode.py` by AST — the analyzer never
imports solver code, so the gate stays jax-free and fast.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path


class ConfigError(RuntimeError):
    """Configuration or registry extraction failed: the gate must fail
    loudly rather than pass vacuously."""


@dataclasses.dataclass
class Config:
    # modules on the tensor hot path: shared-array / host-sync / pod-loop
    # rules run only here
    tensor_modules: tuple[str, ...] = (
        "karpenter_tpu/solver/encode.py",
        "karpenter_tpu/solver/tpu.py",
        "karpenter_tpu/solver/check.py",
        # the decode/validate tail rides the same hot path: the consolidation
        # round's masked-sim probes and the LP/global rounding ladder are
        # per-round host work the pod-loop/host-sync rules must see
        "karpenter_tpu/solver/simulate.py",
        "karpenter_tpu/solver/consolidation.py",
    )
    # "<file>:<constant>" — the frozenset of EncodedSnapshot field names that
    # derived encodes share by reference
    shared_field_registry: str = "karpenter_tpu/solver/encode.py:SHARED_ENCODE_FIELDS"
    # the fallback-family registry module (reason-family-tiers rule)
    fallback_module: str = "karpenter_tpu/solver/fallback.py"
    # metric-label-cardinality scans every package module
    metrics_modules: tuple[str, ...] = ("karpenter_tpu/**/*.py",)
    # identifiers that mark an iterable as pod/offering-scaled (exact match
    # against bare names and attribute tails)
    pod_axis_names: tuple[str, ...] = ("pods", "n_pods")
    # callees whose results live on device: coercing them is a host sync.
    # fnmatch patterns over the dotted callee (and its last segment).
    device_producers: tuple[str, ...] = (
        # the trailing * also covers greedy_pack_grouped_sharded_state — the
        # meshed pack's carry-state variant returns device arrays just the same
        "greedy_pack_grouped_sharded*",
        "recredit_removals",
        "make_tensors",
        "make_item_tensors",
        "jnp.*",
        "jax.*",
        "lax.*",
    )
    # label keys that must be statically enumerable at counter/histogram
    # call sites (identity labels like nodepool/node_name are exempt).
    # "fn" (recompile sentinel) and "quantile" (rolling trace stats) are the
    # solvetrace label keys; "proposer" is the consolidation proposer enum
    # (lp | anneal | binary-search); "event" is the churn serving loop's
    # {arrival | departure} enum; "lock" is racecheck's static make_lock
    # call-site enum; "tenant" is the fleet front-end's capped label
    # (serving.fleet.tenant_label collapses past-the-cap registrations to
    # "overflow"); "cause" is the fleet wake-attribution enum
    # (obs.podtrace.WAKE_CAUSES) and "stage" the podtrace event-lifecycle
    # stage enum (obs.podtrace.STAGES); "state" is faultline's breaker-state
    # enum (serving.faults.TENANT_STATES — stage also covers the recovery
    # ladder's RECOVERY_STAGES) and "seam" its FAULT_SEAMS injection enum;
    # "shard" is the shardfleet router's capped label (serving.shard
    # shard_label — same overflow contract as tenant) — all held to the
    # same bound
    bounded_labels: tuple[str, ...] = ("reason", "backend", "mode", "decision", "kind", "phase", "fn", "quantile", "proposer", "event", "lock", "tenant", "cause", "stage", "state", "seam", "shard")
    # callees whose return value is enum-bounded by construction
    # (tenant_label caps distinct outputs at serving.fleet.TENANT_LABEL_CAP;
    # shard_label at serving.shard.SHARD_LABEL_CAP; demotion_label collapses
    # anything outside scheduler_model_grouped.DEMOTION_REASONS to "other")
    bounded_label_producers: tuple[str, ...] = ("reason_family", "_reason_family", "tenant_label", "shard_label", "demotion_label")
    # wrapper methods whose OWN bodies forward **labels to the registry
    metric_wrappers: tuple[str, ...] = ("_count", "_observe")
    # cap on distinct literal values per bounded label key, repo-wide
    max_label_values: int = 16
    # -- racecheck (the concurrency rules) ------------------------------------
    # modules on the THREADED serving path: guarded-field-access, lock-order,
    # thread-escape and bare-thread-primitive run only here (the long-lived
    # threads: prestager worker, churn driver, store watch delivery, operator
    # HTTP server, leader-election renewer — plus everything their callbacks
    # touch under a lock)
    thread_modules: tuple[str, ...] = (
        "karpenter_tpu/serving/*.py",
        "karpenter_tpu/kube/store.py",
        "karpenter_tpu/state/cluster.py",
        "karpenter_tpu/state/informer.py",
        "karpenter_tpu/state/cost.py",
        "karpenter_tpu/state/nodepoolhealth.py",
        "karpenter_tpu/metrics/registry.py",
        "karpenter_tpu/controllers/provisioning/batcher.py",
        "karpenter_tpu/controllers/provisioning/provisioner.py",
        "karpenter_tpu/controllers/nodeclaim/podevents.py",
        "karpenter_tpu/operator/*.py",
        "karpenter_tpu/obs/trace.py",
        "karpenter_tpu/obs/podtrace.py",
        "karpenter_tpu/obs/racecheck.py",
        "karpenter_tpu/events/__init__.py",
        "karpenter_tpu/utils/clock.py",
        "karpenter_tpu/__main__.py",
    )
    # the sanctioned wrapper module: the ONLY place raw threading primitives
    # may be constructed (bare-thread-primitive exempts it)
    racecheck_module: str = "karpenter_tpu/obs/racecheck.py"
    # the per-class guarded-field registry attribute (field -> guarding lock
    # attr), read by guarded-field-access AND obs.racecheck.touch at runtime
    guarded_registry_attr: str = "GUARDED_FIELDS"
    # call-site patterns that construct locks (identifies which self.<attr>
    # assignments in __init__ are locks, for both concurrency rules)
    lock_factories: tuple[str, ...] = ("make_lock", "make_rlock", "*.Lock", "*.RLock", "Lock", "RLock")
    # the thread-shared registry: sanctioned `threading.Thread(target=...)` /
    # `spawn_thread(...)` entry points and store-watch callbacks, matched by
    # fnmatch against the dotted callee, its tail, "EnclosingClass.tail",
    # and the path-qualified "<module relpath>:<name>" forms. Every entry is
    # a REVIEWED seam — its shared state is lock-guarded or provably
    # confined (see the inventory in karpenter_tpu/serving/__init__.py).
    # Generic callback names are path-qualified so a same-named function in
    # some future module is NOT silently sanctioned.
    thread_shared: tuple[str, ...] = (
        "PendingPrestager._run",
        "PendingPrestager._on_event",
        "*.serve_forever",  # stdlib ThreadingHTTPServer worker
        "*.renew_loop",  # LeaderElector renewer (target is a non-self attr)
        # fleet front-end (serving/fleet.py): the DRR serve loop thread and
        # the per-tenant watch->wake callback (runs on watch delivery; marks
        # the tenant runnable under the fleet's leaf locks)
        "FleetFrontend._serve_loop",
        "karpenter_tpu/serving/fleet.py:_on_watch_event",
        "karpenter_tpu/serving/churn.py:_churn_driver",
        # shardfleet (serving/shard.py): the router's per-shard run_all
        # driver threads (one writer per results key), the breaker-driven
        # health monitor, and the worker-side live env tick loop
        "ShardRouter._drive_shard",
        "ShardRouter._monitor_loop",
        "karpenter_tpu/serving/shard.py:_tick_loop",
        # informer/cost watch callbacks: they only call into the
        # lock-guarded Cluster/ClusterCost/Store surfaces
        "karpenter_tpu/state/informer.py:on_*",
        "karpenter_tpu/state/cost.py:on_*",
        "Cluster.mark_unconsolidated",
        "PodEventsController._on_pod_event",
        "Provisioner.trigger",
    )
    # methods that register a store-watch callback (thread-escape checks the
    # callback operand)
    watch_register_methods: tuple[str, ...] = ("watch",)
    # callee patterns that BLOCK (a solve, a device sync, watch-event
    # delivery): calling one while holding a lock is a lock-order finding
    lock_blocking_calls: tuple[str, ...] = ("*.solve", "solve_prepared", "_drain", "block_until_ready", "device_get")
    # method-name tails too generic to resolve cross-class in the lock-order
    # call graph (dict/list/set API names) — skipped to keep the static graph
    # from manufacturing edges out of `self._cache.get(...)`
    lock_call_blacklist: tuple[str, ...] = (
        "get", "set", "add", "pop", "update", "clear", "remove", "insert", "append",
        "extend", "discard", "popleft", "appendleft", "setdefault", "copy", "sort",
        "count", "items", "keys", "values", "reset", "total", "value", "sum", "join",
    )
    # the human-readable thread-and-lock inventory lock-order findings point at
    thread_inventory_doc: str = "karpenter_tpu/serving/__init__.py"
    # -- swallowed-exception (faultline) ---------------------------------------
    # modules the swallowed-exception rule scans: a bare `except Exception:`
    # (or broader) handler must re-raise or RECORD (an events publish / a
    # metrics emission) — a serving stack only degrades gracefully if every
    # absorbed failure leaves a signal. Suppression needs a justified pragma.
    exception_modules: tuple[str, ...] = ("karpenter_tpu/**/*.py",)
    # callee patterns (fnmatch over the dotted callee and its tail) that
    # count as RECORDING the failure inside the handler body
    exception_recorders: tuple[str, ...] = (
        "*.publish",  # events.Recorder
        "*.inc",
        "*.observe",
        "*.record_failure",
        "*._count",
        "*._observe",
        "*.warning",
        "*.error",
        "*.exception",
    )
    # -- detlint (the determinism rules, ISSUE 19) -----------------------------
    # modules on the BIT-IDENTICAL-PLACEMENT path: the unordered-iteration
    # rule runs here (solver encode/decode, the pack models, the serving
    # stack whose replay/re-homing contracts pin placement digests, and the
    # mesh-sharded pack)
    det_modules: tuple[str, ...] = (
        "karpenter_tpu/solver/*.py",
        "karpenter_tpu/models/*.py",
        "karpenter_tpu/serving/*.py",
        "karpenter_tpu/parallel/*.py",
    )
    # modules reachable from solve/encode/decode/consolidation entry points:
    # wallclock-and-rng-in-solve-path and env-dependent-branch run here (the
    # obs/tracing seams live outside these globs by design — a trace span's
    # perf_counter is observability, not solve input)
    solve_path_modules: tuple[str, ...] = (
        "karpenter_tpu/solver/*.py",
        "karpenter_tpu/models/*.py",
        "karpenter_tpu/parallel/*.py",
    )
    # the reviewed seeded-RNG registry: callee patterns (fnmatch over the
    # dotted callee, its tail, and "<relpath>:<name>") whose randomness is
    # seed-derived and replay-stable — jax.random's key-passing API is
    # deterministic by construction, and the serving FaultSpec / bench RNG
    # producers are reviewed seeded streams
    seeded_rng: tuple[str, ...] = (
        "jax.random.*",
        "jr.*",
    )
    # float-reduction-order scans the host-side accumulation sites adjacent
    # to the sharded pack and the models' host folds
    float_order_modules: tuple[str, ...] = (
        "karpenter_tpu/parallel/sharded.py",
        "karpenter_tpu/models/*.py",
    )
    # canonical-order reduction helpers: a host float accumulation routed
    # through one of these is order-stable by construction (math.fsum is
    # exact; stable_host_sum sorts its operands first)
    canonical_reduce_helpers: tuple[str, ...] = ("fsum", "math.fsum", "stable_host_sum")
    # the registered environment-knob table: every os.environ read in the
    # solve-path modules must name one of these reviewed KARPENTER_* knobs —
    # an unregistered env probe can silently fork behavior between shard
    # workers (env-dependent-branch)
    env_knobs: tuple[str, ...] = (
        "KARPENTER_SOLVER_TYPECHECK",
        "KARPENTER_SOLVER_RACECHECK",
        "KARPENTER_SOLVER_DETCHECK",
        "KARPENTER_SOLVER_COMPILE_CACHE",
        "KARPENTER_SOLVER_MESH",
        "KARPENTER_SOLVER_SHARD_DEVICES",
        "KARPENTER_SOLVER_BUCKET",
        "KARPENTER_SOLVER_MULTIGROUP",
        "KARPENTER_SOLVER_GLOBALPACK",
        "KARPENTER_ENCODE_COLUMNAR",
        # decode-delta escape hatch (tpu._decode re-materializes every slot
        # when off) and the consolidation round's shared-scheduler hatch
        # (simulate.ConsolidationSimulator skips the SchedulerRoundSeed carry
        # when off) — both are exact-reference toggles, placement-identical
        "KARPENTER_SOLVER_FASTDECODE",
        "KARPENTER_SIM_SHARED_SCHED",
    )
    # direct override for tests/self-test; when None the registry file is
    # parsed on first use
    shared_fields: frozenset | None = None

    def resolve_shared_fields(self, root: Path) -> frozenset:
        if self.shared_fields is not None:
            return self.shared_fields
        try:
            rel, _, attr = self.shared_field_registry.partition(":")
            src = (root / rel).read_text()
            tree = ast.parse(src)
        except OSError as e:
            raise ConfigError(f"shared-field registry unreadable: {e}") from e
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == attr for t in node.targets):
                continue
            names = frozenset(
                c.value for c in ast.walk(node.value) if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
            if names:
                self.shared_fields = names
                return names
        raise ConfigError(f"shared-field registry {self.shared_field_registry!r} not found or empty")


_KEYMAP = {f.name.replace("_", "-"): f.name for f in dataclasses.fields(Config)}


def load_config(root: Path) -> Config:
    """Config from `[tool.solverlint]`, falling back to the baked defaults."""
    cfg = Config()
    pyproject = root / "pyproject.toml"
    if not pyproject.exists():
        return cfg
    try:
        import tomllib
    except ModuleNotFoundError:  # py310: same API under its backport name
        import tomli as tomllib

    try:
        table = tomllib.loads(pyproject.read_text()).get("tool", {}).get("solverlint", {})
    except tomllib.TOMLDecodeError as e:
        raise ConfigError(f"pyproject.toml unparseable: {e}") from e
    for key, value in table.items():
        field = _KEYMAP.get(key)
        if field is None:
            raise ConfigError(f"[tool.solverlint] unknown key {key!r}")
        default = getattr(cfg, field)
        # type-check against the default so a mistyped entry is a loud
        # ConfigError (exit 2), not a mid-run TypeError read as "findings"
        if isinstance(default, tuple):
            if not isinstance(value, list) or not all(isinstance(v, str) for v in value):
                raise ConfigError(f"[tool.solverlint] {key} must be a list of strings")
            value = tuple(value)
        elif not isinstance(value, type(default)) or isinstance(value, bool) != isinstance(default, bool):
            raise ConfigError(f"[tool.solverlint] {key} must be {type(default).__name__}, got {type(value).__name__}")
        setattr(cfg, field, value)
    return cfg
