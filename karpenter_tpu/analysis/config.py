"""solverlint configuration: `[tool.solverlint]` in pyproject.toml.

Defaults below ARE the repo's configuration; pyproject entries override them
key-by-key (kebab-case keys map to the dataclass fields). The shared-field
registry is extracted from `solver/encode.py` by AST — the analyzer never
imports solver code, so the gate stays jax-free and fast.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path


class ConfigError(RuntimeError):
    """Configuration or registry extraction failed: the gate must fail
    loudly rather than pass vacuously."""


@dataclasses.dataclass
class Config:
    # modules on the tensor hot path: shared-array / host-sync / pod-loop
    # rules run only here
    tensor_modules: tuple[str, ...] = (
        "karpenter_tpu/solver/encode.py",
        "karpenter_tpu/solver/tpu.py",
        "karpenter_tpu/solver/check.py",
    )
    # "<file>:<constant>" — the frozenset of EncodedSnapshot field names that
    # derived encodes share by reference
    shared_field_registry: str = "karpenter_tpu/solver/encode.py:SHARED_ENCODE_FIELDS"
    # the fallback-family registry module (reason-family-tiers rule)
    fallback_module: str = "karpenter_tpu/solver/fallback.py"
    # metric-label-cardinality scans every package module
    metrics_modules: tuple[str, ...] = ("karpenter_tpu/**/*.py",)
    # identifiers that mark an iterable as pod/offering-scaled (exact match
    # against bare names and attribute tails)
    pod_axis_names: tuple[str, ...] = ("pods", "n_pods")
    # callees whose results live on device: coercing them is a host sync.
    # fnmatch patterns over the dotted callee (and its last segment).
    device_producers: tuple[str, ...] = (
        "greedy_pack_grouped_sharded",
        "recredit_removals",
        "make_tensors",
        "make_item_tensors",
        "jnp.*",
        "jax.*",
        "lax.*",
    )
    # label keys that must be statically enumerable at counter/histogram
    # call sites (identity labels like nodepool/node_name are exempt).
    # "fn" (recompile sentinel) and "quantile" (rolling trace stats) are the
    # solvetrace label keys; "proposer" is the consolidation proposer enum
    # (lp | anneal | binary-search); "event" is the churn serving loop's
    # {arrival | departure} enum — all held to the same bound
    bounded_labels: tuple[str, ...] = ("reason", "backend", "mode", "decision", "kind", "phase", "fn", "quantile", "proposer", "event")
    # callees whose return value is enum-bounded by construction
    bounded_label_producers: tuple[str, ...] = ("reason_family", "_reason_family")
    # wrapper methods whose OWN bodies forward **labels to the registry
    metric_wrappers: tuple[str, ...] = ("_count", "_observe")
    # cap on distinct literal values per bounded label key, repo-wide
    max_label_values: int = 16
    # direct override for tests/self-test; when None the registry file is
    # parsed on first use
    shared_fields: frozenset | None = None

    def resolve_shared_fields(self, root: Path) -> frozenset:
        if self.shared_fields is not None:
            return self.shared_fields
        try:
            rel, _, attr = self.shared_field_registry.partition(":")
            src = (root / rel).read_text()
            tree = ast.parse(src)
        except OSError as e:
            raise ConfigError(f"shared-field registry unreadable: {e}") from e
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == attr for t in node.targets):
                continue
            names = frozenset(
                c.value for c in ast.walk(node.value) if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
            if names:
                self.shared_fields = names
                return names
        raise ConfigError(f"shared-field registry {self.shared_field_registry!r} not found or empty")


_KEYMAP = {f.name.replace("_", "-"): f.name for f in dataclasses.fields(Config)}


def load_config(root: Path) -> Config:
    """Config from `[tool.solverlint]`, falling back to the baked defaults."""
    cfg = Config()
    pyproject = root / "pyproject.toml"
    if not pyproject.exists():
        return cfg
    try:
        import tomllib
    except ModuleNotFoundError:  # py310: same API under its backport name
        import tomli as tomllib

    try:
        table = tomllib.loads(pyproject.read_text()).get("tool", {}).get("solverlint", {})
    except tomllib.TOMLDecodeError as e:
        raise ConfigError(f"pyproject.toml unparseable: {e}") from e
    for key, value in table.items():
        field = _KEYMAP.get(key)
        if field is None:
            raise ConfigError(f"[tool.solverlint] unknown key {key!r}")
        default = getattr(cfg, field)
        # type-check against the default so a mistyped entry is a loud
        # ConfigError (exit 2), not a mid-run TypeError read as "findings"
        if isinstance(default, tuple):
            if not isinstance(value, list) or not all(isinstance(v, str) for v in value):
                raise ConfigError(f"[tool.solverlint] {key} must be a list of strings")
            value = tuple(value)
        elif not isinstance(value, type(default)) or isinstance(value, bool) != isinstance(default, bool):
            raise ConfigError(f"[tool.solverlint] {key} must be {type(default).__name__}, got {type(value).__name__}")
        setattr(cfg, field, value)
    return cfg
