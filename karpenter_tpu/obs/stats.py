"""Shared quantile math for every latency surface in the repo.

One nearest-rank implementation serves the e2e harness
(`testing/metrics_poller._p95`), the solvetrace rolling P50/P90/P99 windows
(`obs.trace.TraceRecorder`), and any test asserting exact quantile values.
The previous poller-local `round(q * (n - 1))` rule UNDERESTIMATES at small
n (n=13, q=0.95: round(11.4) -> the 12th sample instead of the 13th) and
inherits banker's-rounding surprises; nearest-rank is the Prometheus/NIST
definition — the smallest sample v such that at least ceil(q*n) samples
are <= v — and always returns a real sample, never an interpolation.
"""

from __future__ import annotations

import math


def quantile(values, q: float, assume_sorted: bool = False) -> float:
    """Nearest-rank quantile of `values` (any iterable of floats).

    Returns the ceil(q*n)-th smallest sample (1-based), clamped to the
    sample range; 0.0 for an empty input — matching the poller's historical
    empty-stats contract."""
    ordered = list(values) if assume_sorted else sorted(values)
    n = len(ordered)
    if n == 0:
        return 0.0
    idx = min(n - 1, max(0, math.ceil(q * n) - 1))
    return ordered[idx]


class RollingQuantiles:
    """A bounded window of observations with nearest-rank quantile reads.

    Append is O(1) (ring semantics via a capped list + cursor); quantile
    reads sort on demand — callers that read several quantiles at once
    should use `snapshot()` to pay the sort once."""

    __slots__ = ("_cap", "_items", "_head")

    def __init__(self, capacity: int):
        self._cap = max(1, int(capacity))
        self._items: list[float] = []
        self._head = 0

    def append(self, value: float) -> None:
        if len(self._items) < self._cap:
            self._items.append(float(value))
            return
        self._items[self._head] = float(value)
        self._head = (self._head + 1) % self._cap

    def __len__(self) -> int:
        return len(self._items)

    def snapshot(self) -> list[float]:
        """The window's samples, sorted ascending (one sort per read batch)."""
        return sorted(self._items)

    def quantile(self, q: float) -> float:
        return quantile(self.snapshot(), q, assume_sorted=True)
