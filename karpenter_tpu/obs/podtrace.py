"""podtrace: end-to-end event-lifecycle tracing for the fleet serving path.

solvetrace (obs/trace.py) instruments the SOLVE; this module instruments the
EVENT — the journey a watch event makes from the store's delivery seam to a
placement decision. Every Pod watch event delivered by `kube/store.py` is
stamped with a monotonic arrival time and threaded, cross-thread, through
the whole serving stack:

- ARRIVAL: `Store._drain` calls `PodTracer.on_delivery` per delivered event
  (commit + delivery stamps) — a new provisionable pod opens an EventRecord,
  a DELETE cancels it, and a MODIFIED carrying `spec.node_name` is the bind
  completion that closes the decode stage.
- COALESCE: the record sits in the batcher's idle/max window until the
  provisioner takes the generation; `Provisioner.provision` stamps dispatch
  on every traced pod in the batch (`on_dispatch`) and links the batch
  summary (count, oldest-event age, window residency) into the SolveTrace so
  `explain()` can join both views.
- SCHED WAIT: in fleet mode, `FleetFrontend._observe_sched_wait` hands the
  tenant's DRR wait (plus round and banked credit at dispatch) to the
  tracer; the next dispatch's events carry it. Zero outside a fleet.
- PRESTAGE: `PendingPrestager` stamps when it stages a pod's clone ahead of
  a take (`on_prestaged`) and marks take-misses — staged-vs-missed is the
  double-buffer's observable surface. The prestage stamp OVERLAPS the
  coalescing window by design, so it is reported as an attribute, never
  added into the linear e2e decomposition.
- SOLVE: `on_solved` stamps solve completion for the dispatched batch,
  records the linking SolveTrace seq, and COMPLETES placed events — e2e is
  event-to-PLACEMENT (the product's headline number); the later bind stamp
  fills the `decode` stage (decode -> claim -> lifecycle -> bind) without
  reopening the record.

Completed records land in a bounded ring with rolling per-stage P50/P90/P99
(published as the bounded `karpenter_solver_event_stage_quantile_seconds
{tenant, stage, quantile}` family), an SLO budget tracker (configurable
target via KARPENTER_PODTRACE_SLO, breach counter + burn rate), and a
Perfetto export (`obs/export.events_to_trace_events`) where watch-delivery /
serve-loop / prestage-worker render as separate tracks joined by flow
arrows. `/debug/events` (+ `?tenant=`) on the OperatorServer dumps the ring.

The additive contract: for a completed record,
    e2e == coalesce + sched_wait + solve        (placement)
and `decode` extends past placement to the observed bind. Tracing is
default-on (KARPENTER_PODTRACE=0 disables), must never change placements
(tests pin bit-identical results on vs off), and its cost is gated by
bench `event_latency` at the churn_sustained headline scale via the direct
self-time meter (`start_selftime`): <2% on the TPU target where the device
pack dominates and the host bookkeeping overlaps it; the 2-core CPU proxy
— where every microsecond of bookkeeping serializes with the solve —
gates at its measured ~4% floor, recorded with an explicit scope tag (the
fleet_compile_cache precedent). The hot
path is priced accordingly: delivery stamps are a few dict ops under a
leaf lock, completions cache their stage decomposition once, and the
quantile gauges publish per /metrics SCRAPE, never per event. Like the
rest of obs/, importing this module never initializes jax.
"""

from __future__ import annotations

import os
import time

from ..utils.ringbuffer import RingBuffer
from .racecheck import make_lock, touch
from .stats import quantile

# the bounded per-event stage enum (the `stage` metric label): the linear
# e2e decomposition plus the overlapped prestage stamp and the post-placement
# bind ("decode") tail. Quantile publication iterates exactly this tuple.
STAGES = ("coalesce", "sched_wait", "prestage", "solve", "decode", "e2e")

# the bounded fleet wake-cause enum (the `cause` label on
# karpenter_solver_fleet_wake_total): who made a tenant runnable first —
# the store watch seam, the batcher trigger hook, the serve loop's window
# (eta) timeout, the liveness poll floor, or a deterministic driver's rearm.
WAKE_CAUSES = ("watch-event", "batcher-window", "poll-floor", "rearm")

_QUANTILE_POINTS = {"p50": 0.50, "p90": 0.90, "p99": 0.99}
_MAX_ACTIVE = 200_000  # hard bound on in-flight records (pending backlog)


def _env_enabled() -> bool:
    return os.environ.get("KARPENTER_PODTRACE", "1").strip().lower() not in ("0", "false", "off")


def _env_slo() -> float:
    try:
        return float(os.environ.get("KARPENTER_PODTRACE_SLO", "0.25"))
    except ValueError:
        return 0.25


class EventRecord:
    """One watch event's lifecycle. Monotonic stamps are absolute
    perf-counter-family times; `to_dict` exports stage DURATIONS plus the
    wall-clock arrival so exports can place records on a shared timeline."""

    __slots__ = (
        "uid",
        "name",
        "key",
        "tenant",
        "seq",
        "rv",
        "wall_arrival",
        "t_arrival",
        "deliver_lag",
        "t_prestaged",
        "staged",
        "t_dispatch",
        "sched_wait",
        "drr_round",
        "drr_credit",
        "wake_cause",
        "t_solved",
        "solve_seq",
        "t_bound",
        "outcome",
        "stages",
    )

    def __init__(self, uid: str, name: str, tenant: str, rv, t_commit: float, t_deliver: float, key: str = ""):
        self.uid = uid
        self.name = name
        self.key = key or name
        self.tenant = tenant
        self.seq = 0  # assigned at completion (ring order)
        self.rv = rv
        self.wall_arrival = time.time()
        self.t_arrival = t_commit
        self.deliver_lag = max(0.0, t_deliver - t_commit)
        self.t_prestaged = 0.0
        self.staged = False
        self.t_dispatch = 0.0
        self.sched_wait = 0.0
        self.drr_round = 0
        self.drr_credit = 0.0
        self.wake_cause = ""
        self.t_solved = 0.0
        self.solve_seq = 0
        self.t_bound = 0.0
        self.outcome = ""  # "" in flight | placed | bound | cancelled | dropped
        # stage decomposition cached at completion (and patched at bind):
        # always recomputable from the stamps via stage_seconds() — the
        # cache exists so quantile reads over the ring cost dict lookups,
        # not recomputation, and completions skip per-stage window appends
        self.stages: dict[str, float] | None = None

    # -- derived stage durations ----------------------------------------------
    def stage_seconds(self) -> dict[str, float]:
        """The per-stage decomposition. `coalesce + sched_wait + solve` sums
        exactly to `e2e` (event-to-placement); `prestage` is the overlapped
        staging latency (informational) and `decode` the placement-to-bind
        tail observed from the bind's own watch event."""
        out = dict.fromkeys(STAGES, 0.0)
        if self.t_dispatch:
            out["sched_wait"] = self.sched_wait
            out["coalesce"] = max(0.0, self.t_dispatch - self.t_arrival - self.sched_wait)
        if self.staged and self.t_prestaged:
            hi = self.t_dispatch or self.t_prestaged
            out["prestage"] = max(0.0, min(self.t_prestaged, hi) - self.t_arrival)
        if self.t_solved and self.t_dispatch:
            out["solve"] = max(0.0, self.t_solved - self.t_dispatch)
            out["e2e"] = out["coalesce"] + out["sched_wait"] + out["solve"]
        if self.t_bound and self.t_solved:
            out["decode"] = max(0.0, self.t_bound - self.t_solved)
        return out

    def stage_view(self) -> dict[str, float]:
        """The cached stage decomposition when completed, else computed
        fresh from the stamps — the ONE cache-or-recompute seam every
        reader (to_dict, tracer stats, churn report, bench) goes through."""
        return self.stages if self.stages is not None else self.stage_seconds()

    def to_dict(self) -> dict:
        stages = self.stage_view()
        return {
            "seq": self.seq,
            "uid": self.uid,
            "name": self.name,
            "tenant": self.tenant,
            "wall_arrival": self.wall_arrival,
            "outcome": self.outcome,
            "staged": self.staged,
            "wake_cause": self.wake_cause,
            "sched_round": self.drr_round,
            "sched_credit": round(self.drr_credit, 3),
            "solve_seq": self.solve_seq,
            "deliver_lag_s": round(self.deliver_lag, 6),
            "stages": {k: round(v, 6) for k, v in stages.items()},
        }


class SLOBudget:
    """The event-latency SLO tracker: a configurable e2e target, a breach
    (burn) counter, and the remaining error budget against an allowed burn
    fraction. Mutated only under the owning tracer's lock."""

    __slots__ = ("target_seconds", "allowed_frac", "completed", "breaches")

    def __init__(self, target_seconds: float, allowed_frac: float = 0.01):
        self.target_seconds = float(target_seconds)
        self.allowed_frac = float(allowed_frac)
        self.completed = 0
        self.breaches = 0

    def observe(self, e2e: float) -> bool:
        self.completed += 1
        if e2e > self.target_seconds:
            self.breaches += 1
            return True
        return False

    def to_dict(self) -> dict:
        burn = (self.breaches / self.completed) if self.completed else 0.0
        return {
            "target_seconds": self.target_seconds,
            "allowed_breach_frac": self.allowed_frac,
            "completed": self.completed,
            "breaches": self.breaches,
            "burn_rate": round(burn, 6),
            "budget_remaining": round(max(0.0, 1.0 - burn / self.allowed_frac) if self.allowed_frac else 0.0, 6),
        }


class PodTracer:
    """The fleet-wide event flight recorder: one per tenant Environment
    (`env.podtracer`), hooked into the store's delivery seam and fed by the
    provisioner / fleet / prestager touch points above. Thread-safe: arrival
    and bind stamps land on watch-delivery threads (under the store's
    `_deliver_lock`), prestage stamps on the worker, dispatch/solve stamps on
    whatever thread pumps the loop — every mutation goes through `_lock`
    (leaf; metric emission happens OUTSIDE it, like the fleet's wake path)."""

    # racecheck guarded-field registry (analysis: guarded-field-access;
    # runtime: obs.racecheck.touch at the stat increments)
    GUARDED_FIELDS = {
        "_active": "_lock",
        "_awaiting_bind": "_lock",
        "_ring": "_lock",
        "_dispatched": "_lock",
        "_pending_sched": "_lock",
        "seq": "_lock",
        "dropped": "_lock",
        "cancelled": "_lock",
        "deliveries": "_lock",
        "wake_causes": "_lock",
        "prestage_misses": "_lock",
        "_dropped_published": "_lock",
    }

    def __init__(
        self,
        tenant: str = "",
        capacity: int = 2048,
        enabled: bool | None = None,
        slo_seconds: float | None = None,
        registry=None,
    ):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.tenant = tenant
        self.capacity = int(capacity)
        self.registry = registry
        self._lock = make_lock("podtrace")
        self._active: dict[str, EventRecord] = {}
        self._awaiting_bind: dict[str, EventRecord] = {}
        self._ring: RingBuffer[EventRecord] = RingBuffer(self.capacity)
        # uids stamped by the LAST on_dispatch — exactly the solve's batch,
        # so on_solved can never complete a record the solve never saw
        self._dispatched: set[str] = set()
        self._pending_sched: tuple[float, int, float, str] | None = None
        self.seq = 0  # completed-record sequence (ring order, the churn mark)
        self.dropped = 0  # records evicted from the ring or refused at the cap
        self.cancelled = 0
        self.deliveries = 0  # pod watch events observed at the seam
        self.wake_causes: dict[str, int] = {}
        self.prestage_misses = 0
        self._dropped_published = 0  # this tracer's share already on the counter
        self.slo = SLOBudget(_env_slo() if slo_seconds is None else slo_seconds)
        # direct self-cost meter (bench `event_latency`): when armed via
        # `start_selftime()`, every tracer entry point accumulates its own
        # wall time here — an EXACT measure of the tracing cost that a
        # differential on/off comparison cannot deliver on a noisy box.
        # Unarmed (the default), the hot paths pay one attribute check.
        self.selftime = 0.0
        self._selftime_on = False

    # on_prestaged is deliberately ABSENT: it delegates to
    # on_prestaged_batch, whose armed wrapper would otherwise be timed a
    # second time through the instance-attribute lookup (double-counting)
    _SELFTIME_POINTS = (
        "on_delivery",
        "on_dispatch",
        "on_solved",
        "on_prestaged_batch",
        "on_take_miss",
        "on_wake",
        "note_sched_wait",
    )

    def start_selftime(self) -> None:
        """Arm the meter by shadowing every entry point with a timed
        instance-attribute wrapper — the unarmed hot path is untouched (the
        wrappers only exist while armed). `selftime` accumulation is plain
        (exact on the single-threaded bench harness; approximate if armed
        under concurrent delivery, which the bench never does)."""
        self.selftime = 0.0
        self._selftime_on = True
        for name in self._SELFTIME_POINTS:
            orig = getattr(type(self), name)

            def _timed(*a, _orig=orig, **kw):
                t0 = time.perf_counter()
                try:
                    return _orig(self, *a, **kw)
                finally:
                    self.selftime += time.perf_counter() - t0

            setattr(self, name, _timed)

    def stop_selftime(self) -> float:
        self._selftime_on = False
        for name in self._SELFTIME_POINTS:
            self.__dict__.pop(name, None)
        return self.selftime

    # -- the store delivery seam (watch threads, under _deliver_lock) ---------
    def on_delivery(self, event: str, obj, t_commit: float, t_deliver: float) -> None:
        """Stamp one delivered watch event. Borrow contract: `obj` is the
        stored object — read scalar fields only, never retain or mutate.

        HOT PATH (runs per pod watch event under the store's delivery lock;
        the bench `event_latency` overhead gate prices every branch): the
        counters mutate under `_lock` like the registry declares but skip
        the per-call `touch()` assertion — the low-rate touch points
        (dropped/misses/wakes) keep the runtime arm's coverage."""
        if not self.enabled or obj.kind != "Pod":
            return
        meta = obj.metadata
        uid = meta.uid
        if event == "MODIFIED":
            if meta.deletion_timestamp is None and not obj.spec.node_name:
                return  # spec/status churn on a pending pod: nothing to stamp
            with self._lock:
                self.deliveries += 1
                rec = self._active.pop(uid, None)
                if meta.deletion_timestamp is not None:
                    if rec is not None:
                        rec.outcome = "cancelled"
                        self.cancelled += 1
                    else:
                        self._awaiting_bind.pop(uid, None)
                    return
                if rec is not None:
                    # bound before on_solved saw the placement (direct bind)
                    self._awaiting_bind[uid] = rec
                    return
                waiting = self._awaiting_bind.pop(uid, None)
                if waiting is not None:
                    # the bind closes the decode stage of the already-
                    # completed record: the ring entry (and its cached
                    # stage decomposition) updates in place
                    waiting.t_bound = t_deliver
                    waiting.outcome = "bound"
                    if waiting.stages is not None and waiting.t_solved:
                        waiting.stages["decode"] = max(0.0, t_deliver - waiting.t_solved)
            return
        if event == "DELETED":
            with self._lock:
                self.deliveries += 1
                rec = self._active.pop(uid, None)
                if rec is not None:
                    rec.outcome = "cancelled"
                    self.cancelled += 1
                else:
                    self._awaiting_bind.pop(uid, None)
            return
        # ADDED: a new provisionable pod opens the lifecycle record
        if obj.spec.node_name or meta.deletion_timestamp is not None:
            return
        with self._lock:
            self.deliveries += 1
            if len(self._active) >= _MAX_ACTIVE:
                touch(self, "dropped")
                self.dropped += 1
                return
            self._active[uid] = EventRecord(
                uid, meta.name, self.tenant, meta.resource_version, t_commit, t_deliver,
                key=f"{meta.namespace}/{meta.name}",
            )

    # -- the prestager seams (worker thread / solve thread) -------------------
    def on_prestaged(self, uid: str) -> None:
        self.on_prestaged_batch((uid,))

    def on_prestaged_batch(self, uids) -> None:
        """Stamp a whole prestager pump's staged pods under ONE lock hold
        (the pump drains bursts of watch events; per-pod locking here showed
        up in the event_latency overhead gate)."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            active = self._active
            for uid in uids:
                rec = active.get(uid)
                if rec is not None and not rec.t_prestaged:
                    rec.t_prestaged = now
                    rec.staged = True

    def on_take_miss(self, uid: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            touch(self, "prestage_misses")
            self.prestage_misses += 1

    # -- fleet wake / DRR seams -----------------------------------------------
    def on_wake(self, cause: str) -> None:
        """Count a wake signal by its bounded cause (the first signal that
        marked this tenant runnable — attribution, not a trigger count)."""
        if not self.enabled:
            return
        with self._lock:
            touch(self, "wake_causes")
            self.wake_causes[cause] = self.wake_causes.get(cause, 0) + 1

    def note_sched_wait(self, seconds: float, drr_round: int = 0, credit: float = 0.0, cause: str = "") -> None:
        """The fleet measured this tenant's runnable->dispatch wait (plus
        the wake cause that opened the runnable episode); the next
        `on_dispatch` applies them to every event in that batch."""
        if not self.enabled:
            return
        with self._lock:
            self._pending_sched = (float(seconds), int(drr_round), float(credit), cause)

    # -- the provisioner seams (solve thread) ---------------------------------
    def on_dispatch(self, pods, window: dict | None = None, cause: str = "") -> dict | None:
        """The provisioner took a generation and assembled its batch: stamp
        dispatch on every traced pod. Returns the event-batch summary the
        solver links into its SolveTrace ({count, oldest_age_s [, window_s,
        sched_wait_s]}), or None when nothing in the batch is traced."""
        if not self.enabled:
            return None
        now = time.monotonic()
        oldest = 0.0
        n = 0
        with self._lock:
            sched = self._pending_sched
            self._pending_sched = None
            if sched is not None and not cause:
                cause = sched[3]  # the wake cause that opened the episode
            dispatched = self._dispatched = set()
            for pod in pods:
                uid = pod.metadata.uid
                rec = self._active.get(uid)
                if rec is None:
                    continue
                rec.t_dispatch = now
                dispatched.add(uid)
                if sched is not None:
                    rec.sched_wait, rec.drr_round, rec.drr_credit = sched[0], sched[1], sched[2]
                if cause and not rec.wake_cause:
                    rec.wake_cause = cause
                oldest = max(oldest, now - rec.t_arrival)
                n += 1
        if not n:
            return None
        batch = {"count": n, "oldest_age_s": round(oldest, 6)}
        if window and window.get("count"):
            batch["window_s"] = round(window.get("window_s", 0.0), 6)
        if sched is not None:
            batch["sched_wait_s"] = round(sched[0], 6)
        return batch

    def on_solved(self, results, solve_seq: int = 0) -> None:
        """The solve finished: stamp completion for the dispatched batch and
        COMPLETE every placed event (e2e = event-to-placement). Unplaced
        events keep their record and re-stamp on the next dispatch.

        Placement membership is derived by INVERSION over the LAST
        dispatched batch (the `_dispatched` set on_dispatch just built —
        never earlier batches' strays): the solver contract puts every
        batch pod either in a node/claim or in `pod_errors`, so a batch
        record completes unless its pod key is errored — the error set is
        tiny/empty in steady state, where the placed set is the whole
        backlog (the event_latency overhead gate prices this scan)."""
        if not self.enabled:
            return
        now = time.monotonic()
        errored = set(getattr(results, "pod_errors", None) or ()) if results is not None else set()
        solved = results is not None
        finished: list[EventRecord] = []
        breaches = 0
        with self._lock:
            dispatched, self._dispatched = self._dispatched, set()
            for uid in dispatched:
                rec = self._active.get(uid)
                if rec is None:
                    continue
                if solved and rec.key not in errored:
                    rec.t_solved = now
                    rec.solve_seq = solve_seq
                    rec.outcome = "placed"
                    del self._active[uid]
                    self._awaiting_bind[uid] = rec
                    finished.append(rec)
            if len(self._awaiting_bind) > _MAX_ACTIVE:
                # a bind that never comes must not pin records forever
                self._awaiting_bind.clear()
            ring, cap = self._ring, self.capacity
            slo_observe = self.slo.observe
            for rec in finished:
                self.seq += 1
                rec.seq = self.seq
                if len(ring) >= cap:
                    touch(self, "dropped")
                    self.dropped += 1
                ring.insert(rec)
                stages = rec.stages = rec.stage_seconds()
                if slo_observe(stages["e2e"]):
                    breaches += 1
        # metric emission OUTSIDE the podtrace lock (leaf discipline): the
        # registry's own locks order below whatever the caller holds already.
        # Only the cheap SLO burn counter is emitted here — the quantile
        # gauges publish SCRAPE-driven (`publish_quantiles`, called by the
        # OperatorServer's /metrics handler), so the serving hot path never
        # sorts a stage window.
        if self.registry is not None and breaches:
            from ..metrics import SOLVER_EVENT_SLO_BREACH_TOTAL

            try:
                self.registry.counter(SOLVER_EVENT_SLO_BREACH_TOTAL).inc(breaches, tenant=self.tenant)  # solverlint: ok(metric-label-cardinality): tenant is the fleet registration label (a serving.fleet.tenant_label output; "" outside a fleet) — the bounded fleet enum
            except Exception:  # noqa: BLE001  # solverlint: ok(swallowed-exception): observability must never fail a solve — a broken SLO counter drops one increment
                pass

    def publish_quantiles(self) -> None:
        """Publish the rolling per-stage quantile gauges + the dropped
        counter. Scrape-driven: the /metrics handler calls this per scrape
        (and tests/dashboards may call it directly), so the sort cost rides
        the scrape, never the serving path."""
        if self.registry is None or not self.enabled:
            return
        from ..metrics import (
            SOLVER_EVENT_STAGE_QUANTILE_SECONDS,
            SOLVER_EVENT_TRACE_DROPPED_TOTAL,
        )

        try:
            g = self.registry.gauge(SOLVER_EVENT_STAGE_QUANTILE_SECONDS)
            for stage, qs in self.stats().items():
                if not qs["n"]:
                    continue
                for qn in _QUANTILE_POINTS:
                    g.set(qs[qn], tenant=self.tenant, stage=stage, quantile=qn)  # solverlint: ok(metric-label-cardinality): stage iterates the static STAGES tuple and quantile the three-point enum — both bounded by construction
            with self._lock:
                # publish THIS tracer's delta, not a sync against the shared
                # counter total — in fleet mode every tenant tracer feeds the
                # same unlabeled family, so totals must sum across tracers
                delta = self.dropped - self._dropped_published
                self._dropped_published = self.dropped
            if delta > 0:
                self.registry.counter(SOLVER_EVENT_TRACE_DROPPED_TOTAL).inc(delta)
        except Exception:  # noqa: BLE001  # solverlint: ok(swallowed-exception): observability must never break a scrape — the dropped-counter delta retries next scrape
            pass

    # -- reading ---------------------------------------------------------------
    def events(self) -> list[EventRecord]:
        with self._lock:
            return self._ring.items()

    def events_since(self, seq: int) -> list[EventRecord]:
        return [r for r in self.events() if r.seq > seq]

    def stats(self, records: list[EventRecord] | None = None) -> dict[str, dict[str, float]]:
        """{stage: {n, p50, p90, p99}} over the completed-record ring. The
        rolling window IS the ring: each record's decomposition is cached at
        completion, so this read sorts on demand instead of the hot path
        maintaining per-stage windows per completion. Callers that already
        snapshotted the ring (dump) pass it in to skip a second copy."""
        if records is None:
            records = self.events()
        out: dict[str, dict[str, float]] = {}
        for stage in STAGES:
            samples = sorted(r.stage_view()[stage] for r in records)
            out[stage] = {
                "n": len(samples),
                **{qn: quantile(samples, p, assume_sorted=True) for qn, p in _QUANTILE_POINTS.items()},
            }
        return out

    def dump(self, limit: int | None = None) -> dict:
        """The /debug/events payload: ring content (oldest first), rolling
        per-stage quantiles, SLO budget, wake-cause attribution, health."""
        ring = self.events()  # ONE snapshot serves both stats and the slice
        records = ring if limit is None else (ring[-limit:] if limit > 0 else [])
        with self._lock:
            out = {
                "enabled": self.enabled,
                "tenant": self.tenant,
                "capacity": self.capacity,
                "completed": self.seq,
                "in_flight": len(self._active),
                "awaiting_bind": len(self._awaiting_bind),
                "cancelled": self.cancelled,
                "deliveries": self.deliveries,
                "dropped": self.dropped,
                "prestage_misses": self.prestage_misses,
                "wake_causes": dict(self.wake_causes),
            }
        out["slo"] = self.slo.to_dict()
        out["stats"] = self.stats(ring)
        out["events"] = [r.to_dict() for r in records]
        return out


# -- the per-tenant surface registry ------------------------------------------
# `/debug/events?tenant=` and `/debug/solves?tenant=` resolve tenants here:
# the fleet front-end registers each session's (TraceRecorder, PodTracer)
# pair at add_tenant and unregisters at remove. Module-scoped like the
# fleet's label table; constructed through the sanctioned factory.
_TENANTS: dict[str, tuple[object, object]] = {}
_TENANTS_LOCK = make_lock("podtrace")


def register_tenant(label: str, recorder, tracer) -> None:
    with _TENANTS_LOCK:
        _TENANTS[label] = (recorder, tracer)


def unregister_tenant(label: str) -> None:
    with _TENANTS_LOCK:
        _TENANTS.pop(label, None)


def tenant_surfaces() -> dict[str, tuple[object, object]]:
    with _TENANTS_LOCK:
        return dict(_TENANTS)


def reset_tenants() -> None:
    """Drop the registrations (test isolation)."""
    with _TENANTS_LOCK:
        _TENANTS.clear()
