"""solvetrace: a per-solve flight recorder for the scheduling solver.

Every `TPUSolver.solve` produces one `SolveTrace`: the mode/backend that
served it, a span tree of its phases (encode/pack/residual/decode, with the
host FFD's per-phase split attached when a fallback or residual ran), the
cache-hit attribution that explains WHY the solve took the path it did
(encode delta vs full, row-cache hit, FFD fit-memo stats, repair counts,
fallback reason families), and a JIT-recompile stamp from the sentinel
below. Traces land in a bounded ring (`TraceRecorder`) that maintains
rolling P50/P90/P99 per (mode, phase), published as the
`karpenter_solver_solve_quantile_seconds` gauge family and dumped whole via
the OperatorServer's `/debug/solves` route or the `python -m
karpenter_tpu.obs` exporter.

Overhead contract: recording must never change placements (the solver's
on/off parity test pins bit-identical results) and costs <2% on the 50k-pod
scenario (bench's `trace_overhead_pct` asserts it). The span API times with
bare `time.perf_counter()` pairs exactly like the hand-rolled timers it
replaced; a disabled recorder (KARPENTER_SOLVETRACE=0) skips the span tree,
ring, sentinel, and quantile publication but keeps the flat per-phase
totals so the `last_phase_seconds` compat surface stays truthful either way.

This module imports neither jax nor numpy: the sentinel discovers jitted
entry points through `sys.modules`, so building a trace never forces a
device backend to initialize.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..utils.ringbuffer import RingBuffer
from .racecheck import make_lock
from .stats import RollingQuantiles, quantile

QUANTILE_NAMES = ("p50", "p90", "p99")
_QUANTILE_POINTS = {"p50": 0.50, "p90": 0.90, "p99": 0.99}

# The solver's jitted entry points, watched by the recompile sentinel:
# (fn label, module, attribute). Labels are the `fn` metric label values —
# a static enum by construction. The meshed (shard_map) kernels are now the
# DEFAULT multi-device pack and are watched through the module-level
# `_JitCacheProbe` objects in parallel/sharded.py: the per-(mesh, statics)
# jits live inside lru_caches, so each probe aggregates `_cache_size()`
# over every kernel it built — warm meshed re-solves must record zero here
# exactly like the single-device path.
JIT_WATCHLIST = (
    ("pack_full", "karpenter_tpu.models.scheduler_model_grouped", "_pack_compressed_impl"),
    ("pack_delta", "karpenter_tpu.models.scheduler_model_grouped", "_pack_delta_compressed_impl"),
    ("pack_grouped", "karpenter_tpu.models.scheduler_model_grouped", "_greedy_pack_grouped_impl"),
    ("recredit", "karpenter_tpu.models.scheduler_model_grouped", "_recredit_impl"),
    ("pack_perpod", "karpenter_tpu.models.scheduler_model", "_greedy_pack_impl"),
    ("anneal", "karpenter_tpu.models.consolidation_model", "anneal_chains"),
    ("lp_repack", "karpenter_tpu.models.globalpack", "_globalpack_impl"),
    ("lp_score", "karpenter_tpu.models.globalpack", "_score_subsets_impl"),
    ("pack_sharded", "karpenter_tpu.parallel.sharded", "pack_sharded_probe"),
    ("shard_feas", "karpenter_tpu.parallel.sharded", "shard_compat_probe"),
)


class RecompileSentinel:
    """Detects JIT recompiles by diffing the watched functions' compile-cache
    sizes around each solve. `jax.jit` wrappers expose `_cache_size()` (the
    in-memory trace/executable cache), which grows exactly when a call sees
    an unseen static/shape signature — a retrace, i.e. the event the churn
    loop's "zero steady-state recompiles" target forbids. Functions whose
    module is not imported yet simply don't appear in the snapshot; a module
    imported MID-solve contributes its first compile to that solve's delta
    (before-count defaults to 0), which is the honest attribution."""

    def __init__(self, watchlist=JIT_WATCHLIST):
        self.watchlist = tuple(watchlist)

    def snapshot(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for label, modname, attr in self.watchlist:
            mod = sys.modules.get(modname)
            fn = getattr(mod, attr, None) if mod is not None else None
            size = getattr(fn, "_cache_size", None)
            if size is None:
                continue
            try:
                out[label] = int(size())
            except Exception:  # noqa: BLE001  # solverlint: ok(swallowed-exception): a broken jit-cache probe must never fail a solve; the sentinel just skips the entry
                continue
        return out

    def delta(self, before: dict[str, int] | None) -> dict[str, int]:
        """Per-fn cache-entry increments since `before` (positive only)."""
        before = before or {}
        after = self.snapshot()
        return {k: v - before.get(k, 0) for k, v in after.items() if v > before.get(k, 0)}


_SENTINEL = RecompileSentinel()


def sentinel() -> RecompileSentinel:
    return _SENTINEL


class Span:
    """One timed phase. `t0` is a perf_counter stamp (exported relative to
    the owning trace's start); `attrs` carry small structured context like
    the encode mode."""

    __slots__ = ("name", "t0", "dur", "attrs", "children")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.t0 = 0.0
        self.dur = 0.0
        self.attrs = attrs
        self.children: list[Span] = []

    def to_dict(self, base: float) -> dict:
        d = {"name": self.name, "start_s": round(self.t0 - base, 6), "dur_s": round(self.dur, 6)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict(base) for c in self.children]
        return d


class _SpanHandle:
    __slots__ = ("_trace", "span")

    def __init__(self, trace: "SolveTrace", span: Span):
        self._trace = trace
        self.span = span

    def __enter__(self) -> Span:
        tr = self._trace
        if tr.enabled:
            parent = tr._stack[-1] if tr._stack else None
            (parent.children if parent is not None else tr.spans).append(self.span)
            tr._stack.append(self.span)
        self.span.t0 = time.perf_counter()
        return self.span

    def __exit__(self, et, ev, tb):
        s = self.span
        s.dur = time.perf_counter() - s.t0
        tr = self._trace
        tr.phase_totals[s.name] = tr.phase_totals.get(s.name, 0.0) + s.dur
        if tr.enabled and tr._stack and tr._stack[-1] is s:
            tr._stack.pop()
        return False


class SolveTrace:
    """The flight record of one solve. Mutated in place by the solver's exit
    paths (mode/backend writes arrive through the `last_solve_mode` compat
    setters) and sealed by `TraceRecorder.commit`."""

    __slots__ = (
        "seq",
        "enabled",
        "wall_time",
        "t0",
        "duration",
        "mode",
        "backend",
        "n_pods",
        "n_sigs",
        "fallback_reasons",
        "attribution",
        "phase_totals",
        "spans",
        "_stack",
        "recompiles",
        "jit_before",
    )

    def __init__(self, seq: int = 0, enabled: bool = False, n_pods: int = 0):
        self.seq = seq
        self.enabled = enabled
        self.wall_time = time.time()
        self.t0 = time.perf_counter()
        self.duration = 0.0
        self.mode = ""
        self.backend = ""
        self.n_pods = n_pods
        self.n_sigs = 0
        self.fallback_reasons: list[str] = []
        self.attribution: dict = {}
        self.phase_totals: dict[str, float] = {}
        self.spans: list[Span] = []
        self._stack: list[Span] = []
        self.recompiles: dict[str, int] = {}
        self.jit_before: dict[str, int] | None = None

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **attrs) -> _SpanHandle:
        return _SpanHandle(self, Span(name, attrs))

    def add_phase(self, name: str, dur: float, **attrs) -> None:
        """Record an already-measured phase (the host FFD accumulates its
        per-pod phase split in counters; this folds the totals in as spans
        back-dated by their duration)."""
        self.phase_totals[name] = self.phase_totals.get(name, 0.0) + dur
        if self.enabled:
            s = Span(name, attrs)
            s.t0 = time.perf_counter() - dur
            s.dur = dur
            parent = self._stack[-1] if self._stack else None
            (parent.children if parent is not None else self.spans).append(s)

    def note(self, **kv) -> None:
        """Attach cache-hit / fallback / repair attribution facts."""
        if self.enabled:
            self.attribution.update(kv)

    # -- reading -------------------------------------------------------------
    @property
    def families(self) -> list[str]:
        from ..solver.fallback import reason_family

        return sorted({reason_family(r) for r in self.fallback_reasons})

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "wall_time": self.wall_time,
            "mode": self.mode,
            "backend": self.backend,
            "n_pods": self.n_pods,
            "n_sigs": self.n_sigs,
            "duration_s": round(self.duration, 6),
            "phases": {k: round(v, 6) for k, v in self.phase_totals.items()},
            "spans": [s.to_dict(self.t0) for s in self.spans],
            "cache": dict(self.attribution),
            "fallback_reasons": list(self.fallback_reasons),
            "fallback_families": self.families,
            "recompiles": dict(self.recompiles),
        }

    def explain(self) -> str:
        """Answer "why did this solve go the way it did" from the recorded
        attribution — the human-facing rendering of the trace."""
        a = self.attribution
        lines = [
            f"solve #{self.seq}: mode={self.mode or '?'} backend={self.backend or '?'} "
            f"{self.duration * 1e3:.2f}ms, {self.n_pods} pods ({self.n_sigs} signatures)"
        ]
        phases = ", ".join(f"{k}={v * 1e3:.2f}ms" for k, v in sorted(self.phase_totals.items()))
        if phases:
            lines.append(f"  phases: {phases}")
        enc = a.get("encode_mode")
        if enc is not None:
            row = "hit" if a.get("row_cache") else "miss"
            lines.append(f"  encode: mode={enc} row_cache={row}")
        eb = a.get("event_batch")
        if eb:
            extra = f", window {eb['window_s'] * 1e3:.1f}ms" if "window_s" in eb else ""
            extra += f", sched wait {eb['sched_wait_s'] * 1e3:.1f}ms" if "sched_wait_s" in eb else ""
            lines.append(
                f"  events: {eb.get('count', 0)} traced watch event(s), oldest "
                f"{eb.get('oldest_age_s', 0.0) * 1e3:.1f}ms old at dispatch{extra} (podtrace: /debug/events)"
            )
        if self.mode in ("hybrid", "hybrid-delta"):
            lines.append(
                f"  why hybrid: pod-local fallback families {self.families} "
                f"flagged {a.get('residual_pods', '?')} residual pod(s); the tensor majority packed on device"
            )
        elif self.mode == "fallback":
            lines.append(f"  why fallback: {self.families} — whole snapshot on the host FFD")
        elif self.mode == "delta":
            refresh = " + row refresh" if a.get("row_refresh") else ""
            lines.append(
                f"  why delta: pod delta of the previous solve "
                f"(+{a.get('delta_added', 0)}/-{a.get('delta_removed', 0)} pods{refresh}) re-packed from device-resident state"
            )
        if a.get("delta_reject"):
            lines.append(
                f"  why not delta: {a['delta_reject']} — the delta classifier routed this solve to the full path"
            )
        if a.get("repair_pods"):
            lines.append(
                f"  repair: {a['repair_pods']} pod(s) of {a.get('repair_sigs', '?')} signature(s) "
                f"re-solved on the bounded host repair ({a.get('repair_reason', 'min-values')})"
            )
        memo = a.get("ffd_memo")
        if memo:
            probes = sum(memo.values()) or 1
            lines.append(f"  ffd memo: {memo} (hit rate {memo.get('hit', 0) / probes:.1%})")
        if self.recompiles:
            lines.append(f"  recompiles: {self.recompiles} — this solve paid a JIT trace/compile")
        else:
            lines.append("  recompiles: none")
        return "\n".join(lines)


_tls = threading.local()


def current_trace() -> SolveTrace | None:
    """The solve trace active on this thread, if any — how layers below the
    solver (host FFD scheduler, residual path) attach their phase splits
    without plumbing a trace argument through every signature."""
    return getattr(_tls, "trace", None)


def _env_enabled() -> bool:
    return os.environ.get("KARPENTER_SOLVETRACE", "1").strip().lower() not in ("0", "false", "off")


class TraceRecorder:
    """Bounded ring of the last `capacity` SolveTraces plus rolling
    per-(mode, phase) quantile windows. Thread-safe; one process-wide default
    instance serves every solver unless a private one is injected (tests,
    the bench's tracing-off arm)."""

    # racecheck guarded-field registry: solves commit from whatever thread
    # ran them while /debug/solves reads from HTTP handler threads
    GUARDED_FIELDS = {"_ring": "_lock", "_windows": "_lock", "dropped": "_lock", "seq": "_lock"}

    def __init__(self, capacity: int = 256, enabled: bool | None = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.capacity = int(capacity)
        self._ring: RingBuffer[SolveTrace] = RingBuffer(self.capacity)
        self._windows: dict[tuple[str, str], RollingQuantiles] = {}
        self.dropped = 0
        self.seq = 0
        self._lock = make_lock("trace")

    # -- lifecycle -----------------------------------------------------------
    def begin(self, n_pods: int = 0) -> SolveTrace:
        with self._lock:
            self.seq += 1
            seq = self.seq
        tr = SolveTrace(seq=seq, enabled=self.enabled, n_pods=n_pods)
        _tls.trace = tr
        return tr

    def commit(self, trace: SolveTrace, registry=None) -> None:
        if getattr(_tls, "trace", None) is trace:
            _tls.trace = None
        trace.duration = time.perf_counter() - trace.t0
        if not trace.enabled:
            return
        mode = trace.mode or "none"
        with self._lock:
            if len(self._ring) >= self.capacity:
                self.dropped += 1
                if registry is not None:
                    from ..metrics import SOLVER_TRACE_DROPPED_TOTAL

                    registry.counter(
                        SOLVER_TRACE_DROPPED_TOTAL, "SolveTraces evicted from the bounded ring", ()
                    ).inc()
            self._ring.insert(trace)
            changed = [("total", trace.duration), *trace.phase_totals.items()]
            for phase, dt in changed:
                win = self._windows.get((mode, phase))
                if win is None:
                    win = self._windows[(mode, phase)] = RollingQuantiles(self.capacity)
                win.append(dt)
        if registry is not None:
            self._publish(registry, mode, [p for p, _ in changed], trace.recompiles)

    def _publish(self, registry, mode: str, phases: list[str], recompiles: dict[str, int]) -> None:
        from ..metrics import SOLVER_RECOMPILE_TOTAL, SOLVER_SOLVE_QUANTILE_SECONDS

        if recompiles:
            c = registry.counter(SOLVER_RECOMPILE_TOTAL, "JIT recompiles by solver entry point", ("fn",))
            for fn, n in sorted(recompiles.items()):
                c.inc(n, fn=fn)  # solverlint: ok(metric-label-cardinality): fn is always a label from the static JIT_WATCHLIST registry — enum-bounded by construction
        g = registry.gauge(
            SOLVER_SOLVE_QUANTILE_SECONDS,
            "Rolling solve-latency quantiles over the trace ring, per (mode, phase)",
            ("mode", "phase", "quantile"),
        )
        for phase in phases:
            with self._lock:
                win = self._windows.get((mode, phase))
                samples = win.snapshot() if win is not None else []
            if not samples:
                continue
            for qn in ("p50", "p90", "p99"):
                g.set(quantile(samples, _QUANTILE_POINTS[qn], assume_sorted=True), mode=mode, phase=phase, quantile=qn)  # solverlint: ok(metric-label-cardinality): mode is the solver's exit-path enum and phase the span-name enum — both bounded by construction

    # -- reading -------------------------------------------------------------
    def traces(self) -> list[SolveTrace]:
        with self._lock:
            return self._ring.items()

    def last(self) -> SolveTrace | None:
        items = self.traces()
        return items[-1] if items else None

    def stats(self) -> dict[str, dict[str, float]]:
        """{"<mode>/<phase>": {n, p50, p90, p99}} over the rolling windows."""
        with self._lock:
            wins = dict(self._windows)
        out: dict[str, dict[str, float]] = {}
        for (mode, phase), win in sorted(wins.items()):
            samples = win.snapshot()
            out[f"{mode}/{phase}"] = {
                "n": len(samples),
                **{qn: quantile(samples, _QUANTILE_POINTS[qn], assume_sorted=True) for qn in QUANTILE_NAMES},
            }
        return out

    def summary_since(self, seq: int) -> dict:
        """Aggregate of traces recorded after `seq` (bench attaches this per
        scenario): solve count, modes served, total recompiles by fn, and the
        newest trace's per-phase split."""
        traces = [t for t in self.traces() if t.seq > seq]
        modes: dict[str, int] = {}
        recompiles: dict[str, int] = {}
        for t in traces:
            modes[t.mode or "none"] = modes.get(t.mode or "none", 0) + 1
            for fn, n in t.recompiles.items():
                recompiles[fn] = recompiles.get(fn, 0) + n
        out = {"n_solves": len(traces), "modes": modes, "recompiles": recompiles}
        if traces:
            last = traces[-1]
            out["last_phases"] = {k: round(v, 6) for k, v in last.phase_totals.items()}
            out["last_duration_s"] = round(last.duration, 6)
            # the tail shares (ISSUE 20): what fraction of the window's solve
            # wall the decode and exact-validate phases claim — the two
            # columns the decode-delta memo and the ranked-ladder validation
            # exist to shrink
            total = sum(t.duration for t in traces)
            if total > 0:
                for phase in ("decode", "validate"):
                    spent = sum(t.phase_totals.get(phase, 0.0) for t in traces)
                    out[f"{phase}_share"] = round(spent / total, 4)
        return out

    def dump(self, limit: int | None = None) -> dict:
        """The /debug/solves payload: ring content (oldest first), rolling
        stats, and recorder health. `limit` keeps only the newest `limit`
        solves — 0 (or negative) means none, None means all."""
        traces = self.traces()
        if limit is not None:
            traces = traces[-limit:] if limit > 0 else []
        with self._lock:  # dump runs on HTTP handler threads
            recorded, dropped = self.seq, self.dropped
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "recorded": recorded,
            "dropped": dropped,
            "stats": self.stats(),
            "solves": [t.to_dict() for t in traces],
        }


_DEFAULT = TraceRecorder()


def default_recorder() -> TraceRecorder:
    return _DEFAULT
