"""detcheck: the runtime arm of the determinism sanitizer.

The repo's north-star contract is BIT-IDENTICAL placement: two solves over
the same inputs must agree digest-for-digest — across the delta/full seam,
across shard workers, across replays of a recorded event log. The static
arm (`analysis/rules.py`: unordered-iteration-escape,
wallclock-and-rng-in-solve-path, float-reduction-order,
env-dependent-branch) proves what it can from source; this module enforces
the rest at runtime, the way a race detector backs up a lock comment:

- under ``KARPENTER_SOLVER_DETCHECK=1`` every `TPUSolver.solve` records a
  replayable dump of its input snapshot plus the node-name-free digest of
  its placement (`results_digest` — the cross-process cousin of
  `serving.shard.placement_digest`);
- `TPUSolver.check_determinism()` re-executes the recorded solve SEQUENCE
  in a child process under a PERTURBED ``PYTHONHASHSEED`` with every dict
  and set in the rebuilt inputs adversarially re-inserted in reversed order
  (`perturb`) — the same problem, a hostile iteration order — and compares
  the digest lists. Any divergence raises `DetCheckError` naming the solve
  and the parent/child modes;
- pod object IDENTITY is preserved across the replayed sequence (the delta
  encoder's two-pointer walk is an `is` walk), so the child genuinely
  exercises the warm delta / hybrid-delta carries, not a full re-solve per
  step;
- `check_globalpack` covers the consolidation proposer the same way
  in-process: one `global_repack_plan` over pristine inputs, one over
  perturbed inputs, digests compared.

With the env var off, `detcheck_enabled()` is one cached-bool read on the
solve path — bit-identical behavior, zero overhead (bench.py's
``detcheck_overhead`` gate pins this). Perturbation only touches orders the
contract declares meaningless: dict insertion order and set iteration
order. Lists and tuples are ORDERED inputs and replay verbatim.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import subprocess
import sys
import tempfile
from pathlib import Path

_ENABLED: bool | None = None

# stdout marker line the parent parses out of the child replay
_MARKER = "KARPENTER-DETCHECK-RESULT "

# recorded solves kept per solver; beyond this the OLDEST drop (the child's
# first replayed solve then runs cold, which the bit-identical delta/full
# contract makes digest-equivalent)
_LOG_MAX = 128

# child-side store rebuild order: owners before dependents so the informers
# observe Pod bindings against already-known Nodes/NodeClaims
_KIND_ORDER = {"NodePool": 0, "NodeClaim": 1, "Node": 2, "Pod": 3}


def detcheck_enabled() -> bool:
    """Cached read of KARPENTER_SOLVER_DETCHECK (call `_refresh()` after
    changing the env var mid-process, e.g. in tests)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("KARPENTER_SOLVER_DETCHECK", "").strip().lower() in ("1", "true", "on")
    return _ENABLED


def _refresh() -> None:
    global _ENABLED
    _ENABLED = None


class DetCheckError(AssertionError):
    """A determinism-contract violation: the dual run produced a different
    placement digest, or the sanitizer could not complete the replay."""


# -- adversarial input perturbation -------------------------------------------

_ATOMIC = (str, bytes, bytearray, int, float, bool, complex, type(None))


def perturb(obj, _memo: dict | None = None):
    """Rebuild `obj`'s object graph with every dict and set re-inserted in
    REVERSED iteration order — the same content under the most hostile
    insertion order the contract permits. Identity-preserving (shared
    references stay shared, via an id memo) and order-preserving for lists
    and tuples, which are meaningful sequences. Objects carrying a plain
    ``__dict__`` are perturbed in place (attribute dict rotated); anything
    else (arrays, locks, slotted objects) passes through untouched."""
    memo = _memo if _memo is not None else {}
    if isinstance(obj, _ATOMIC):
        return obj
    oid = id(obj)
    if oid in memo:
        return memo[oid]
    if isinstance(obj, dict):
        out: dict = {}
        memo[oid] = out
        for k in reversed(list(obj.keys())):
            out[perturb(k, memo)] = perturb(obj[k], memo)
        return out
    if isinstance(obj, (set, frozenset)):
        items = [perturb(v, memo) for v in reversed(list(obj))]
        out = frozenset(items) if isinstance(obj, frozenset) else set(items)
        memo[oid] = out
        return out
    if isinstance(obj, list):
        out = []
        memo[oid] = out
        out.extend(perturb(v, memo) for v in obj)
        return out
    if isinstance(obj, tuple):
        out = tuple(perturb(v, memo) for v in obj)
        memo[oid] = out
        return out
    d = getattr(obj, "__dict__", None)
    if type(d) is dict:
        # in place: the object keeps its identity; its attribute dict is
        # re-inserted reversed, and every attribute value recurses
        memo[oid] = obj
        for k in reversed(list(d.keys())):
            v = d.pop(k)
            d[k] = perturb(v, memo)
        return obj
    memo[oid] = obj
    return obj


# -- digests ------------------------------------------------------------------


def results_digest(results) -> str:
    """Node-name-free content digest of a solve's placement structure:
    new claims as (nodepool, sorted instance-type options, sorted pod keys),
    existing-node assignments as (node name, sorted pod keys), and the pod
    errors. Random claim-name suffixes never enter, so two replays of the
    same inputs digest identically iff their placements match — comparable
    ACROSS processes (same construction as serving.shard.placement_digest,
    over a Results instead of a store)."""
    claims = sorted(
        [
            nc.nodepool_name,
            sorted(it.name for it in nc.instance_type_options),
            sorted(p.key() for p in nc.pods),
        ]
        for nc in results.new_node_claims
    )
    existing = sorted([n.name(), sorted(p.key() for p in n.pods)] for n in results.existing_nodes if n.pods)
    errors = sorted([k, str(v)] for k, v in results.pod_errors.items())
    payload = {"claims": claims, "existing": existing, "errors": errors, "timed_out": bool(results.timed_out)}
    return hashlib.sha256(json.dumps(payload, sort_keys=True).encode()).hexdigest()


def plan_digest(subsets) -> str:
    """Digest of a global-repack proposal list (`global_repack_plan`'s
    subsets, best-first): candidate-index lists in rank order."""
    return hashlib.sha256(json.dumps([list(map(int, s)) for s in subsets]).encode()).hexdigest()


# -- snapshot dump / rebuild --------------------------------------------------


def dump_snapshot(snap, token_of) -> bytes:
    """Serialize everything a child process needs to re-run this solve from
    scratch. `token_of(obj)` maps pods AND instance types to stable
    identity tokens (see `_SolveLog`): the delta encoder's two-pointer walk
    compares pod IDENTITY, and the row cache key carries `id(instance_type)`
    and the cluster's epoch — so the replay must be told which objects of
    consecutive snapshots were the same parent-side. Tokened objects are
    pickled individually; the child reuses its previous unpickle for a
    token only while the bytes still match, mirroring in-place mutation
    parent-side. The store content is dumped as one inner blob so the child
    can recognize an unchanged cluster and keep ONE Store/Cluster stack
    (stable epoch) across the replayed sequence."""
    with snap.store._lock:
        kinds = sorted(snap.store._objects.keys())
    payload = {
        # store.list deep-copies on the way out: this is a point-in-time dump
        "store_blob": pickle.dumps({k: snap.store.list(k) for k in kinds}),
        "clock": float(snap.clock.now()),
        "pods": [(token_of(p), pickle.dumps(p)) for p in snap.pods],
        "node_pools": snap.node_pools,
        "instance_types": {
            name: [(token_of(it), pickle.dumps(it)) for it in its]
            for name, its in snap.instance_types.items()
        },
        "state_node_names": [sn.name() for sn in snap.state_nodes],
        "daemonset_pods": snap.daemonset_pods,
        "deleting_node_names": sorted(snap.deleting_node_names),
        "flags": {
            "preference_policy": snap.preference_policy,
            "min_values_policy": snap.min_values_policy,
            "enforce_consolidate_after": snap.enforce_consolidate_after,
            "dra_enabled": snap.dra_enabled,
            "reserved_capacity_enabled": snap.reserved_capacity_enabled,
            "reserved_offering_mode": snap.reserved_offering_mode,
            "collect_zone_metrics": snap.collect_zone_metrics,
        },
    }
    return pickle.dumps(payload)


def _linked(token: int, blob: bytes, seen: dict):
    """Token-stable unpickle: the first sighting of a token unpickles (and
    perturbs) fresh; later sightings keep that object's IDENTITY. When the
    bytes changed, the parent mutated the same object in place between
    solves — mirror that by overwriting the retained object's ``__dict__``
    from the fresh unpickle instead of swapping objects."""
    prev = seen.get(token)
    if prev is None:
        obj = perturb(pickle.loads(blob))
        seen[token] = [blob, obj]
        return obj
    if prev[0] != blob:
        fresh = perturb(pickle.loads(blob))
        d = getattr(prev[1], "__dict__", None)
        if type(fresh) is type(prev[1]) and type(d) is dict and type(getattr(fresh, "__dict__", None)) is dict:
            d.clear()
            d.update(fresh.__dict__)
        else:  # slotted or retyped: identity cannot be kept, content wins
            prev[1] = fresh
        prev[0] = blob
    return prev[1]


def load_snapshot(blob: bytes, seen: dict, ctx: dict):
    """Child-side rebuild: a Store/Cluster/informer stack replayed from the
    dump, every rebuilt input perturbed (`perturb`) on the way in. `seen`
    carries token -> (bytes, object) for pods and instance types across the
    replayed sequence, and `ctx` carries the previous solve's rebuilt
    store/cluster — reused while the store content blob is unchanged, so
    the row cache key's cluster epoch stays stable and the warm delta /
    hybrid-delta carries genuinely replay."""
    from ..kube.store import Store
    from ..solver.snapshot import SolverSnapshot
    from ..state.cluster import Cluster
    from ..state.informer import start_informers
    from ..utils.clock import FakeClock

    data = pickle.loads(blob)
    if ctx.get("store_blob") == data["store_blob"]:
        store, cluster, clock = ctx["store"], ctx["cluster"], ctx["clock"]
        drift = data["clock"] - clock.now()
        if drift:
            clock.step(drift)
    else:
        store = Store()
        clock = FakeClock(start=data["clock"])
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        content = pickle.loads(data["store_blob"])
        for kind in sorted(content, key=lambda k: (_KIND_ORDER.get(k, 99), k)):
            for obj in perturb(content[kind]):
                store.create(obj, adopt=True)
        ctx.update(store_blob=data["store_blob"], store=store, cluster=cluster, clock=clock)
    pods = [_linked(token, pod_blob, seen) for token, pod_blob in data["pods"]]
    instance_types = {
        name: [_linked(token, it_blob, seen) for token, it_blob in entries]
        for name, entries in data["instance_types"].items()
    }
    # the SNAPSHOT's node selection in its recorded order (disruption sims
    # filter candidates out of state_nodes without touching the cluster)
    by_name = {sn.name(): sn for sn in cluster.nodes()}
    state_nodes = [by_name[n] for n in data["state_node_names"] if n in by_name]
    return SolverSnapshot(
        store=store,
        cluster=cluster,
        node_pools=perturb(data["node_pools"]),
        instance_types=instance_types,
        state_nodes=state_nodes,
        daemonset_pods=perturb(data["daemonset_pods"]),
        pods=pods,
        clock=clock,
        deleting_node_names=perturb(set(data["deleting_node_names"])),
        **data["flags"],
    )


# -- parent-side recording ----------------------------------------------------


class _SolveLog:
    """Per-solver recording state, attached lazily by `record_solve`. Pins a
    reference to every tokened pod so CPython can never reuse an id while
    the log is live (the token IS the identity record)."""

    def __init__(self):
        self.entries: list[dict] = []
        self.dropped = 0
        self._tokens: dict[int, int] = {}
        self._pins: list = []

    def token_of(self, pod) -> int:
        tok = self._tokens.get(id(pod))
        if tok is None:
            tok = len(self._pins)
            self._tokens[id(pod)] = tok
            self._pins.append(pod)
        return tok

    def append(self, entry: dict) -> None:
        self.entries.append(entry)
        if len(self.entries) > _LOG_MAX:
            del self.entries[0]
            self.dropped += 1


def solve_log(solver) -> _SolveLog:
    log = getattr(solver, "_detcheck_log", None)
    if log is None:
        log = solver._detcheck_log = _SolveLog()
    return log


def record_solve(solver, blob: bytes, results) -> None:
    """Append one recorded solve (input dump + placement digest + mode)."""
    solve_log(solver).append(
        {"payload": blob, "digest": results_digest(results), "mode": solver.last_solve_mode}
    )


def _perturbed_hash_seed() -> str:
    """A hash seed guaranteed to differ from this process's: PYTHONHASHSEED
    unset/random means any fixed seed differs with overwhelming odds; a
    pinned parent seed gets seed+1."""
    cur = os.environ.get("PYTHONHASHSEED", "")
    if cur.isdigit():
        return str((int(cur) + 1) % 4294967295 or 1)
    return "4242"


def run_dual(solver, timeout: float = 600.0, clear: bool = True) -> dict:
    """The dual-run check: replay this solver's recorded solve sequence in a
    subprocess under a perturbed hash seed + adversarially reordered inputs
    and compare placement digests. Raises `DetCheckError` on any divergence;
    returns a summary dict on success (and clears the log by default so
    repeated checks don't re-verify old solves)."""
    if not detcheck_enabled():
        raise DetCheckError("KARPENTER_SOLVER_DETCHECK is not enabled — no solves were recorded")
    log = getattr(solver, "_detcheck_log", None)
    if log is None or not log.entries:
        raise DetCheckError("no recorded solves to check — run solve() with KARPENTER_SOLVER_DETCHECK=1 first")
    job = {
        "solver": {"hybrid": solver.hybrid, "force": solver.force, "recover": solver.recover},
        "solves": [e["payload"] for e in log.entries],
    }
    env = dict(os.environ)
    # the child computes digests directly — recording there would only
    # recurse on a nested check_determinism
    env.pop("KARPENTER_SOLVER_DETCHECK", None)
    env["PYTHONHASHSEED"] = _perturbed_hash_seed()
    root = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = root + os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else root
    fd, jobfile = tempfile.mkstemp(prefix="detcheck-", suffix=".job")
    try:
        with os.fdopen(fd, "wb") as fh:
            pickle.dump(job, fh)
        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.obs.detcheck", jobfile],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    finally:
        try:
            os.unlink(jobfile)
        except OSError:
            pass
    marker = next((ln for ln in proc.stdout.splitlines() if ln.startswith(_MARKER)), None)
    if proc.returncode != 0 or marker is None:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-12:]
        raise DetCheckError(
            "detcheck replay child failed (exit %s) under PYTHONHASHSEED=%s:\n%s"
            % (proc.returncode, env["PYTHONHASHSEED"], "\n".join(tail))
        )
    child = json.loads(marker[len(_MARKER):])
    parent_digests = [e["digest"] for e in log.entries]
    parent_modes = [e["mode"] for e in log.entries]
    if len(child["digests"]) != len(parent_digests):
        raise DetCheckError(
            f"replay produced {len(child['digests'])} digests for {len(parent_digests)} recorded solves"
        )
    bad = [i for i, (a, b) in enumerate(zip(parent_digests, child["digests"])) if a != b]
    if bad:
        detail = "; ".join(
            f"solve #{i} (parent mode={parent_modes[i]!r}, child mode={child['modes'][i]!r}): "
            f"{parent_digests[i][:12]} != {child['digests'][i][:12]}"
            for i in bad
        )
        raise DetCheckError(
            f"placement digest diverged under perturbed hash seed {env['PYTHONHASHSEED']} "
            f"+ reversed insertion order — the bit-identical-placement contract is broken: {detail}"
        )
    out = {
        "solves": len(parent_digests),
        "digests": parent_digests,
        "parent_modes": parent_modes,
        "child_modes": child["modes"],
        "hash_seed": env["PYTHONHASHSEED"],
        "dropped": log.dropped,
    }
    if clear:
        log.entries.clear()
        log.dropped = 0
    return out


def check_globalpack(solver, candidates, instance_types, pending_pods=None, seed: int = 0) -> dict:
    """In-process dual run of the global-repack proposer: the same plan must
    come back digest-identical when every dict/set in its inputs is
    re-inserted in reversed order. Candidates are live state objects (not
    picklable), so this arm perturbs in place instead of forking."""
    first, _ = solver.global_repack_plan(candidates, instance_types, pending_pods=pending_pods, seed=seed)
    memo: dict = {}
    second, _ = solver.global_repack_plan(
        perturb(candidates, memo),
        perturb(instance_types, memo),
        pending_pods=perturb(pending_pods, memo),
        seed=seed,
    )
    a, b = plan_digest(first), plan_digest(second)
    if a != b:
        raise DetCheckError(
            f"global repack plan diverged under reversed insertion order: {a[:12]} != {b[:12]}"
        )
    return {"proposals": len(first), "digest": a}


# -- the child replay entry point ---------------------------------------------


def _child_main(argv: list[str]) -> int:
    from ..solver.tpu import TPUSolver

    with open(argv[0], "rb") as fh:
        job = pickle.load(fh)
    solver = TPUSolver(**job["solver"])
    seen: dict = {}
    ctx: dict = {}
    digests, modes = [], []
    for blob in job["solves"]:
        snap = load_snapshot(blob, seen, ctx)
        results = solver.solve(snap)
        digests.append(results_digest(results))
        modes.append(solver.last_solve_mode)
    print(_MARKER + json.dumps({"digests": digests, "modes": modes}))
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
