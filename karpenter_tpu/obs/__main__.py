"""Offline solvetrace/podtrace exporter CLI.

    python -m karpenter_tpu.obs dump.jsonl --out solves.trace.json
    curl :8080/debug/solves | python -m karpenter_tpu.obs - --out solves.trace.json
    curl :8080/debug/events | python -m karpenter_tpu.obs - --events --out events.trace.json
    python -m karpenter_tpu.obs dump.jsonl --format jsonl   # normalize a dump

Input is either JSONL (one SolveTrace dict per line — the bench/exporter
format) or a whole `/debug/solves` dump; with `--events`, a podtrace
`/debug/events` dump or EventRecord JSONL instead. Output is Chrome/
Perfetto trace_event JSON (default) ready for chrome://tracing or
ui.perfetto.dev — event mode renders the watch-delivery / serve-loop /
prestage-worker tracks with cross-thread flow arrows — or normalized
JSONL."""

from __future__ import annotations

import argparse
import json
import sys

from .export import (
    events_to_jsonl,
    events_to_trace_events,
    parse_dump,
    parse_event_dump,
    to_jsonl,
    to_trace_events,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m karpenter_tpu.obs", description=__doc__)
    parser.add_argument("input", help="trace dump: a JSONL file, a /debug/solves JSON file, or '-' for stdin")
    parser.add_argument("--out", default="-", help="output path ('-' = stdout)")
    parser.add_argument("--format", choices=("perfetto", "jsonl"), default="perfetto")
    parser.add_argument(
        "--events",
        action="store_true",
        help="input is a podtrace dump (/debug/events payload or EventRecord JSONL): "
        "render the event-lifecycle tracks with cross-thread flow arrows instead of solve traces",
    )
    args = parser.parse_args(argv)

    if args.input == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(args.input) as f:
                text = f.read()
        except OSError as e:
            print(f"obs: cannot read {args.input}: {e}", file=sys.stderr)
            return 2
    try:
        traces = parse_event_dump(text) if args.events else parse_dump(text)
    except json.JSONDecodeError as e:
        print(f"obs: input is neither JSONL nor a debug dump: {e}", file=sys.stderr)
        return 2
    if not traces:
        print("obs: no traces in input", file=sys.stderr)
        return 1

    if args.events:
        body = events_to_jsonl(traces) if args.format == "jsonl" else json.dumps(events_to_trace_events(traces))
    else:
        body = to_jsonl(traces) if args.format == "jsonl" else json.dumps(to_trace_events(traces))
    if args.out == "-":
        print(body)
    else:
        with open(args.out, "w") as f:
            f.write(body + "\n")
        print(f"obs: wrote {len(traces)} solve(s) to {args.out} ({args.format})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
