"""SolveTrace exporters: one-line JSON per trace, and Chrome/Perfetto
`trace_event` JSON for flamegraph-style inspection of a bench run
(chrome://tracing / https://ui.perfetto.dev open the output directly).

Both operate on trace DICTS (`SolveTrace.to_dict()` shape), so they can
consume live recorder content, a `/debug/solves` dump, or a JSONL file a
previous process wrote — the `python -m karpenter_tpu.obs` CLI does the
latter."""

from __future__ import annotations

import json


def _as_dict(trace) -> dict:
    return trace if isinstance(trace, dict) else trace.to_dict()


def to_jsonl(traces) -> str:
    """One compact JSON object per line, one line per solve."""
    return "\n".join(json.dumps(_as_dict(t), sort_keys=True) for t in traces)


def _span_events(span: dict, wall_us: float, pid: int, tid: int, out: list) -> None:
    out.append(
        {
            "name": span["name"],
            "ph": "X",  # complete event: one entry carries start + duration
            "ts": wall_us + span.get("start_s", 0.0) * 1e6,
            "dur": max(span.get("dur_s", 0.0) * 1e6, 0.01),
            "pid": pid,
            "tid": tid,
            "cat": "solve",
            "args": span.get("attrs", {}),
        }
    )
    for child in span.get("children", ()):
        _span_events(child, wall_us, pid, tid, out)


def to_trace_events(traces) -> dict:
    """Chrome trace_event JSON: each solve is one top-level "solve" slice on
    the timeline (tid = solve mode, so modes read as separate tracks), its
    phase spans nested inside; recompiles surface as instant events."""
    events: list = []
    tids: dict[str, int] = {}
    meta: list = []
    for t in traces:
        d = _as_dict(t)
        mode = d.get("mode") or "none"
        tid = tids.get(mode)
        if tid is None:
            tid = tids[mode] = len(tids) + 1
            meta.append(
                {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "args": {"name": f"mode:{mode}"}}
            )
        wall_us = d.get("wall_time", 0.0) * 1e6
        events.append(
            {
                "name": f"solve#{d.get('seq', 0)}",
                "ph": "X",
                "ts": wall_us,
                "dur": max(d.get("duration_s", 0.0) * 1e6, 0.01),
                "pid": 1,
                "tid": tid,
                "cat": "solve",
                "args": {
                    "backend": d.get("backend", ""),
                    "n_pods": d.get("n_pods", 0),
                    "cache": d.get("cache", {}),
                    "fallback_families": d.get("fallback_families", []),
                },
            }
        )
        for span in d.get("spans", ()):
            _span_events(span, wall_us, 1, tid, events)
        for fn, n in sorted(d.get("recompiles", {}).items()):
            events.append(
                {
                    "name": f"recompile:{fn}",
                    "ph": "i",
                    "s": "t",
                    "ts": wall_us,
                    "pid": 1,
                    "tid": tid,
                    "cat": "recompile",
                    "args": {"count": n},
                }
            )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def parse_dump(text: str) -> list[dict]:
    """Accept either a /debug/solves dump (object with "solves") or JSONL
    (one trace object per line) and return the trace dicts."""
    text = text.strip()
    if not text:
        return []
    try:  # a single JSON document: a /debug/solves dump, a list, or one trace
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        return list(obj["solves"]) if "solves" in obj else [obj]
    if isinstance(obj, list):
        return obj
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        if isinstance(obj, dict) and "solves" in obj:
            out.extend(obj["solves"])
        else:
            out.append(obj)
    return out
