"""SolveTrace exporters: one-line JSON per trace, and Chrome/Perfetto
`trace_event` JSON for flamegraph-style inspection of a bench run
(chrome://tracing / https://ui.perfetto.dev open the output directly).

Both operate on trace DICTS (`SolveTrace.to_dict()` shape), so they can
consume live recorder content, a `/debug/solves` dump, or a JSONL file a
previous process wrote — the `python -m karpenter_tpu.obs` CLI does the
latter."""

from __future__ import annotations

import json


def _as_dict(trace) -> dict:
    return trace if isinstance(trace, dict) else trace.to_dict()


def to_jsonl(traces) -> str:
    """One compact JSON object per line, one line per solve."""
    return "\n".join(json.dumps(_as_dict(t), sort_keys=True) for t in traces)


def _span_events(span: dict, wall_us: float, pid: int, tid: int, out: list) -> None:
    out.append(
        {
            "name": span["name"],
            "ph": "X",  # complete event: one entry carries start + duration
            "ts": wall_us + span.get("start_s", 0.0) * 1e6,
            "dur": max(span.get("dur_s", 0.0) * 1e6, 0.01),
            "pid": pid,
            "tid": tid,
            "cat": "solve",
            "args": span.get("attrs", {}),
        }
    )
    for child in span.get("children", ()):
        _span_events(child, wall_us, pid, tid, out)


def to_trace_events(traces) -> dict:
    """Chrome trace_event JSON: each solve is one top-level "solve" slice on
    the timeline (tid = solve mode, so modes read as separate tracks), its
    phase spans nested inside; recompiles surface as instant events."""
    events: list = []
    tids: dict[str, int] = {}
    meta: list = []
    for t in traces:
        d = _as_dict(t)
        mode = d.get("mode") or "none"
        tid = tids.get(mode)
        if tid is None:
            tid = tids[mode] = len(tids) + 1
            meta.append(
                {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "args": {"name": f"mode:{mode}"}}
            )
        wall_us = d.get("wall_time", 0.0) * 1e6
        events.append(
            {
                "name": f"solve#{d.get('seq', 0)}",
                "ph": "X",
                "ts": wall_us,
                "dur": max(d.get("duration_s", 0.0) * 1e6, 0.01),
                "pid": 1,
                "tid": tid,
                "cat": "solve",
                "args": {
                    "backend": d.get("backend", ""),
                    "n_pods": d.get("n_pods", 0),
                    "cache": d.get("cache", {}),
                    "fallback_families": d.get("fallback_families", []),
                },
            }
        )
        for span in d.get("spans", ()):
            _span_events(span, wall_us, 1, tid, events)
        for fn, n in sorted(d.get("recompiles", {}).items()):
            events.append(
                {
                    "name": f"recompile:{fn}",
                    "ph": "i",
                    "s": "t",
                    "ts": wall_us,
                    "pid": 1,
                    "tid": tid,
                    "cat": "recompile",
                    "args": {"count": n},
                }
            )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# podtrace export: the event-lifecycle tracks. Thread ids are a static
# enum — watch delivery, the serve/fleet loop (where dispatch+solve run),
# and the prestage worker — so one event's journey renders as slices on
# THREE tracks joined by flow arrows (ph s/t/f sharing the event's flow id).
EVENT_TRACKS = (("watch-delivery", 1), ("serve-loop", 2), ("prestage-worker", 3))


def _event_dict(rec) -> dict:
    return rec if isinstance(rec, dict) else rec.to_dict()


def events_to_trace_events(events) -> dict:
    """Chrome/Perfetto trace_event JSON for podtrace EventRecords: per event
    a `coalesce` slice on the watch-delivery track, a `solve` (+`decode`
    tail) slice on the serve-loop track, and a `prestage` slice on the
    worker track when the double buffer staged it — with flow arrows
    carrying the event across threads (the cross-thread stamps ARE the
    product: arrival on a watch thread, dispatch on the fleet loop, staging
    on the worker)."""
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "args": {"name": name}}
        for name, tid in EVENT_TRACKS
    ]
    tids = dict(EVENT_TRACKS)
    out: list = []
    for i, rec in enumerate(events):
        d = _event_dict(rec)
        stages = d.get("stages", {})
        wall_us = d.get("wall_arrival", 0.0) * 1e6
        flow_id = i + 1
        label = d.get("name") or d.get("uid", "?")
        args = {
            "uid": d.get("uid", ""),
            "tenant": d.get("tenant", ""),
            "outcome": d.get("outcome", ""),
            "wake_cause": d.get("wake_cause", ""),
            "solve_seq": d.get("solve_seq", 0),
            "staged": d.get("staged", False),
        }
        coalesce_us = max((stages.get("coalesce", 0.0) + stages.get("sched_wait", 0.0)) * 1e6, 0.01)
        out.append(
            {
                "name": f"coalesce:{label}", "ph": "X", "ts": wall_us, "dur": coalesce_us,
                "pid": 1, "tid": tids["watch-delivery"], "cat": "event", "args": args,
            }
        )
        # flow start at the end of the coalescing window (the dispatch)...
        out.append(
            {"name": "event-flow", "ph": "s", "id": flow_id, "ts": wall_us + coalesce_us,
             "pid": 1, "tid": tids["watch-delivery"], "cat": "event"}
        )
        if d.get("staged"):
            out.append(
                {
                    "name": f"prestage:{label}", "ph": "X", "ts": wall_us,
                    "dur": max(stages.get("prestage", 0.0) * 1e6, 0.01),
                    "pid": 1, "tid": tids["prestage-worker"], "cat": "event", "args": args,
                }
            )
            out.append(
                {"name": "event-flow", "ph": "t", "id": flow_id,
                 "ts": wall_us + max(stages.get("prestage", 0.0) * 1e6, 0.01),
                 "pid": 1, "tid": tids["prestage-worker"], "cat": "event"}
            )
        # ... landing on the solve slice on the serve-loop track
        solve_ts = wall_us + coalesce_us
        out.append(
            {
                "name": f"solve:{label}", "ph": "X", "ts": solve_ts,
                "dur": max(stages.get("solve", 0.0) * 1e6, 0.01),
                "pid": 1, "tid": tids["serve-loop"], "cat": "event", "args": args,
            }
        )
        out.append(
            {"name": "event-flow", "ph": "f", "bp": "e", "id": flow_id, "ts": solve_ts,
             "pid": 1, "tid": tids["serve-loop"], "cat": "event"}
        )
        if stages.get("decode", 0.0) > 0.0:
            out.append(
                {
                    "name": f"decode:{label}", "ph": "X",
                    "ts": solve_ts + max(stages.get("solve", 0.0) * 1e6, 0.01),
                    "dur": stages["decode"] * 1e6,
                    "pid": 1, "tid": tids["serve-loop"], "cat": "event", "args": args,
                }
            )
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def events_to_jsonl(events) -> str:
    """One compact JSON object per line, one line per completed event."""
    return "\n".join(json.dumps(_event_dict(e), sort_keys=True) for e in events)


def parse_event_dump(text: str) -> list[dict]:
    """Accept a /debug/events dump (object with "tenants"), a single
    tracer dump (object with "events"), or JSONL of EventRecord dicts."""
    text = text.strip()
    if not text:
        return []
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if "tenants" in obj:
            out: list[dict] = []
            for dump in obj["tenants"].values():
                out.extend(dump.get("events", ()))
            return out
        if "events" in obj:
            return list(obj["events"])
        return [obj]
    if isinstance(obj, list):
        return obj
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def parse_dump(text: str) -> list[dict]:
    """Accept either a /debug/solves dump (object with "solves") or JSONL
    (one trace object per line) and return the trace dicts."""
    text = text.strip()
    if not text:
        return []
    try:  # a single JSON document: a /debug/solves dump, a list, or one trace
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        return list(obj["solves"]) if "solves" in obj else [obj]
    if isinstance(obj, list):
        return obj
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        if isinstance(obj, dict) and "solves" in obj:
            out.extend(obj["solves"])
        else:
            out.append(obj)
    return out
