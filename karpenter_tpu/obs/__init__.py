"""karpenter_tpu.obs — the solvetrace flight recorder.

`trace` holds the span API, SolveTrace, the JIT-recompile sentinel, and the
bounded TraceRecorder ring with rolling P50/P90/P99; `export` renders traces
as JSONL or Chrome/Perfetto trace_event JSON (`python -m karpenter_tpu.obs`);
`stats` is the repo's one nearest-rank quantile implementation, shared with
`testing/metrics_poller`. Importing this package never initializes jax."""

from .stats import RollingQuantiles, quantile
from .trace import (
    JIT_WATCHLIST,
    RecompileSentinel,
    SolveTrace,
    Span,
    TraceRecorder,
    current_trace,
    default_recorder,
    sentinel,
)

__all__ = [
    "JIT_WATCHLIST",
    "RecompileSentinel",
    "RollingQuantiles",
    "SolveTrace",
    "Span",
    "TraceRecorder",
    "current_trace",
    "default_recorder",
    "quantile",
    "sentinel",
]
