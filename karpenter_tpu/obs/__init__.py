"""karpenter_tpu.obs — the solvetrace + podtrace flight recorders.

`trace` holds the span API, SolveTrace, the JIT-recompile sentinel, and the
bounded TraceRecorder ring with rolling P50/P90/P99; `podtrace` is the
event-lifecycle recorder (watch-event arrival through coalesce / DRR wait /
prestage / solve / bind, with per-stage quantiles and the SLO budget);
`export` renders both as JSONL or Chrome/Perfetto trace_event JSON
(`python -m karpenter_tpu.obs`, `--events` for the podtrace tracks);
`stats` is the repo's one nearest-rank quantile implementation, shared with
`testing/metrics_poller`. Importing this package never initializes jax."""

from .podtrace import WAKE_CAUSES, EventRecord, PodTracer, SLOBudget
from .podtrace import STAGES as EVENT_STAGES
from .stats import RollingQuantiles, quantile
from .trace import (
    JIT_WATCHLIST,
    RecompileSentinel,
    SolveTrace,
    Span,
    TraceRecorder,
    current_trace,
    default_recorder,
    sentinel,
)

__all__ = [
    "EVENT_STAGES",
    "EventRecord",
    "JIT_WATCHLIST",
    "PodTracer",
    "RecompileSentinel",
    "RollingQuantiles",
    "SLOBudget",
    "SolveTrace",
    "Span",
    "TraceRecorder",
    "WAKE_CAUSES",
    "current_trace",
    "default_recorder",
    "quantile",
    "sentinel",
]
