"""racecheck: the runtime arm of the concurrency sanitizer.

The serving stack is a handful of long-lived threads (prestager worker,
churn driver, store watch delivery, operator HTTP server, leader-election
renewer) coordinating through a small set of named locks. The static arm
(`analysis/rules.py`: guarded-field-access, lock-order, thread-escape,
bare-thread-primitive) proves what it can from source; this module enforces
the rest at runtime, the way Go's race detector backs up "fields guarded by
mu" comments:

- every lock in the stack is constructed through `make_lock`/`make_rlock`
  (the bare-thread-primitive rule pins that), so under
  ``KARPENTER_SOLVER_RACECHECK=1`` every acquisition is observed;
- the DYNAMIC lock-order graph is recorded per acquisition edge (lock A held
  while acquiring lock B); an edge that closes a cycle raises
  `RaceCheckError` at the acquisition site — a potential deadlock caught the
  first time the inverted order executes, not the first time it interleaves;
- guarded-field touch points call `touch(obj, field)`: a cheap owner-thread
  check that the field's declared lock (the class's ``GUARDED_FIELDS``
  registry, which the static rule also reads) is held by the current thread;
- lock WAIT time feeds the ``karpenter_solver_lock_wait_seconds{lock}``
  histogram (contention observability), and HOLD times above
  ``KARPENTER_RACECHECK_HOLD_OUTLIER`` seconds are recorded as outliers —
  a lock held across a solve or a device sync shows up here even when no
  inversion ever fires.

With the env var off, `make_lock`/`make_rlock` return the plain
`threading.Lock`/`RLock` objects — bit-identical behavior, zero overhead
(tests pin this parity). Lock NAMES are a small static enum (one name per
lock class, like Go lock ranking): same-name locks on different instances
share a graph node, which is exactly what makes cross-instance order
violations visible.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

# this module IS the sanctioned wrapper the bare-thread-primitive rule
# points at; it necessarily constructs raw primitives itself
_LOCK_CLS = type(threading.Lock())

_ENABLED: bool | None = None


def racecheck_enabled() -> bool:
    """Cached read of KARPENTER_SOLVER_RACECHECK (call `_refresh()` after
    changing the env var mid-process, e.g. in tests)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("KARPENTER_SOLVER_RACECHECK", "").strip().lower() in ("1", "true", "on")
    return _ENABLED


def _refresh() -> None:
    global _ENABLED
    _ENABLED = None


class RaceCheckError(AssertionError):
    """A concurrency-discipline violation: lock-order inversion, a guarded
    field touched without its lock, or a non-reentrant relock."""


# per-thread state: the stack of InstrumentedLocks currently held, plus a
# reentrancy guard so metric emission from inside the instrumentation never
# re-enters the bookkeeping
_tls = threading.local()


class _Global:
    """Process-wide sanitizer state. Guarded by its own PLAIN lock — the one
    lock in the stack that is deliberately uninstrumented (it nests inside
    every instrumented acquisition and never calls out)."""

    def __init__(self):
        self.lock = threading.Lock()
        # (a, b): lock named `a` was held while acquiring `b`; value = first
        # observation "thread-name file-agnostic description"
        self.edges: dict[tuple[str, str], str] = {}
        self.adj: dict[str, set[str]] = {}
        self.violations: list[str] = []
        self.wait: dict[str, list[float]] = {}  # name -> [count, total, max]
        self.hold_outliers: list[tuple[str, float, str]] = []
        self.touch_checks = 0
        self.registry_ref = None  # weakref: see set_metrics_registry

    def clear(self) -> None:
        self.edges.clear()
        self.adj.clear()
        self.violations.clear()
        self.wait.clear()
        self.hold_outliers.clear()
        self.touch_checks = 0


_G = _Global()

_HOLD_OUTLIER_SECONDS = float(os.environ.get("KARPENTER_RACECHECK_HOLD_OUTLIER", "0.25"))
_MAX_OUTLIERS = 256


def set_metrics_registry(registry) -> None:
    """Install the registry the wait-time histogram is emitted to (the
    operator Environment does this when racecheck is enabled).

    Process-global, last-writer-wins — a production process runs ONE
    Environment; in a multi-env test process the newest install receives
    the emissions. Held by WEAK reference so a torn-down Environment's
    registry is released (emissions just stop) instead of being pinned
    alive by the sanitizer forever."""
    _G.registry_ref = weakref.ref(registry) if registry is not None else None


def reset() -> None:
    """Drop the recorded graph/stats (test isolation). Held-lock state is
    per-thread and survives — only call between quiesced phases."""
    with _G.lock:
        _G.clear()


def snapshot() -> dict:
    """A copy of the sanitizer's observations for tests and debugging."""
    with _G.lock:
        return {
            "edges": dict(_G.edges),
            "violations": list(_G.violations),
            "wait": {k: tuple(v) for k, v in _G.wait.items()},
            "hold_outliers": list(_G.hold_outliers),
            "touch_checks": _G.touch_checks,
        }


def _held_stack() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _reaches(adj: dict[str, set[str]], src: str, dst: str) -> bool:
    """DFS reachability over the tiny (≤ #lock names) order graph."""
    stack, seen = [src], {src}
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for nxt in adj.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _record_edges(held: list, name: str) -> None:
    """Record (held -> name) for every currently-held lock; raise on any edge
    that closes a cycle (the full cycle check, not just pairwise inversion —
    a→b, b→c, c→a never shows a directly reversed edge)."""
    if not held:
        return
    me = threading.current_thread().name
    with _G.lock:
        for h in held:
            a = h.name
            if a == name or (a, name) in _G.edges:
                continue
            if _reaches(_G.adj, name, a):
                first = _G.edges.get((name, a)) or next(
                    (w for (x, _y), w in _G.edges.items() if x == name), "?"
                )
                msg = (
                    f"lock-order inversion: thread {me!r} acquires {name!r} while holding {a!r}, "
                    f"but the order {name!r} -> ... -> {a!r} was already observed ({first})"
                )
                _G.violations.append(msg)
                raise RaceCheckError(msg)
            _G.edges[(a, name)] = f"thread {me}"
            _G.adj.setdefault(a, set()).add(name)


def _record_wait(name: str, seconds: float) -> None:
    with _G.lock:
        stats = _G.wait.setdefault(name, [0.0, 0.0, 0.0])
        stats[0] += 1
        stats[1] += seconds
        if seconds > stats[2]:
            stats[2] = seconds
    registry = _G.registry_ref() if _G.registry_ref is not None else None
    if registry is not None and not getattr(_tls, "busy", False):
        _tls.busy = True  # metric locks are instrumented too: don't recurse
        try:
            from ..metrics import SOLVER_LOCK_WAIT_BUCKETS, SOLVER_LOCK_WAIT_SECONDS

            registry.histogram(
                SOLVER_LOCK_WAIT_SECONDS,
                "Time spent waiting to acquire a named serving-stack lock (racecheck wrapper)",
                ("lock",),
                SOLVER_LOCK_WAIT_BUCKETS,
            ).observe(seconds, lock=name)  # solverlint: ok(metric-label-cardinality): lock names are the static make_lock call-site literals — an enum the bare-thread-primitive rule keeps closed
        except Exception as e:  # noqa: BLE001  # solverlint: ok(swallowed-exception): recorded into _G.violations below — surfaced as a sanitizer violation, never a leaked lock
            # an emission failure mid-acquire would otherwise propagate out
            # of acquire() with the lock held but `with` never entered —
            # surface it as a violation instead of a leaked lock
            with _G.lock:
                _G.violations.append(f"lock-wait metric emission failed for {name!r}: {e!r}")
        finally:
            _tls.busy = False


class InstrumentedLock:
    """Drop-in for threading.Lock/RLock recording order edges, wait time,
    hold-time outliers, and the owner thread (for `touch` / `held_by_me`)."""

    __slots__ = ("name", "reentrant", "_lock", "_owner", "_count", "_acquired_at")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._owner: int | None = None
        self._count = 0
        self._acquired_at = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            if not self.reentrant:
                # a plain Lock would deadlock silently here; fail loudly
                raise RaceCheckError(f"non-reentrant lock {self.name!r} re-acquired by its owner thread")
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._count += 1
            return ok
        if getattr(_tls, "busy", False):  # inside our own metric emission
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._owner, self._count, self._acquired_at = me, 1, time.perf_counter()
            return ok
        held = _held_stack()
        _record_edges(held, self.name)
        t0 = time.perf_counter()
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            return False
        now = time.perf_counter()
        self._owner, self._count, self._acquired_at = me, 1, now
        held.append(self)
        _record_wait(self.name, now - t0)
        return True

    def release(self) -> None:
        me = threading.get_ident()
        if self._owner != me:
            raise RaceCheckError(f"lock {self.name!r} released by thread {me} which does not own it")
        self._count -= 1
        if self._count == 0:
            hold = time.perf_counter() - self._acquired_at
            if hold > _HOLD_OUTLIER_SECONDS:
                with _G.lock:
                    if len(_G.hold_outliers) < _MAX_OUTLIERS:
                        _G.hold_outliers.append((self.name, hold, threading.current_thread().name))
            self._owner = None
            held = getattr(_tls, "held", None)
            if held:
                if held[-1] is self:
                    held.pop()
                elif self in held:  # out-of-order release: tolerated, still tracked
                    held.remove(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    @property
    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self) -> bool:
        return self._owner is not None


# -- the sanctioned constructors (what bare-thread-primitive points at) -------
def make_lock(name: str):
    """A mutex for the named lock class. Plain `threading.Lock` when the
    sanitizer is off; instrumented when KARPENTER_SOLVER_RACECHECK=1."""
    return InstrumentedLock(name, reentrant=False) if racecheck_enabled() else threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of `make_lock` (same-thread re-acquisition is legal
    and recorded without an order edge)."""
    return InstrumentedLock(name, reentrant=True) if racecheck_enabled() else threading.RLock()


def make_event() -> threading.Event:
    """Events are inherently thread-safe; routed through here so the
    bare-thread-primitive rule keeps one inventory of every primitive."""
    return threading.Event()


def spawn_thread(target, name: str | None = None, args: tuple = (), daemon: bool = True) -> threading.Thread:
    """Construct AND start a worker thread. The thread-escape rule requires
    `target` to be in the declared thread-shared registry, so every entry
    point into concurrent execution is a reviewed, named seam."""
    t = threading.Thread(target=target, name=name, args=args, daemon=daemon)
    t.start()
    return t


def touch(obj, field: str) -> None:
    """Assert `obj`'s declared guard for `field` is held by this thread.

    The declared touch points (stat counters and caches named in a class's
    GUARDED_FIELDS registry) call this on their mutation paths; a touch
    without the lock raises `RaceCheckError` under the sanitizer and costs
    one cached-bool check when it is off."""
    if not racecheck_enabled():
        return
    guards = getattr(type(obj), "GUARDED_FIELDS", None)
    if not guards or field not in guards:
        raise RaceCheckError(f"{type(obj).__name__}.{field} touched but not declared in GUARDED_FIELDS")
    lk = getattr(obj, guards[field], None)
    # debug stat only read by snapshot(): deliberately approximate — the
    # unsynchronized += can lose an increment under contention, which is
    # fine for a did-any-touch-run indicator, while taking _G.lock here
    # would serialize every touch point across all threads and skew the
    # very contention numbers the sanitizer reports
    _G.touch_checks += 1
    if isinstance(lk, InstrumentedLock) and not lk.held_by_me:
        msg = f"guarded field {type(obj).__name__}.{field} touched without holding {guards[field]!r}"
        with _G.lock:
            _G.violations.append(msg)
        raise RaceCheckError(msg)
